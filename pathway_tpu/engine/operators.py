"""Engine operator nodes.

Block-oriented counterparts of the reference's dataflow operators
(``src/engine/dataflow.rs`` lowering of the ``Graph`` trait,
``src/engine/graph.rs:647-1015``): rowwise map/filter/reindex are stateless block
kernels; group-by keeps per-group accumulators (``reduce.rs`` styles); combine covers
update_rows/update_cells/restrict/intersect/difference/having; join is an incremental
symmetric hash join with outer-padding accounting; flatten explodes sequence columns.
All state lives keyed by uint64 row keys, diffs are ±weights.
"""

from __future__ import annotations

import threading
import time as _time_mod
from collections import OrderedDict
from typing import Any, Callable, Iterable

import numpy as np

from pathway_tpu.engine.blocks import (
    DeltaBatch,
    column_to_list,
    concat_batches,
    concat_cols,
    consolidate,
    group_starts,
    interleave_positions,
    make_column,
    merge_consolidated,
    net_input_batch,
    scatter_cols,
)
from pathway_tpu.engine import jax_kernels
from pathway_tpu.engine.colstore import ColumnarKeyedStore, ColumnarMultimap, SortedCounts
from pathway_tpu.observability import audit as _audit
from pathway_tpu.observability import engine_phases as _phases
from pathway_tpu.observability import lineage as _lineage
from pathway_tpu.engine.graph import END_OF_STREAM, SOLO, Node
from pathway_tpu.engine.reducers_impl import ReducerImpl
from pathway_tpu.internals.keys import combine_keys, row_keys, splitmix64

# ---------------------------------------------------------------------------- inputs


class StaticInputNode(Node):
    name = "static_input"

    snapshot_attrs = ("_emitted",)

    def exchange_key(self, port):
        return SOLO  # sources/sinks live on worker 0

    def __init__(self, batch_factory: Callable[[int], DeltaBatch]):
        super().__init__(n_inputs=0)
        self.batch_factory = batch_factory
        self._emitted = False

    def poll(self, time: int) -> list[DeltaBatch]:
        if self._emitted or time == END_OF_STREAM:
            return []
        self._emitted = True
        return [self.batch_factory(time)]


class StreamInputNode(Node):
    """Receives events from connector threads via a lock-protected queue.

    The engine-side half of the reference's connector loop
    (``src/connectors/mod.rs:91`` + ``adaptors.rs:20-47`` InputSession/UpsertSession):
    events accumulate between ticks; ``poll`` drains them as one delta block per tick.
    ``upsert=True`` gives UpsertSession semantics: a new row for an existing key
    retracts the previous one; value ``None`` deletes.
    """

    name = "stream_input"

    snapshot_attrs = ("_state",)

    #: flow plane opt-in: live connector queues are credit-gated when
    #: ``PATHWAY_FLOW=on``; deterministic timed fixtures opt out (they replay
    #: pre-timed events, not a live producer)
    flow_gated = True

    #: set (as an instance attribute) by the persistence input-log wrapper:
    #: its log captures events BEFORE the gate, so gating must stand down on
    #: that node (see ``_push_gated``)
    flow_ungated = False

    #: set (as an instance attribute) by serving connectors under the shard
    #: map (``PATHWAY_SHARDMAP=on``): every fabric door pushes requests into
    #: its OWN process's copy of this node, so exchange must route each row
    #: by its key instead of funnelling everything to global worker 0 —
    #: otherwise zero-hop admission would re-introduce the worker-0 hop.
    fabric_ingest = False

    def exchange_key(self, port):
        if self.fabric_ingest:
            return lambda batch: batch.keys  # zero-hop: stay on the owner
        return SOLO  # sources/sinks live on worker 0

    #: upsert state is keyed by engine key but PLACED by the connector's
    #: partition slice, which need not follow key ownership — a migration
    #: must scan every old worker's (small) upsert dict, not only the
    #: shard-map overlap set
    migrate_aligned = False

    def migrate_mode(self) -> str | None:
        # a non-partitioned source is only ever fed through global worker 0's
        # copy, so its whole upsert dict must stay there (positional); only
        # partition-fed or door-fed copies hold per-worker state worth a
        # keyed merge
        if getattr(self, "local_source", False) or self.fabric_ingest:
            return "keyed"
        return "solo"

    def migrate_restore(self, shards: list[dict], keep) -> dict | None:
        """Upsert-session memory (key → current row) re-owned by the NEW shard
        map so a later upsert/delete of a migrated key still finds the row to
        retract. Keys are engine keys, so the keep mask applies directly;
        non-upsert sources carry an empty dict and merge trivially."""
        merged: dict[int, tuple] = {}
        for s in shards:
            st = s.get("_state") or {}
            if not st:
                continue
            ks = np.fromiter(st.keys(), dtype=np.uint64, count=len(st))
            mask = keep(ks)
            for k, keepit in zip(st.keys(), mask):
                if keepit:
                    merged[k] = st[k]
        return {"_state": merged}

    def __init__(self, columns: list[str], np_dtypes: dict | None = None, upsert: bool = False):
        super().__init__(n_inputs=0)
        self.columns = columns
        self.np_dtypes = np_dtypes or {}
        self.upsert = upsert
        self._lock = threading.Lock()
        self._pending: list[tuple[int, tuple | None, int]] = []  # (key, values, diff)
        # flow control (``pathway_tpu/flow``): the credit gate bounding this
        # queue, or None when the plane is off — push/poll pay one is-None test
        from pathway_tpu import flow as _flow

        self.service_class = _flow.INTERACTIVE
        self.flow_gate = _flow.register_input(self)
        # shed-policy pairing memory: (key, values) -> count of SHED inserts,
        # so a later retract of a shed row is absorbed instead of reaching
        # the engine as an unpaired -1 (negative multiplicity). Bounded;
        # overflow falls back to the documented append-mostly caveat.
        self._shed_pairs: dict = {}
        self._state: dict[int, tuple] = {}  # upsert sessions remember current row
        # input events drained by poll() so far — the operator-snapshot offset:
        # state at a snapshot reflects exactly this many log events
        self.polled_total = 0
        # watermark probes (observability plane, read by
        # ``observability.metrics.input_watermarks``): ingest wall clock of
        # the newest event, oldest still-undrained event (feeds the per-tick
        # ingest stamp the sink latency histograms subtract), total rows, and
        # — when the connector declares an event-time column — the event-time
        # high-water mark
        self.wm_rows = 0
        self.wm_ingest_ns: int | None = None
        self.wm_oldest_pending_ns: int | None = None
        self.wm_event_time: float | None = None
        self.event_time_index: int | None = None
        self.input_name: str | None = None

    def _observe_event_time(self, values: tuple | None) -> None:
        idx = self.event_time_index
        if idx is None or values is None:
            return
        try:
            et = float(values[idx])
        except (TypeError, ValueError, IndexError):
            return
        if self.wm_event_time is None or et > self.wm_event_time:
            self.wm_event_time = et

    # called from connector threads
    def push(self, key: int, values: tuple | None, diff: int = 1) -> None:
        gate = self.flow_gate
        if gate is not None:
            self._push_gated([(int(key), values, diff)], gate)
            return
        now = _time_mod.time_ns()
        with self._lock:
            self._pending.append((int(key), values, diff))
            self.wm_rows += 1
            self.wm_ingest_ns = now
            if self.wm_oldest_pending_ns is None:
                self.wm_oldest_pending_ns = now
            self._observe_event_time(values)

    def push_many(self, events: Iterable[tuple[int, tuple | None, int]]) -> None:
        events = list(events)
        gate = self.flow_gate
        if gate is not None:
            self._push_gated(events, gate)
            return
        self._append_events(events)

    def _append_events(self, events: list[tuple[int, tuple | None, int]]) -> None:
        """One lock + extend for a block of events, with the watermark stamps
        the per-row push path maintains."""
        if not events:
            return
        now = _time_mod.time_ns()
        with self._lock:
            self._pending.extend(events)
            self.wm_rows += len(events)
            self.wm_ingest_ns = now
            if self.wm_oldest_pending_ns is None:
                self.wm_oldest_pending_ns = now
            if self.event_time_index is not None:
                for _k, values, _d in events:
                    self._observe_event_time(values)

    # ---- flow-gated ingest (PATHWAY_FLOW=on) ----
    def _push_gated(self, events: list, gate) -> None:
        """Credit-gated ingest: inserts acquire one credit per row (blocking
        the producer or shedding overflow per ``PATHWAY_FLOW_POLICY``); a
        retract whose insert is still queued cancels it in place and RETURNS
        the insert's credit — the pair never reaches the engine."""
        if self.flow_ungated:
            # the persistence input-log wrapper set this flag: its log
            # captures every event BEFORE it reaches this gate, so a shed or
            # cancelled event would exist in the durable log but never in
            # polled_total, corrupting the epoch offset arithmetic — and
            # blocking here can deadlock seekable sources, whose sync_lock
            # is held across push while the persistence flush wants it on
            # the tick path. Persisted inputs therefore bypass credit gating
            # (the input log already bounds replay; poll-side priority
            # budgets still apply, they only defer draining).
            self._append_events(events)
            return
        n = len(events)
        i = 0
        while i < n:
            ev = events[i]
            if ev[2] < 0 or ev[1] is None:
                # retracts — and upsert DELETE tombstones (values=None) — are
                # never shed: their insert is already in downstream state and
                # dropping the removal would leave a phantom row forever. A
                # retract whose insert was itself SHED is absorbed instead
                # (the engine must not see an unpaired -1); otherwise
                # admit_retract bypasses the shed overflow check.
                if (
                    not self._try_cancel_queued(ev, gate)
                    and not self._absorb_shed_retract(ev, gate)
                    and gate.admit_retract()
                ):
                    self._append_events([ev])
                i += 1
                continue
            j = i
            while j < n and events[j][2] >= 0 and events[j][1] is not None:
                j += 1
            while i < j:
                chunk = events[i : min(j, i + gate.chunk_rows())]
                take = gate.admit(len(chunk))
                if take:
                    self._append_events(chunk[:take])
                if take < len(chunk):
                    self._note_shed(chunk[take:])
                i += len(chunk)

    #: bounded size of the shed-pair memory; past it, retracts of shed rows
    #: fall back to the documented append-mostly shed caveat
    _SHED_PAIRS_MAX = 65536

    def _note_shed(self, dropped: list) -> None:
        """Remember shed inserts by (key, values) so their retracts can be
        absorbed later. Unhashable values (array payloads) are skipped."""
        pairs = self._shed_pairs
        for k, v, d in dropped:
            if len(pairs) >= self._SHED_PAIRS_MAX:
                return
            try:
                pk = (k, v)
                pairs[pk] = pairs.get(pk, 0) + d
            except TypeError:
                continue

    def _absorb_shed_retract(self, ev: tuple, gate) -> bool:
        """A retract whose matching insert was shed cancels against the
        shed-pair memory — counted as shed so produced == admitted + shed."""
        if ev[2] != -1 or not self._shed_pairs:
            return False
        try:
            pk = (ev[0], ev[1])
            count = self._shed_pairs.get(pk, 0)
        except TypeError:
            return False
        if count <= 0:
            return False
        if count == 1:
            del self._shed_pairs[pk]
        else:
            self._shed_pairs[pk] = count - 1
        gate.note_absorbed_retract()
        return True

    #: newest queued entries scanned for a retract-cancel match. The cancel is
    #: purely an optimization (an unmatched pair flows to the engine and nets
    #: out there), so capping the scan keeps retract-heavy streams off an
    #: O(retracts × queue-bound) cliff while still catching the common
    #: insert-then-immediately-retract pattern.
    _CANCEL_SCAN_WINDOW = 256

    def _try_cancel_queued(self, ev: tuple, gate) -> bool:
        """Cancel the newest still-queued insert matching a retract's key and
        values (bounded backward scan under the node lock). Multiset sessions
        only: in an upsert session the queued ``(k, v1, +1)`` is a REPLACE of
        the settled ``v0`` and its ``-1`` a delete — cancelling the pair would
        resurrect ``v0`` instead of deleting ``k``."""
        key, values, diff = ev
        if diff != -1 or self.upsert:
            return False
        with self._lock:
            floor = max(0, len(self._pending) - self._CANCEL_SCAN_WINDOW) - 1
            for idx in range(len(self._pending) - 1, floor, -1):
                k2, v2, d2 = self._pending[idx]
                if k2 != key or d2 != 1:
                    continue
                try:
                    match = v2 == values
                except Exception:
                    match = False
                if match:
                    del self._pending[idx]
                    break
            else:
                return False
        gate.cancel(1)
        return True

    def poll(self, time: int) -> list[DeltaBatch]:
        gate = self.flow_gate
        with self._lock:
            budget = gate.budget if gate is not None else None
            if (
                budget is not None
                and time != END_OF_STREAM
                and budget < len(self._pending)
            ):
                # priority admission: drain only this tick's budget. The
                # drained rows include the queue's oldest, so THIS tick's
                # ingest stamp is exact; the tail (strictly newer rows whose
                # exact arrival times aren't retained) re-stamps to now —
                # slightly understating tail age beats reusing the drained
                # stamp forever, which would grow every sink's measured
                # latency monotonically under sustained budgeted draining
                # and wedge the AIMD controller at full throttle
                pending = self._pending[:budget]
                self._pending = self._pending[budget:]
                oldest_ns = self.wm_oldest_pending_ns
                self.wm_oldest_pending_ns = _time_mod.time_ns()
            else:
                pending, self._pending = self._pending, []
                oldest_ns, self.wm_oldest_pending_ns = self.wm_oldest_pending_ns, None
        if gate is not None and pending and time != END_OF_STREAM:
            gate.on_drain(len(pending))
        if time == END_OF_STREAM:
            return []
        if pending and oldest_ns is not None:
            from pathway_tpu.observability.metrics import run_metrics

            run_metrics().note_tick_ingest(time, oldest_ns)
        self.polled_total += len(pending)
        if not pending:
            return []
        if not self.upsert:
            # native sessions: one C-speed filter+transpose, no per-row loop
            if any(e[1] is None for e in pending):
                pending = [e for e in pending if e[1] is not None]
                if not pending:
                    return []
            keys, rows, diffs = map(list, zip(*pending))
            batch = DeltaBatch.from_rows(
                keys, rows, self.columns, time, diffs=diffs, np_dtypes=self.np_dtypes
            )
            return [net_input_batch(batch)]
        keys: list[int] = []
        diffs: list[int] = []
        rows: list[tuple] = []
        for key, values, diff in pending:
            if self.upsert:
                old = self._state.get(key)
                if old is not None:
                    keys.append(key)
                    diffs.append(-1)
                    rows.append(old)
                if values is not None and diff > 0:
                    keys.append(key)
                    diffs.append(1)
                    rows.append(values)
                    self._state[key] = values
                elif key in self._state:
                    del self._state[key]
            else:
                if values is None:
                    continue
                keys.append(key)
                diffs.append(diff)
                rows.append(values)
        if not keys:
            return []
        batch = DeltaBatch.from_rows(
            keys, rows, self.columns, time, diffs=diffs, np_dtypes=self.np_dtypes
        )
        return [net_input_batch(batch)]


# ---------------------------------------------------------------------------- rowwise


class RowwiseNode(Node):
    """select/with_columns: stateless block program.

    Stateless stages normally process where their input was produced (no
    exchange). A stage marked ``expensive`` (it runs python/numpy UDFs, e.g.
    embedders) instead exchanges by row key, spreading the per-row compute
    across workers — otherwise every UDF chained after a worker-0 source would
    serialize there (VERDICT r2 #5)."""

    name = "rowwise"

    def exchange_key(self, port):
        if self.expensive:
            return lambda batch: batch.keys
        return None  # stateless: process where produced

    def __init__(
        self,
        program: Callable[[DeltaBatch], dict[str, np.ndarray]],
        expensive: bool = False,
        exprs: dict | None = None,
    ):
        super().__init__(n_inputs=1)
        self.program = program
        self.expensive = expensive
        #: the named expression ASTs ``program`` was compiled from, when the
        #: builder has them — lets the chain-fusion pass compose consecutive
        #: rowwise stages into one block program / jitted kernel
        #: (``engine/fusion.py``); None keeps the node opaque (closure-only
        #: programs, e.g. iterate internals)
        self.exprs = exprs

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        return [batch.with_data(self.program(batch))]


class FilterNode(Node):
    name = "filter"

    def exchange_key(self, port):
        return None  # stateless: process where produced

    def __init__(
        self, predicate: Callable[[DeltaBatch], np.ndarray], expr: Any = None
    ):
        super().__init__(n_inputs=1)
        self.predicate = predicate
        #: predicate AST for the chain-fusion pass (see RowwiseNode.exprs)
        self.expr = expr

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        mask = self.predicate(batch)
        if mask.dtype != np.bool_:
            from pathway_tpu.internals.errors import ERROR

            mask = np.fromiter(
                (v is not None and v is not ERROR and bool(v) for v in mask),
                dtype=bool,
                count=len(mask),
            )
        return [batch.take(np.flatnonzero(mask))]


class ReindexNode(Node):
    """with_id_from / groupby key derivation: new keys from a key program."""

    name = "reindex"

    def exchange_key(self, port):
        return None  # stateless: process where produced

    def __init__(self, key_program: Callable[[DeltaBatch], np.ndarray]):
        super().__init__(n_inputs=1)
        self.key_program = key_program

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        new_keys = self.key_program(batch)
        lin = _lineage.current()
        if lin is not None:
            lin.record_edge(self, new_keys, batch.keys)
        return [batch.with_keys(new_keys)]


class SelectColumnsNode(Node):
    name = "select_columns"

    def exchange_key(self, port):
        return None  # stateless: process where produced

    def __init__(self, columns: list[str], rename: dict[str, str] | None = None):
        super().__init__(n_inputs=1)
        self.columns = columns
        self.rename = rename or {}

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        data = {self.rename.get(c, c): batch.data[c] for c in self.columns}
        return [batch.with_data(data)]


class ConcatNode(Node):
    """Disjoint union (``concat``); with ``salts`` reindexes each side so ids
    cannot collide (``concat_reindex``)."""

    name = "concat"

    def exchange_key(self, port):
        return None  # stateless: process where produced

    def __init__(self, n_inputs: int, columns: list[str], salts: list[int] | None = None):
        super().__init__(n_inputs=n_inputs)
        self.columns = columns
        self.salts = salts

    def process(self, inputs, time):
        out = []
        for port, batch in enumerate(inputs):
            if batch is None:
                continue
            batch = batch.select_columns(self.columns)
            if self.salts is not None:
                new_keys = splitmix64(batch.keys ^ np.uint64(self.salts[port]))
                lin = _lineage.current()
                if lin is not None:
                    lin.record_edge(self, new_keys, batch.keys)
                batch = batch.with_keys(new_keys)
            out.append(batch)
        return out


class FlattenNode(Node):
    """Explode a sequence column; output keys = hash(key, index)
    (reference: ``flatten_table``, ``src/engine/graph.rs``)."""

    name = "flatten"

    def exchange_key(self, port):
        return None  # stateless: process where produced

    def __init__(self, flatten_col: str, other_cols: list[str]):
        super().__init__(n_inputs=1)
        self.flatten_col = flatten_col
        self.other_cols = other_cols

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        keys_out: list[int] = []
        diffs_out: list[int] = []
        flat_vals: list[Any] = []
        other_idx: list[int] = []
        col = batch.data[self.flatten_col]
        for i in range(len(batch)):
            seq = col[i]
            if seq is None:
                continue
            if isinstance(seq, np.ndarray):
                items = list(seq)
            elif isinstance(seq, (tuple, list, str, bytes)):
                items = list(seq)
            else:
                from pathway_tpu.internals.json import Json

                items = list(seq.value) if isinstance(seq, Json) else list(seq)
            for j, item in enumerate(items):
                keys_out.append(int(combine_keys(
                    np.asarray([batch.keys[i]], dtype=np.uint64),
                    splitmix64(np.asarray([j], dtype=np.uint64)),
                )[0]))
                diffs_out.append(int(batch.diffs[i]))
                flat_vals.append(item)
                other_idx.append(i)
        data = {self.flatten_col: make_column(flat_vals, np.dtype(object))}
        idx = np.asarray(other_idx, dtype=np.int64)
        lin = _lineage.current()
        if lin is not None and len(idx):
            lin.record_edge(
                self, np.asarray(keys_out, dtype=np.uint64), batch.keys[idx]
            )
        for c in self.other_cols:
            data[c] = batch.data[c][idx]
        return [
            DeltaBatch(
                np.asarray(keys_out, dtype=np.uint64),
                np.asarray(diffs_out, dtype=np.int64),
                data,
                time,
            )
        ]


# ------------------------------------------------------------------- microbatch UDF


class MicrobatchUdfSpec:
    """One ``is_batched`` UDF column of a microbatched select: the compiled
    argument program plus the raw batch callable."""

    __slots__ = (
        "name", "args_program", "fn", "kw_names", "propagate_none",
        "min_bucket", "deterministic",
    )

    def __init__(
        self, name, args_program, fn, kw_names, propagate_none,
        min_bucket=8, deterministic=False,
    ):
        self.name = name
        #: batch -> (list of positional arg arrays, list of kwarg arrays)
        self.args_program = args_program
        self.fn = fn
        self.kw_names = kw_names
        self.propagate_none = propagate_none
        self.min_bucket = min_bucket
        self.deterministic = deterministic


def _launch_udf_batch(spec: MicrobatchUdfSpec, items: list) -> list:
    """Run one padded bucket through the UDF's batch fn. ``items`` are
    ``(args_tuple, kwargs_tuple)`` rows; a failing batch retries row by row so
    one bad input poisons only its own row (the inline BatchApply discipline,
    ``expression_vm._eval_batch_apply``)."""
    from pathway_tpu.internals.errors import report_error

    args = [list(col) for col in zip(*(it[0] for it in items))]
    kwargs = {
        k: [it[1][j] for it in items] for j, k in enumerate(spec.kw_names)
    }
    try:
        results = spec.fn(*args, **kwargs)
        if len(results) != len(items):
            raise ValueError(
                f"batch UDF returned {len(results)} results for {len(items)} rows"
            )
        return list(results)
    except Exception:
        out = []
        # pad rows are the SAME object as the last real item (repeat-last
        # padding) — the identity cache computes each distinct row once, so
        # the error path never re-runs the bucket's padding copies
        cache: dict[int, Any] = {}
        for it in items:
            if id(it) in cache:
                out.append(cache[id(it)])
                continue
            try:
                r = spec.fn(
                    *[[v] for v in it[0]],
                    **{k: [it[1][j]] for j, k in enumerate(spec.kw_names)},
                )
                val = r[0]
            except Exception as e:
                val = report_error(
                    f"apply {getattr(spec.fn, '__name__', spec.fn)!s}: {e!r}"
                )
            cache[id(it)] = val
            out.append(val)
        return out


class MicrobatchApplyNode(Node):
    """Cross-tick accumulate-then-launch select for ``is_batched`` device UDFs.

    The wiring the framework's founding bet demands (PAPER.md, SURVEY §7.1.5):
    instead of one jitted call per delta block — a streaming tick of 64 rows
    dispatches a 64-row encoder call at a fraction of batch-512 device
    throughput — rows are buffered **across ticks** per UDF, padded to
    power-of-two buckets (``ops/microbatch.py``, compile-cache discipline) and
    launched once per bucket. Full ``max_batch`` chunks launch as soon as they
    accumulate; the tail flushes when the oldest buffered row ages past the
    autocommit deadline, so added latency is bounded by
    ``autocommit_duration_ms``. Static runs flush at their single tick's
    frontier and behave exactly like the inline path.

    ``mode="hold"`` (the measured default): buffered rows are invisible
    downstream until their batch completes, then appear at the flush tick —
    value-identical to per-block dispatch, timestamps may shift later.
    ``mode="pending"``: rows appear immediately with ``PENDING`` in the UDF
    columns and settle via a retract/insert correction on the completing tick —
    the ``Value::Pending`` future discipline; consume through
    ``Table.await_futures()``.

    Retraction semantics: a retract of a still-buffered key cancels in-buffer
    (the launch never sees it); a retract of a settled key replays the
    remembered output row, so nondeterministic UDFs retract exactly what they
    inserted. Output rows are remembered only while some UDF is NOT declared
    deterministic (the reference caches non-deterministic UDF results for the
    same reason); all-deterministic selects keep zero per-row state and
    recompute retract rows, exactly like the inline path.
    """

    name = "microbatch_select"

    snapshot_attrs = ("waiting", "emitted")

    #: replay-cache FIFO bound — sized past any in-flight serving window
    _RECENT_MAX = 8192

    def exchange_key(self, port):
        # device UDF rows spread across workers by key shard, same as an
        # expensive RowwiseNode — each worker accumulates and launches its shard
        return lambda batch: batch.keys

    def __init__(
        self,
        out_columns: list[str],
        pass_names: list[str],
        pre_program: Callable[[DeltaBatch], dict[str, np.ndarray]],
        udf_specs: list[MicrobatchUdfSpec],
        np_dtypes: dict | None = None,
        mode: str = "hold",
        max_batch: int = 512,
        flush_ms: float | None = None,
        runtime: Any = None,
    ):
        super().__init__(n_inputs=1)
        self.out_columns = out_columns
        self.pass_names = pass_names
        self.pre_program = pre_program
        self.udf_specs = udf_specs
        self.np_dtypes = np_dtypes or {}
        self.mode = mode
        self.max_batch = max_batch
        self.flush_ms = flush_ms
        self.runtime = runtime
        # out column -> ("pass", i) | ("udf", j)
        udf_pos = {s.name: j for j, s in enumerate(udf_specs)}
        pass_pos = {n: i for i, n in enumerate(pass_names)}
        self._slots = [
            ("udf", udf_pos[n]) if n in udf_pos else ("pass", pass_pos[n])
            for n in out_columns
        ]
        # key -> [diff, enqueue_wall_time, passthrough tuple, cells]; cells[j]
        # is ("done", value) for instantly-decided rows (ERROR poisoning /
        # propagate_none) or ("args", args_tuple, kwargs_tuple) awaiting launch
        # (a later same-key insert overwrites: keyed last-write-wins, the
        # discipline every keyed store in this engine follows)
        self.waiting: dict[int, list] = {}
        # key -> [count, row tuple] of settled rows live downstream. Retained
        # ONLY while some UDF is not declared deterministic — retracts must
        # then replay exactly what was inserted (the reference caches
        # non-deterministic UDF results for the same reason). All-deterministic
        # selects keep no state and recompute retract rows like the inline path.
        self._remember = any(not s.deterministic for s in udf_specs)
        self.emitted: dict[int, list] = {}
        # bounded replay cache for all-DETERMINISTIC selects: key ->
        # (input signature, output row) of recent emissions. A retract of a
        # recently-emitted row replays the cached output instead of re-running
        # the device UDF — value-identical by the determinism contract, and
        # load-bearing for the serving plane, where every served query row is
        # retracted one tick after its response (delete_completed_queries):
        # without it each retract re-embeds its row in a tiny padded launch.
        # Pure cache: a miss falls back to recompute, so the FIFO bound and
        # its absence from snapshots cost correctness nothing.
        self._recent: "OrderedDict[int, tuple]" = OrderedDict()

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        # snapshot-restored enqueue stamps came from another process's
        # perf_counter epoch — reset so the deadline clock starts now
        import time as _t

        now = _t.perf_counter()
        for entry in self.waiting.values():
            entry[1] = now

    # ------------------------------------------------------------- helpers

    def _assemble(self, pass_vals: tuple, udf_vals: list) -> tuple:
        return tuple(
            pass_vals[i] if kind == "pass" else udf_vals[i]
            for kind, i in self._slots
        )

    def _pending_row(self, entry: list) -> tuple:
        from pathway_tpu.internals.errors import PENDING

        cells = entry[3]
        return self._assemble(
            entry[2],
            [c[1] if c[0] == "done" else PENDING for c in cells],
        )

    def _entry_rows(self, sub: DeltaBatch):
        """(keys, diffs, pass tuples, cells) for an insert sub-batch."""
        from pathway_tpu.internals.errors import ERROR

        pre = self.pre_program(sub)
        pass_lists = [column_to_list(np.asarray(pre[n])) for n in self.pass_names]
        per_spec = [spec.args_program(sub) for spec in self.udf_specs]
        n = len(sub)
        rows_cells: list[list] = []
        for r in range(n):
            cells = []
            for (arg_arrays, kw_arrays), spec in zip(per_spec, self.udf_specs):
                vals = tuple(a[r] for a in arg_arrays)
                kwvals = tuple(a[r] for a in kw_arrays)
                if any(v is ERROR for v in vals) or any(v is ERROR for v in kwvals):
                    cells.append(("done", ERROR))
                elif spec.propagate_none and (
                    any(v is None for v in vals) or any(v is None for v in kwvals)
                ):
                    cells.append(("done", None))
                else:
                    cells.append(("args", vals, kwvals))
            rows_cells.append(cells)
        pass_tuples = [tuple(pl[r] for pl in pass_lists) for r in range(n)]
        return sub.keys.tolist(), sub.diffs.tolist(), pass_tuples, rows_cells

    def _launch(self, all_cells: list[list]) -> list[list]:
        """Run every awaiting cell through the padded dispatcher; returns one
        value list per row, aligned with ``self.udf_specs``."""
        from pathway_tpu.ops.microbatch import MicrobatchDispatcher

        n = len(all_cells)
        max_batch = self._effective_max_batch()
        out = [[None] * len(self.udf_specs) for _ in range(n)]
        for j, spec in enumerate(self.udf_specs):
            need = [(i, all_cells[i][j]) for i in range(n) if all_cells[i][j][0] == "args"]
            if need:
                d = MicrobatchDispatcher(
                    lambda items, s=spec: _launch_udf_batch(s, items),
                    max_batch=max_batch,
                    min_bucket=spec.min_bucket,
                    label=spec.name,
                )
                results = d.map([(cell[1], cell[2]) for _, cell in need])
                for (i, _), rv in zip(need, results):
                    out[i][j] = rv
            for i in range(n):
                cell = all_cells[i][j]
                if cell[0] == "done":
                    out[i][j] = cell[1]
        return out

    def _rows_for(self, sub: DeltaBatch) -> list[tuple]:
        """Synchronous fallback: compute output rows for a sub-batch right now
        (retractions of keys this node has no memory of — restored snapshots
        excepted, only possible for rows that predate the node)."""
        _keys, _diffs, pass_tuples, cells = self._entry_rows(sub)
        udf_vals = self._launch(cells)
        return [self._assemble(p, v) for p, v in zip(pass_tuples, udf_vals)]

    # ------------------------------------------------------------- operator

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None or not len(batch):
            return []
        batch = consolidate(batch)
        if not len(batch):
            return []
        out: list[DeltaBatch] = []
        dels = np.flatnonzero(batch.diffs < 0)
        if len(dels):
            out.extend(self._retract(batch, dels, time))
        ins = np.flatnonzero(batch.diffs > 0)
        if len(ins):
            out.extend(self._enqueue(batch, ins, time))
        if len(self.waiting) >= self._effective_max_batch():
            out.extend(self._flush(time, only_full=True))
        return out

    def _effective_max_batch(self) -> int:
        """Launch bucket for this flush: the static ``max_batch`` cap, tuned
        down live by the flow plane's AIMD controller when sinks approach
        their latency SLO (``pathway_tpu/flow/controller.py``). Smaller
        buckets change launch SHAPES only — values stay byte-identical."""
        from pathway_tpu import flow as _flow

        plane = _flow.current()
        if plane is None:
            return self.max_batch
        return max(1, min(self.max_batch, plane.target_batch()))

    def _entry_sig(self, pass_vals: tuple, cells: list) -> tuple:
        """Flat input signature of an entry — pass-through values + every UDF
        arg — for matching a retract against a buffered insert by VALUE."""
        flat = list(pass_vals)
        for c in cells:
            if c[0] == "done":
                flat.append(c[1])
            else:
                flat.extend(c[1])
                flat.extend(c[2])
        return tuple(flat)

    @staticmethod
    def _sig_matches(a: tuple, b: tuple) -> bool:
        """NaN-tolerant value equality: a retract row must match the buffered
        copy of ITSELF even when an input value is NaN (NaN != NaN would
        otherwise turn the cancel into a phantom retract + re-insert)."""
        if len(a) != len(b):
            return False
        for x, y in zip(a, b):
            if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                try:
                    if not np.array_equal(x, y, equal_nan=True):
                        return False
                except TypeError:  # non-float dtypes reject equal_nan
                    if not np.array_equal(x, y):
                        return False
            elif x != y:
                if isinstance(x, float) and isinstance(y, float) \
                        and np.isnan(x) and np.isnan(y):
                    continue
                return False
        return True

    def _retract(self, batch, idx, time):
        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []
        unknown: list[tuple[int, int]] = []  # (row index, residual diff)
        # input signatures of every retract row whose key is buffered (or in
        # the recent-emission replay cache) — one vectorized _entry_rows pass,
        # not a 1-row program per retract
        cand = [
            int(i)
            for i in idx
            if int(batch.keys[i]) in self.waiting
            or int(batch.keys[i]) in self._recent
        ]
        sigs: dict[int, tuple] = {}
        if cand:
            _k, _d, pts, cls = self._entry_rows(
                batch.take(np.asarray(cand, dtype=np.int64))
            )
            sigs = {i: self._entry_sig(p, c) for i, p, c in zip(cand, pts, cls)}
        for i in idx:
            i = int(i)
            k = int(batch.keys[i])
            d = int(batch.diffs[i])
            w = self.waiting.get(k)
            if w is not None:
                # only a retract whose input VALUES match the buffered entry
                # cancels in-buffer — a cross-tick upsert may retract the old
                # settled version of the key after buffering the new one, and
                # that retract must instead replay/recompute the settled row
                if not self._sig_matches(sigs[i], self._entry_sig(w[2], w[3])):
                    w = None
            if w is not None:
                # cancel at most the buffered count; any excess (consolidate
                # may merge retracts of the buffered AND settled copies into
                # one diff) falls through to the settled row below
                cancel = max(d, -w[0])
                if cancel:
                    if self.mode == "pending":
                        out_keys.append(k)
                        out_diffs.append(cancel)
                        out_rows.append(self._pending_row(w))
                    w[0] += cancel
                    if w[0] <= 0:
                        del self.waiting[k]
                    d -= cancel
                if d == 0:
                    continue
            e = self.emitted.get(k)
            if e is not None:
                out_keys.append(k)
                out_diffs.append(d)
                out_rows.append(e[1])
                e[0] += d
                if e[0] <= 0:
                    del self.emitted[k]
                continue
            rec = self._recent.get(k)
            if rec is not None and self._sig_matches(sigs[i], rec[0]):
                # deterministic replay: the cached emission IS what a
                # recompute would produce for these inputs — skip the launch
                out_keys.append(k)
                out_diffs.append(d)
                out_rows.append(rec[1])
                continue
            unknown.append((i, d))
        if unknown:
            sub = batch.take(np.asarray([i for i, _ in unknown], dtype=np.int64))
            for (i, dd), row in zip(unknown, self._rows_for(sub)):
                out_keys.append(int(batch.keys[i]))
                out_diffs.append(dd)
                out_rows.append(row)
        if not out_keys:
            return []
        return [
            DeltaBatch.from_rows(
                out_keys, out_rows, self.out_columns, time,
                diffs=out_diffs, np_dtypes=self.np_dtypes,
            )
        ]

    def _enqueue(self, batch, idx, time):
        import time as _t

        sub = batch.take(idx)
        keys, diffs, pass_tuples, cells = self._entry_rows(sub)
        now = _t.perf_counter()
        entries = []
        for r in range(len(keys)):
            k = int(keys[r])
            entry = [int(diffs[r]), now, pass_tuples[r], cells[r]]
            prev = self.waiting.get(k)
            if prev is not None:
                entry[0] += prev[0]
                entry[1] = prev[1]  # keep the oldest age for the deadline
            self.waiting[k] = entry
            entries.append(entry)
        if self.mode != "pending":
            return []
        rows = [self._pending_row(e) for e in entries]
        return [
            DeltaBatch.from_rows(
                [int(k) for k in keys], rows, self.out_columns, time,
                diffs=[int(d) for d in diffs], np_dtypes=self.np_dtypes,
            )
        ]

    def _flush(self, time, only_full: bool = False):
        n = len(self.waiting)
        max_batch = self._effective_max_batch()
        consume = (n // max_batch) * max_batch if only_full else n
        if consume == 0:
            return []
        keys = list(self.waiting.keys())[:consume]
        entries = [self.waiting.pop(k) for k in keys]
        from pathway_tpu import observability as _obs

        tracer = _obs.current()
        if tracer is not None and tracer.tick_span_id is not None:
            import time as _t

            w0 = _t.time_ns()
            udf_vals = self._launch([e[3] for e in entries])
            tracer.span(
                "microbatch/launch",
                w0,
                _t.time_ns(),
                **{
                    "pathway.operator.id": self.node_index,
                    "pathway.rows": consume,
                    "pathway.only_full": only_full,
                    "pathway.udfs": ",".join(s.name for s in self.udf_specs),
                },
            )
        else:
            udf_vals = self._launch([e[3] for e in entries])
        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []
        for k, entry, vals in zip(keys, entries, udf_vals):
            diff = entry[0]
            row = self._assemble(entry[2], vals)
            if self.mode == "pending":
                out_keys.append(k)
                out_diffs.append(-diff)
                out_rows.append(self._pending_row(entry))
            out_keys.append(k)
            out_diffs.append(diff)
            out_rows.append(row)
            if self._remember:
                e = self.emitted.get(k)
                if e is None:
                    self.emitted[k] = [diff, row]
                else:
                    e[0] += diff
                    e[1] = row
            else:
                rec = self._recent
                rec[k] = (self._entry_sig(entry[2], entry[3]), row)
                if len(rec) > self._RECENT_MAX:
                    rec.popitem(last=False)
        return [
            DeltaBatch.from_rows(
                out_keys, out_rows, self.out_columns, time,
                diffs=out_diffs, np_dtypes=self.np_dtypes,
            )
        ]

    def _should_flush(self, time) -> bool:
        if time == END_OF_STREAM:
            return True
        rt = self.runtime
        if rt is None or not getattr(rt, "streaming", False):
            # static run: exactly one tick — flush at its frontier (emissions
            # re-enter the same logical time, matching the inline path)
            return True
        conns = getattr(rt, "connectors", None)
        if conns and all(d.is_finished() for d in conns):
            # drain tick: sources exhausted, nothing more will accumulate
            return True
        first = next(iter(self.waiting.values()))
        deadline = self.flush_ms
        if deadline is None:
            deadline = getattr(rt, "autocommit_duration_ms", 20) or 20
        import time as _t

        return (_t.perf_counter() - first[1]) * 1000.0 >= deadline

    def on_frontier(self, time):
        if not self.waiting or not self._should_flush(time):
            return []
        return self._flush(time)


# ---------------------------------------------------------------------------- groupby


class GroupByNode(Node):
    """Incremental grouped aggregation.

    State per group: reducer accumulators + the last emitted output row; an update
    retracts the previous aggregate row and emits the new one at the same timestamp —
    exactly the visible behavior of the reference's ``group_by_table`` +
    ``reduce.rs`` reducers, but driven by whole blocks with vectorized per-batch
    partial aggregation for semigroup reducers.
    """

    name = "groupby"

    snapshot_attrs = ("state", "cstate", "use_dict", "_seq", "_archived")

    def exchange_key(self, port):
        return self._gkeys  # co-locate rows of one group

    def __init__(
        self,
        group_cols: list[str],
        reducer_specs: list[tuple[str, ReducerImpl, list[str]]],
        key_col: str | None = None,
        out_group_cols: list[str] | None = None,
    ):
        super().__init__(n_inputs=1)
        self.group_cols = group_cols
        self.key_col = key_col
        self.reducer_specs = reducer_specs
        self.out_group_cols = out_group_cols if out_group_cols is not None else group_cols
        # gkey -> {"g": group values tuple, "acc": [state...], "emitted": tuple|None}
        self.state: dict[int, dict] = {}
        self._seq = 0
        self.out_columns = list(self.out_group_cols) + [s[0] for s in self.reducer_specs]
        # first-load fast path: per-group partials parked as arrays; folded into
        # the dict state only if incremental deltas arrive later
        self._archived: list[dict] = []
        # fully-columnar state (sorted gk → n/accumulator/group-value arrays):
        # active while every reducer is additive-columnar and every batch's
        # aggregated columns are numeric; falls back to the dict path otherwise
        self.use_dict = not all(spec[1].columnar for spec in reducer_specs)
        self.cstate: dict | None = None

    GLOBAL_KEY = 0x6A09E667F3BCC908  # single group for global reduce()

    NONE_KEY = 0xBB67AE8584CAA73B  # groups rows whose id-expression is (transiently) None

    def _gkeys(self, batch: DeltaBatch) -> np.ndarray:
        if self.key_col is not None:
            col = batch.data[self.key_col]
            if col.dtype == object:
                # tolerate None ids: mid-tick outer-join padding may flow through
                # before the matching side arrives; corrections retract it later
                gkeys = np.fromiter(
                    (self.NONE_KEY if v is None else int(v) for v in col),
                    dtype=np.uint64,
                    count=len(col),
                )
            else:
                gkeys = col.astype(np.uint64)
        elif not self.group_cols:
            gkeys = np.full(len(batch), self.GLOBAL_KEY, dtype=np.uint64)
        else:
            gkeys = row_keys([batch.data[c] for c in self.group_cols], n=len(batch))
        lin = _lineage.current()
        if lin is not None and len(gkeys):
            # lineage: a group key derives from the input row keys it absorbs
            lin.record_edge(self, gkeys, batch.keys)
        return gkeys

    def _vector_first_load(self, batch: DeltaBatch, time: int) -> list[DeltaBatch] | None:
        """All-new groups, semigroup-only reducers: aggregate with reduceat and
        emit columns directly from arrays; park partials for lazy state build."""
        gkeys = self._gkeys(batch)
        order = np.argsort(gkeys, kind="stable")
        gk_sorted = gkeys[order]
        starts = group_starts(gk_sorted)
        diffs = batch.diffs
        counts = np.add.reduceat(diffs[order], starts)
        partials: list[Any] = []
        for (_, impl, cols) in self.reducer_specs:
            arrays = [batch.data[c] for c in cols]
            p = impl.grouped_partials(arrays, diffs, order, starts)
            if p is None:
                return None  # column needs the per-group path
            partials.append(p)
        first_rows = order[starts]
        gk_arr = gk_sorted[starts]
        group_arrays = [batch.data[c][first_rows] for c in self.group_cols]

        extracted: list[list] = []
        for r, (_, impl, _) in enumerate(self.reducer_specs):
            extracted.append([impl.extract(p) for p in partials[r]])

        self._archived.append(
            {
                "gk": gk_arr.tolist(),
                "gvals": [column_to_list(a) for a in group_arrays],
                "counts": counts.tolist(),
                "partials": partials,
                "extracted": extracted,
            }
        )

        emit_mask = (counts > 0) & (gk_arr != np.uint64(self.NONE_KEY))
        idx = np.flatnonzero(emit_mask)
        if not len(idx):
            return []
        data: dict[str, np.ndarray] = {}
        for name, arr in zip(self.out_group_cols, group_arrays):
            data[name] = arr[idx]
        for r, (name, _, _) in enumerate(self.reducer_specs):
            vals = [extracted[r][i] for i in idx]
            probe = np.asarray(vals[:1]) if vals else None
            npd = probe.dtype if probe is not None and probe.ndim == 1 and probe.dtype.kind in "iufb" else np.dtype(object)
            data[name] = make_column(vals, npd)
        return [
            DeltaBatch(gk_arr[idx], np.ones(len(idx), dtype=np.int64), data, time)
        ]

    def _materialize_archived(self) -> None:
        for arch in self._archived:
            gks = arch["gk"]
            gvals = arch["gvals"]
            counts = arch["counts"]
            partials = arch["partials"]
            extracted = arch["extracted"]
            for i in range(len(gks)):
                gk = gks[i]
                g_tuple = tuple(col[i] for col in gvals)
                st = self.state.get(gk)
                if st is None:
                    st = {
                        "g": g_tuple,
                        "acc": [spec[1].make() for spec in self.reducer_specs],
                        "n": 0,
                        "emitted": None,
                    }
                    self.state[gk] = st
                st["n"] += counts[i]
                for r, spec in enumerate(self.reducer_specs):
                    st["acc"][r] = spec[1].merge_partial(st["acc"][r], partials[r][i])
                if st["n"] > 0 and gk != self.NONE_KEY:
                    st["emitted"] = g_tuple[: len(self.out_group_cols)] + tuple(
                        extracted[r][i] for r in range(len(self.reducer_specs))
                    )
                elif st["n"] <= 0:
                    del self.state[gk]
        self._archived = []

    def _process_columnar(self, batch: DeltaBatch, time: int) -> list[DeltaBatch] | None:
        """Whole-state vectorized aggregation: state is sorted arrays, a delta
        block merges in with searchsorted + reduceat; no per-group Python.
        Returns None when this batch's columns can't vectorize (→ dict path)."""
        gkeys = self._gkeys(batch)
        diffs = batch.diffs
        jaxed = jax_kernels.try_grouped(gkeys, diffs, self.reducer_specs, batch.data)
        if jaxed is not None:
            order, starts, u_gk, counts, partials = jaxed
        else:
            order = np.argsort(gkeys, kind="stable")
            gk_sorted = gkeys[order]
            starts = group_starts(gk_sorted)
            partials = []
            for (_, impl, cols) in self.reducer_specs:
                arrays = [batch.data[c] for c in cols]
                p = impl.grouped_partials_np(arrays, diffs, order, starts)
                if p is None:
                    return None
                partials.append(p)
            u_gk = gk_sorted[starts]
            counts = np.add.reduceat(diffs[order], starts)
        first_rows = order[starts]
        batch_gcols = [batch.data[c][first_rows] for c in self.group_cols]

        st = self.cstate
        if st is None:
            st = self.cstate = {
                "gk": np.empty(0, dtype=np.uint64),
                "n": np.empty(0, dtype=np.int64),
                "accs": [np.empty(0, dtype=p.dtype) for p in partials],
                "gcols": [a[:0] for a in batch_gcols],
            }
        sgk = st["gk"]
        if len(sgk):
            pos = np.searchsorted(sgk, u_gk).clip(0, len(sgk) - 1)
            exists = sgk[pos] == u_gk
        else:
            pos = np.zeros(len(u_gk), dtype=np.int64)
            exists = np.zeros(len(u_gk), dtype=bool)
        old_n = np.where(exists, st["n"][pos] if len(sgk) else 0, 0)
        new_n = old_n + counts
        old_accs: list[np.ndarray] = []
        new_accs: list[np.ndarray] = []
        for acc_arr, p in zip(st["accs"], partials):
            dt = np.result_type(acc_arr.dtype, p.dtype)
            old = np.zeros(len(u_gk), dtype=dt)
            if len(acc_arr):
                ex = np.flatnonzero(exists)
                old[ex] = acc_arr[pos[ex]]
            old_accs.append(old)
            new_accs.append(old + p)

        # emission: retract the previously-emitted aggregate of every changed
        # group, emit the new one (None-id group excluded, see on_end)
        not_none = u_gk != np.uint64(self.NONE_KEY)
        was = exists & (old_n > 0) & not_none
        now = (new_n > 0) & not_none
        changed = np.zeros(len(u_gk), dtype=bool)
        for old, new in zip(old_accs, new_accs):
            changed |= old != new
        emit_retract = was & (~now | changed)
        emit_insert = now & (~was | changed)

        # group-col values: the state's first-seen copy for existing groups,
        # the batch's for new groups
        g_out: list[np.ndarray] = []
        for sc, bc in zip(st["gcols"], batch_gcols):
            if not len(sc):
                g_out.append(bc)
                continue
            ex = np.flatnonzero(exists)
            if sc.dtype == bc.dtype:
                merged = bc.copy()
                merged[ex] = sc[pos[ex]]
            else:
                merged = np.empty(len(u_gk), dtype=object)
                merged[:] = list(bc) if bc.dtype.kind in ("M", "m") else bc
                picked = sc[pos[ex]]
                merged[ex] = list(picked) if sc.dtype.kind in ("M", "m") else picked
            g_out.append(merged)

        # update state: in-place for surviving groups, rebuild for add/remove
        remove = exists & (new_n <= 0)
        add = ~exists & (new_n > 0)
        upd = exists & (new_n > 0)
        if upd.any():
            ui = pos[upd]
            st["n"][ui] = new_n[upd]
            for r in range(len(st["accs"])):
                vals = new_accs[r][upd]
                if st["accs"][r].dtype != vals.dtype:
                    st["accs"][r] = st["accs"][r].astype(
                        np.result_type(st["accs"][r].dtype, vals.dtype)
                    )
                st["accs"][r][ui] = vals
        if remove.any() or add.any():
            keep = np.ones(len(sgk), dtype=bool)
            keep[pos[remove]] = False
            kept_gk = sgk[keep]
            add_gk = u_gk[add]
            # persistent arrangement discipline: both runs are sorted and
            # DISJOINT (added groups were absent from state), so the merged
            # arrangement is a two-way interleave by searchsorted positions —
            # no argsort of the whole state per tick (the re-arrangement tax
            # BASELINE §incremental attributes)
            ia, ib = interleave_positions(kept_gk, add_gk)
            total = len(kept_gk) + len(add_gk)
            positions = [ia, ib]
            gk_m = np.empty(total, dtype=np.uint64)
            gk_m[ia] = kept_gk
            gk_m[ib] = add_gk
            st["gk"] = gk_m
            n_m = np.empty(total, dtype=np.int64)
            n_m[ia] = st["n"][keep]
            n_m[ib] = new_n[add]
            st["n"] = n_m
            for r in range(len(st["accs"])):
                a, b = st["accs"][r][keep], new_accs[r][add]
                dt = np.result_type(a.dtype, b.dtype)
                acc_m = np.empty(total, dtype=dt)
                acc_m[ia] = a
                acc_m[ib] = b
                st["accs"][r] = acc_m
            st["gcols"] = [
                scatter_cols([sc[keep], bc[add]], positions, total)
                for sc, bc in zip(st["gcols"], batch_gcols)
            ]

        r_idx = np.flatnonzero(emit_retract)
        i_idx = np.flatnonzero(emit_insert)
        if not len(r_idx) and not len(i_idx):
            return []
        keys_out = np.concatenate([u_gk[r_idx], u_gk[i_idx]])
        diffs_out = np.concatenate(
            [np.full(len(r_idx), -1, dtype=np.int64), np.ones(len(i_idx), dtype=np.int64)]
        )
        data: dict[str, np.ndarray] = {}
        for name, col in zip(self.out_group_cols, g_out):
            data[name] = concat_cols([col[r_idx], col[i_idx]])
        for r, (name, _, _) in enumerate(self.reducer_specs):
            data[name] = np.concatenate([old_accs[r][r_idx], new_accs[r][i_idx]])
        return [DeltaBatch(keys_out, diffs_out, data, time)]

    def _cstate_entries(self, st: dict, out: dict) -> None:
        """Expand one columnar state block into per-group dict entries."""
        gk_list = st["gk"].tolist()
        n_list = st["n"].tolist()
        gcol_lists = [column_to_list(c) for c in st["gcols"]]
        acc_lists = [a.tolist() for a in st["accs"]]
        for i, gk in enumerate(gk_list):
            g_tuple = tuple(col[i] for col in gcol_lists)
            accs = [acc_lists[r][i] for r in range(len(acc_lists))]
            emitted = None
            if n_list[i] > 0 and gk != self.NONE_KEY:
                emitted = g_tuple[: len(self.out_group_cols)] + tuple(accs)
            out[gk] = {
                "g": g_tuple, "acc": accs, "n": n_list[i], "emitted": emitted,
            }

    def _decolumnarize(self) -> None:
        """A batch arrived that the columnar path can't aggregate (object
        column): convert the array state to dict state and stay there."""
        self.use_dict = True
        st = self.cstate
        self.cstate = None
        if st is None:
            return
        self._cstate_entries(st, self.state)

    def migrate_restore(self, shards: list[dict], keep) -> dict | None:
        """O(moved-state) rescale merge: group keys route by ``_gkeys`` so
        every group lives on its shard-map owner — old shards are key-disjoint
        and a plain filtered union rebuilds this worker's state. Columnar
        blocks merge as sorted disjoint runs; if ANY old shard had fallen back
        to the dict path the merged state must too (the dict path ignores
        ``cstate``), so columnar blocks decolumnarize during the merge."""
        state: dict[int, dict] = {}
        archived: list[dict] = []
        cparts: list[dict] = []
        seq = 0
        any_dict = any(s.get("use_dict") for s in shards)
        for s in shards:
            seq = max(seq, int(s.get("_seq", 0)))
            for gk, gst in (s.get("state") or {}).items():
                if bool(keep(np.asarray([gk], dtype=np.uint64))[0]):
                    state[gk] = gst
            for arch in s.get("_archived") or []:
                gk_arr = np.asarray(arch["gk"], dtype=np.uint64)
                mask = keep(gk_arr)
                if not mask.any():
                    continue
                idx = np.flatnonzero(mask)
                archived.append(
                    {
                        "gk": [arch["gk"][i] for i in idx],
                        "gvals": [[col[i] for i in idx] for col in arch["gvals"]],
                        "counts": [arch["counts"][i] for i in idx],
                        "partials": [
                            p[idx] if isinstance(p, np.ndarray) else [p[i] for i in idx]
                            for p in arch["partials"]
                        ],
                        "extracted": [[ex[i] for i in idx] for ex in arch["extracted"]],
                    }
                )
            cst = s.get("cstate")
            if cst is not None and len(cst["gk"]):
                mask = keep(cst["gk"])
                if not mask.any():
                    continue
                part = {
                    "gk": cst["gk"][mask],
                    "n": cst["n"][mask],
                    "accs": [a[mask] for a in cst["accs"]],
                    "gcols": [c[mask] for c in cst["gcols"]],
                }
                if any_dict:
                    self._cstate_entries(part, state)
                else:
                    cparts.append(part)
        cstate = None
        if cparts:
            if len(cparts) == 1:
                cstate = cparts[0]
            else:
                gk = np.concatenate([p["gk"] for p in cparts])
                order = np.argsort(gk, kind="stable")
                cstate = {
                    "gk": gk[order],
                    "n": np.concatenate([p["n"] for p in cparts])[order],
                    "accs": [
                        np.concatenate([p["accs"][r] for p in cparts])[order]
                        for r in range(len(cparts[0]["accs"]))
                    ],
                    "gcols": [
                        concat_cols([p["gcols"][c] for p in cparts])[order]
                        for c in range(len(cparts[0]["gcols"]))
                    ],
                }
        if not state and not archived and cstate is None:
            return None
        return {
            "state": state,
            "cstate": cstate,
            "use_dict": any_dict,
            "_seq": seq,
            "_archived": archived,
        }

    def process(self, inputs, time):
        tok = _phases.start()
        try:
            return self._process_impl(inputs, time)
        finally:
            _phases.stop(tok, "groupby")

    def _process_impl(self, inputs, time):
        batch = inputs[0]
        if batch is None or not len(batch):
            return []
        if not self.use_dict:
            res = self._process_columnar(batch, time)
            if res is not None:
                return res
            self._decolumnarize()
        if not self.state and len(batch) and bool((batch.diffs > 0).all()):
            if all(spec[1].semigroup for spec in self.reducer_specs) and not self._archived:
                fast = self._vector_first_load(batch, time)
                if fast is not None:
                    return fast
        if self._archived:
            self._materialize_archived()
        gkeys = self._gkeys(batch)
        order = np.argsort(gkeys, kind="stable")
        gk_sorted = gkeys[order]
        starts = group_starts(gk_sorted)
        ends = np.append(starts[1:], len(gk_sorted))

        group_arrays = [batch.data[c] for c in self.group_cols]
        diffs = batch.diffs
        spec_arrays = [
            [batch.data[c] for c in cols] for (_, _, cols) in self.reducer_specs
        ]

        # one vectorized pass for group counts and semigroup partials; only
        # multiset/stateful reducers fall back to per-row updates inside the loop
        n_groups = len(starts)
        group_counts = (
            np.add.reduceat(diffs[order], starts).tolist() if n_groups else []
        )
        grouped: list[Any | None] = []
        for spec, arrays in zip(self.reducer_specs, spec_arrays):
            impl = spec[1]
            if impl.semigroup and n_groups:
                grouped.append(impl.grouped_partials(arrays, diffs, order, starts))
            else:
                grouped.append(None)
        first_rows = order[starts] if n_groups else order
        group_val_lists = [column_to_list(arr[first_rows]) for arr in group_arrays]
        gk_list = gk_sorted[starts].tolist() if n_groups else []

        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []

        for gi in range(n_groups):
            s = starts[gi]
            e = ends[gi]
            gk = gk_list[gi]
            st = self.state.get(gk)
            if st is None:
                st = {
                    "g": tuple(col[gi] for col in group_val_lists),
                    "acc": [spec[1].make() for spec in self.reducer_specs],
                    "n": 0,
                    "emitted": None,
                }
                self.state[gk] = st
            # update accumulators
            st["n"] += int(group_counts[gi])
            for r, (spec, arrays) in enumerate(zip(self.reducer_specs, spec_arrays)):
                impl = spec[1]
                if grouped[r] is not None:
                    st["acc"][r] = impl.merge_partial(st["acc"][r], grouped[r][gi])
                elif impl.semigroup:
                    idx = order[s:e]
                    cols_slice = [arr[idx] for arr in arrays]
                    partial = impl.batch_partial(cols_slice, diffs[idx], slice(None))
                    st["acc"][r] = impl.merge_partial(st["acc"][r], partial)
                else:
                    for i in order[s:e]:
                        st["acc"][r] = (
                            impl.update(
                                st["acc"][r],
                                tuple(arr[i] for arr in arrays),
                                int(diffs[i]),
                                time,
                                self._seq,
                            )
                            or st["acc"][r]
                        )
                        self._seq += 1
            # emit — except the None-id group: mid-tick join padding may put rows
            # there transiently; if they persist, they are dropped from output
            # (reference: error-keyed rows go to the error log, not results)
            if gk == self.NONE_KEY:
                continue
            old = st["emitted"]
            if st["n"] <= 0:
                new = None
                del self.state[gk]
            else:
                g_vals = st["g"][: len(self.out_group_cols)]
                new = g_vals + tuple(
                    spec[1].extract(st["acc"][r])
                    for r, spec in enumerate(self.reducer_specs)
                )
                st["emitted"] = new
            if old == new and not _tuple_differs(old, new):
                continue
            if old is not None:
                out_keys.append(gk)
                out_diffs.append(-1)
                out_rows.append(old)
            if new is not None:
                out_keys.append(gk)
                out_diffs.append(1)
                out_rows.append(new)

        if not out_keys:
            return []
        return [
            DeltaBatch.from_rows(out_keys, out_rows, self.out_columns, time, diffs=out_diffs)
        ]

    def on_end(self):
        # join padding parks rows under NONE_KEY transiently and corrections
        # normally clear it; rows still there when the stream closes had a
        # genuinely-None id-expression and were excluded from output — say so
        # instead of losing them silently (reference routes error-keyed rows to
        # the error log)
        n_none = 0
        st = self.state.get(self.NONE_KEY)
        if st is not None:
            n_none = st["n"]
        elif self.cstate is not None and len(self.cstate["gk"]):
            pos = int(np.searchsorted(self.cstate["gk"], np.uint64(self.NONE_KEY)))
            if pos < len(self.cstate["gk"]) and self.cstate["gk"][pos] == np.uint64(self.NONE_KEY):
                n_none = int(self.cstate["n"][pos])
        if n_none > 0:
            import warnings

            warnings.warn(
                f"groupby: {n_none} row(s) with a None grouping id were "
                "excluded from the output",
                stacklevel=2,
            )


def _tuple_differs(a, b) -> bool:
    if (a is None) != (b is None):
        return True
    if a is None:
        return False
    if len(a) != len(b):
        return True
    for x, y in zip(a, b):
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            if not np.array_equal(x, y):
                return True
        elif x != y:
            return True
    return False


# ---------------------------------------------------------------------------- combine


class SideSpec:
    __slots__ = ("required", "negated")

    def __init__(self, required: bool = True, negated: bool = False):
        self.required = required
        self.negated = negated


class CombineNode(Node):
    """Key-aligned N-way combine, fully columnar.

    One node covers the reference's same-universe operator family:
    ``update_rows``/``update_cells`` (override semantics), ``restrict``/
    ``intersect`` (required sides), ``difference`` (negated side), ``having``,
    and cross-table aligned selects over equal universes. State per side is a
    :class:`ColumnarKeyedStore`; a tick applies every side's delta block, then
    re-combines only the affected keys with mode-specific vectorized assembly.
    Change detection uses row digests (the same digest discipline
    ``consolidate`` already relies on).

    Modes: ``"side0"`` (emit side 0's row under the presence gate),
    ``"update_rows"`` (later sides override whole rows),
    ``"update_cells"`` (side 1 overrides the listed columns of side 0),
    ``"concat"`` (concatenate all sides' rows in order).
    """

    name = "combine"

    snapshot_attrs = ("stores", "emitted")

    def __init__(
        self,
        sides: list["SideSpec"],
        side_columns: list[list[str]],
        mode: str,
        out_columns: list[str],
        np_dtypes: dict | None = None,
        override_positions: list[tuple[int, int]] | None = None,
    ):
        super().__init__(n_inputs=len(sides))
        self.sides = sides
        self.side_columns = side_columns
        self.mode = mode
        self.out_columns = out_columns
        self.np_dtypes = np_dtypes or {}
        # update_cells: (index in side-1 columns, index in out columns)
        self.override_positions = override_positions or []
        # update_rows: per-side (src idx, out idx) by NAME — a side whose
        # column order differs from out_columns must not write cross-column
        out_pos = {n: i for i, n in enumerate(out_columns)}
        self._side_out_maps = [
            [(j, out_pos[n]) for j, n in enumerate(cols) if n in out_pos]
            for cols in side_columns
        ]
        self.stores = [ColumnarKeyedStore(len(cols)) for cols in side_columns]
        self.emitted = ColumnarKeyedStore(len(out_columns))

    def process(self, inputs, time):
        affected_parts: list[np.ndarray] = []
        for port, batch in enumerate(inputs):
            if batch is None or not len(batch):
                continue
            # same-tick insert+retract of one row must net out BEFORE the
            # delete-then-insert application order below
            batch = consolidate(batch)
            if not len(batch):
                continue
            store = self.stores[port]
            dels = np.flatnonzero(batch.diffs < 0)
            if len(dels):
                store.delete(batch.keys[dels])
            ins = np.flatnonzero(batch.diffs > 0)
            if len(ins):
                cols = [batch.data[c][ins] for c in self.side_columns[port]]
                store.upsert(batch.keys[ins], cols)
            affected_parts.append(batch.keys)
        if not affected_parts:
            return []
        keys = np.unique(np.concatenate(affected_parts))

        presents: list[np.ndarray] = []
        aligned: list[list[np.ndarray]] = []
        for store in self.stores:
            p, cols = store.get(keys)
            presents.append(p)
            aligned.append(cols)

        gate = np.ones(len(keys), dtype=bool)
        for spec, present in zip(self.sides, presents):
            if spec.required:
                gate &= ~present if spec.negated else present
        # a key with no contributing side left (fully retracted) emits nothing
        contributing = [
            p for spec, p in zip(self.sides, presents) if not spec.negated
        ]
        if contributing:
            gate &= np.logical_or.reduce(contributing)

        new_cols = self._assemble(keys, presents, aligned)
        was, old_cols = self.emitted.get(keys)

        changed = np.ones(len(keys), dtype=bool)
        both = was & gate
        if both.any():
            idx = np.flatnonzero(both)
            new_d = row_keys([c[idx] for c in new_cols], n=len(idx))
            old_d = row_keys([c[idx] for c in old_cols], n=len(idx))
            changed[idx] = new_d != old_d

        retract = was & (~gate | changed)
        insert = gate & (~was | changed)
        r_idx = np.flatnonzero(retract)
        i_idx = np.flatnonzero(insert)
        if not len(r_idx) and not len(i_idx):
            return []

        if len(r_idx):
            self.emitted.delete(keys[r_idx])
        if len(i_idx):
            self.emitted.upsert(keys[i_idx], [c[i_idx] for c in new_cols])

        out_keys = np.concatenate([keys[r_idx], keys[i_idx]])
        out_diffs = np.concatenate(
            [np.full(len(r_idx), -1, dtype=np.int64), np.ones(len(i_idx), dtype=np.int64)]
        )
        data: dict[str, np.ndarray] = {}
        for j, name in enumerate(self.out_columns):
            arr = concat_cols([old_cols[j][r_idx], new_cols[j][i_idx]])
            npd = self.np_dtypes.get(name)
            if npd is not None and npd != np.dtype(object) and arr.dtype == object:
                arr = make_column(arr.tolist(), npd)
            data[name] = arr
        return [DeltaBatch(out_keys, out_diffs, data, time)]

    def _assemble(
        self,
        keys: np.ndarray,
        presents: list[np.ndarray],
        aligned: list[list[np.ndarray]],
    ) -> list[np.ndarray]:
        if self.mode == "side0":
            return aligned[0]
        if self.mode == "update_rows":
            # later sides override whole rows where present (column mapping by
            # NAME: side orders may differ from out_columns)
            out = [np.empty(len(keys), dtype=object) for _ in self.out_columns]
            for src_j, dst_j in self._side_out_maps[0]:
                out[dst_j][:] = aligned[0][src_j]
            for s in range(1, len(aligned)):
                idx = np.flatnonzero(presents[s])
                for src_j, dst_j in self._side_out_maps[s]:
                    out[dst_j][idx] = aligned[s][src_j][idx]
            return out
        if self.mode == "update_cells":
            out = [c.copy() for c in aligned[0]]
            idx = np.flatnonzero(presents[1])
            for src_j, dst_j in self.override_positions:
                out[dst_j][idx] = aligned[1][src_j][idx]
            return out
        if self.mode == "concat":
            return [c for cols in aligned for c in cols]
        raise ValueError(f"combine: unknown mode {self.mode!r}")


# ---------------------------------------------------------------------------- join


class JoinNode(Node):
    """Incremental symmetric hash equi-join with outer padding.

    The block counterpart of ``join_tables`` (``src/engine/graph.rs:783`` region),
    with state held the way differential holds arrangements — columnar and sorted
    (``engine/colstore.py``) — so every delta block, first load or late-stream,
    is probed and applied with searchsorted/repeat-expansion kernels; there is no
    per-row dict path at all. For outer variants, a ``SortedCounts`` per side
    tracks live-row counts per join key; its batch 0↔+ transitions drive padded
    (null-extended) row flips. Output row keys are ``hash(left_key, right_key)``
    (padded rows: hash with a side salt), matching the reference's
    id-from-both-sides discipline.
    """

    name = "join"

    snapshot_attrs = ("store", "jk_counts")

    def exchange_key(self, port):
        col = self.left_on if port == 0 else self.right_on

        def key_fn(batch, c=col):
            arr = batch.data[c]
            if arr.dtype == object:
                # null join keys never match; shard 0 handles their padding
                return np.fromiter(
                    (0 if v is None else int(v) for v in arr),
                    dtype=np.uint64,
                    count=len(arr),
                )
            return arr.astype(np.uint64)

        return key_fn

    def migrate_restore(self, shards: list[dict], keep) -> dict | None:
        """O(moved-state) rescale merge: both arrangements and the outer-pad
        counts are addressed by the join key — the same key ``exchange_key``
        routes by — so old shards are jk-disjoint and a filtered union of
        their live rows rebuilds this worker's state. Tombstoned rows are
        dropped in transit (``iter_live``), so the migrated store starts
        compacted."""
        store = [
            ColumnarMultimap(len(self.left_cols)),
            ColumnarMultimap(len(self.right_cols)),
        ]
        jk_counts = [SortedCounts(), SortedCounts()]
        moved = 0
        for s in shards:
            for side in (0, 1):
                for jk, rk, cols in s["store"][side].iter_live():
                    if not len(jk):
                        continue
                    mask = keep(jk)
                    if mask.any():
                        store[side].insert(
                            jk[mask], rk[mask], [c[mask] for c in cols]
                        )
                        moved += int(mask.sum())
                sc = s["jk_counts"][side]
                if len(sc.keys):
                    mask = keep(sc.keys) & (sc.counts != 0)
                    if mask.any():
                        jk_counts[side].add(sc.keys[mask], sc.counts[mask])
                        moved += int(mask.sum())
        if not moved:
            return None
        return {"store": store, "jk_counts": jk_counts}

    def __init__(
        self,
        left_cols: list[str],
        right_cols: list[str],
        left_on: str,
        right_on: str,
        how: str = "inner",  # inner | left | right | outer
        out_columns: list[str] | None = None,
        left_id_only: bool = False,
    ):
        super().__init__(n_inputs=2)
        self.left_cols = left_cols
        self.right_cols = right_cols
        self.left_on = left_on
        self.right_on = right_on
        self.how = how
        self.left_id_only = left_id_only
        self.out_columns = out_columns or (
            ["__left_id__", "__right_id__"] + left_cols + right_cols
        )
        # columnar per-side state: sorted segments of (jk, rk, values)
        self.store = [ColumnarMultimap(len(left_cols)), ColumnarMultimap(len(right_cols))]
        # per-side live-row counts per jk (outer padding only)
        self.jk_counts = [SortedCounts(), SortedCounts()]

    # -------------------------------------------------------------- block kernels

    def _jk_valid(self, batch: DeltaBatch, side: int) -> tuple[np.ndarray, np.ndarray]:
        col = batch.data[self.left_on if side == 0 else self.right_on]
        if col.dtype == object:
            n = len(col)
            valid = np.fromiter((v is not None for v in col), dtype=bool, count=n)
            jk = np.zeros(n, dtype=np.uint64)
            nz = np.flatnonzero(valid)
            if len(nz):
                jk[nz] = np.fromiter((int(col[i]) for i in nz), dtype=np.uint64, count=len(nz))
            return jk, valid
        return col.astype(np.uint64), np.ones(len(col), dtype=bool)

    def _side_cols(self, side: int) -> list[str]:
        return self.left_cols if side == 0 else self.right_cols

    def _out_col_names(self) -> tuple[str, str, list[str], list[str]]:
        nl = len(self.left_cols)
        return (
            self.out_columns[0],
            self.out_columns[1],
            self.out_columns[2 : 2 + nl],
            self.out_columns[2 + nl :],
        )

    def _pad_arrays(
        self,
        side: int,
        rk: np.ndarray,
        cols: list[np.ndarray],
        diffs: np.ndarray,
        time: int,
    ) -> DeltaBatch:
        """Null-padded output rows for unmatched rows of ``side``."""
        lid, rid, l_names, r_names = self._out_col_names()
        if side == 0:
            out_keys = rk if self.left_id_only else splitmix64(rk ^ np.uint64(0xA0B0))
        else:
            out_keys = splitmix64(rk ^ np.uint64(0xB0A0))
        lin = _lineage.current()
        if lin is not None and len(rk):
            lin.record_edge(self, out_keys, rk)
        none_col = np.full(len(rk), None, dtype=object)
        data: dict[str, np.ndarray] = {}
        data[lid] = rk if side == 0 else none_col
        data[rid] = rk if side == 1 else none_col
        my_names = l_names if side == 0 else r_names
        other_names = r_names if side == 0 else l_names
        for name, arr in zip(my_names, cols):
            data[name] = arr
        for name in other_names:
            data[name] = none_col
        return DeltaBatch(out_keys, diffs.astype(np.int64), data, time)

    def _matched_arrays(
        self,
        side: int,
        my_rk: np.ndarray,
        my_cols: list[np.ndarray],
        o_rk: np.ndarray,
        o_cols: list[np.ndarray],
        diffs: np.ndarray,
        time: int,
    ) -> DeltaBatch:
        """Matched output rows: ``side``'s delta rows × the other side's state."""
        lid, rid, l_names, r_names = self._out_col_names()
        if side == 0:
            lk, rk, l_cols, r_cols = my_rk, o_rk, my_cols, o_cols
        else:
            lk, rk, l_cols, r_cols = o_rk, my_rk, o_cols, my_cols
        out_keys = lk if self.left_id_only else combine_keys(lk, rk)
        lin = _lineage.current()
        if lin is not None and len(out_keys):
            # a matched join row derives from BOTH side rows
            lin.record_edge(self, out_keys, lk)
            lin.record_edge(self, out_keys, rk)
        data: dict[str, np.ndarray] = {lid: lk, rid: rk}
        for name, arr in zip(l_names, l_cols):
            data[name] = arr
        for name, arr in zip(r_names, r_cols):
            data[name] = arr
        return DeltaBatch(out_keys, diffs.astype(np.int64), data, time)

    def _apply_side(self, side: int, batch: DeltaBatch, time: int) -> list[DeltaBatch]:
        """Apply one side's delta block against the other side's columnar state."""
        jk, valid = self._jk_valid(batch, side)
        diffs = batch.diffs
        my_cols = [batch.data[c] for c in self._side_cols(side)]
        pad_mine = self.how in ("left", "outer") if side == 0 else self.how in ("right", "outer")
        pad_other = self.how in ("right", "outer") if side == 0 else self.how in ("left", "outer")
        other = self.store[1 - side]
        out: list[DeltaBatch] = []
        # null join keys never match; padded if outer on my side
        if pad_mine and not valid.all():
            inv = np.flatnonzero(~valid)
            out.append(
                self._pad_arrays(
                    side, batch.keys[inv], [c[inv] for c in my_cols], diffs[inv], time
                )
            )
        for sign in (-1, 1):  # retractions before insertions
            idx = np.flatnonzero(valid & ((diffs < 0) if sign < 0 else (diffs > 0)))
            if not len(idx):
                continue
            q_jk = jk[idx]
            q_rk = batch.keys[idx]
            q_diff = diffs[idx]
            q_cols = [c[idx] for c in my_cols]
            # matched rows appear/disappear with my delta's sign
            m_q, m_rk, m_cols = other.match(q_jk)
            if len(m_q):
                out.append(
                    self._matched_arrays(
                        side, q_rk[m_q], [c[m_q] for c in q_cols],
                        m_rk, m_cols, q_diff[m_q], time,
                    )
                )
            # my padded rows exist exactly while the other side has no match
            if pad_mine:
                unmatched = np.flatnonzero(self.jk_counts[1 - side].get(q_jk) == 0)
                if len(unmatched):
                    out.append(
                        self._pad_arrays(
                            side, q_rk[unmatched],
                            [c[unmatched] for c in q_cols], q_diff[unmatched], time,
                        )
                    )
            # apply my delta to my state; 0<->+ transitions flip the other
            # side's padded rows. My jk counts are only consulted when the
            # OTHER side pads (== pad_other), so one-sided joins track one side.
            if not pad_other:
                if sign < 0:
                    self.store[side].delete(q_jk, q_rk)
                else:
                    self.store[side].insert(q_jk, q_rk, q_cols)
                continue
            uniq, prev, new = self.jk_counts[side].add(q_jk, q_diff)
            if sign < 0:
                self.store[side].delete(q_jk, q_rk)
                flipped = uniq[(prev > 0) & (new <= 0)]
                flip_diff = 1  # other side lost its last match: padded rows appear
            else:
                self.store[side].insert(q_jk, q_rk, q_cols)
                flipped = uniq[(prev <= 0) & (new > 0)]
                flip_diff = -1  # other side gained a first match: padded rows retract
            if len(flipped):
                f_q, f_rk, f_cols = other.match(flipped)
                if len(f_q):
                    out.append(
                        self._pad_arrays(
                            1 - side, f_rk, f_cols,
                            np.full(len(f_rk), flip_diff, dtype=np.int64), time,
                        )
                    )
        return out

    def process(self, inputs, time):
        tok = _phases.start()
        try:
            return self._process_impl(inputs, time)
        finally:
            _phases.stop(tok, "join")

    def _process_impl(self, inputs, time):
        # Sides apply sequentially (left first), each probing the other's
        # state as of that moment — the batch-granular equivalent of the
        # reference's record-at-a-time symmetric join discipline.
        out: list[DeltaBatch] = []
        for side in (0, 1):
            batch = inputs[side]
            if batch is not None and len(batch):
                out.extend(self._apply_side(side, batch, time))
        out = [b for b in out if not b.is_empty]
        if not out:
            return []
        if len(out) == 1:
            # every batch _apply_side emits is sign-pure (per-sign sub-batches,
            # flips are constant-diff), so a lone batch cannot net against itself
            return out
        merged = concat_batches(out)
        if merged is None:
            return []
        # unique_hint: a tick's matched output keys are (left, right)-pair
        # hashes, distinct within the tick except same-tick upserts — the
        # digest-free canonicalization almost always applies
        return [consolidate(merged, unique_hint=True)]


# ---------------------------------------------------------------------------- outputs


def _observe_sink_latency(node: Node, time: int) -> None:
    """End-to-end latency probe shared by the sinks: wall time from the
    oldest event ingested for this tick (stamped by ``StreamInputNode.poll``)
    to the tick's emission here — accumulated into the sink's log-bucketed
    histogram (``/metrics`` Prometheus histograms, ``/status`` quantiles)."""
    from pathway_tpu.observability.metrics import run_metrics

    m = run_metrics()
    ingest_ns = m.tick_ingest_ns(time)
    if ingest_ns is None:
        return  # static tick / no live ingest stamped for this time
    m.observe_sink_latency(
        f"{node.name}:{node.node_index}",
        max(0.0, (_time_mod.time_ns() - ingest_ns) / 1e9),
    )


class SubscribeNode(Node):
    """``pw.io.subscribe`` (reference: ``io/_subscribe.py`` → ``subscribe_table``,
    ``src/engine/graph.rs:543``).

    Callbacks fire once per logical time with the tick's emissions
    CONSOLIDATED (net diffs per key+row), matching the reference's
    ``BatchWrapper`` per-time delivery — intra-tick churn (e.g. an as-of-now
    reply overwriting the query-tick padding, or a sweep-round partial that a
    later round corrects) is invisible to user callbacks."""

    name = "subscribe"

    #: sink marker + service class: the flow plane's AIMD controller reads
    #: latency histograms only from ``interactive``-class sinks (the ones the
    #: SLO governs); ``pw.io.subscribe(..., service_class="bulk")`` opts out
    is_sink = True

    def exchange_key(self, port):
        # default: sources/sinks live on worker 0. With ``route_by`` set
        # (shard-map zero-hop serving), callbacks instead fire on the worker
        # owning each row's route key — every process observes exactly its
        # own slice of the changelog, so N doors answer independently.
        return self.route_by if self.route_by is not None else SOLO

    def __init__(
        self,
        columns: list[str],
        on_change: Callable | None = None,
        on_time_end: Callable | None = None,
        on_end: Callable | None = None,
        route_by: Callable | None = None,
    ):
        super().__init__(n_inputs=1)
        self.service_class = "interactive"
        self.columns = columns
        self.on_change = on_change
        self.on_time_end = on_time_end
        self._on_end = on_end
        self.route_by = route_by
        self._pending: list[DeltaBatch] = []

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is not None:
            aud = _audit.current()
            if aud is not None:
                # raw-side incremental digest: accumulated from the deltas as
                # they arrive, BEFORE the tick netting below — the shadow
                # audit's independent path through the consolidation machinery
                aud.on_sink_delta(self, batch)
            self._pending.append(batch)
        return []

    def on_tick_complete(self, time):
        if not self._pending:
            return
        batches, self._pending = self._pending, []
        # incremental tick netting: each emission is consolidated at its own
        # size and merged in O(overlap) — byte-identical to consolidating the
        # tick's whole concatenation (the merge_consolidated ≡
        # consolidate∘concat property, swept in tests/test_incremental_hot_path.py)
        net = None
        for b in batches:
            net = merge_consolidated(net, consolidate(b))
        aud = _audit.current()
        if aud is not None:
            # net-side fold + invariant checks + sampled shadow compare
            aud.on_sink_net(self, net, time)
        if net is not None and len(net) and self.on_change is not None:
            for key, diff, row in net.rows():
                row_dict = dict(zip(self.columns, row))
                self.on_change(
                    key=key, row=row_dict, time=time, is_addition=diff > 0
                )
        # on_time_end is a per-time commit signal: it fires whenever raw data
        # arrived this tick, even if consolidation nets to zero (a retract +
        # re-insert of identical rows still marks the time as processed);
        # only on_change is gated on the net batch
        if self.on_time_end is not None and time != END_OF_STREAM:
            self.on_time_end(time)
        _observe_sink_latency(self, time)

    def on_end(self):
        if self._on_end is not None:
            self._on_end()


class CaptureNode(Node):
    """Accumulates the final consolidated state (debug/compute_and_print) and the
    full stream of deltas (stream assertions).

    The tick path is O(1) per block: batches are parked columnar (they ARE the
    delta log) and folded lazily on access. ``current`` folds with one
    vectorized last-op-wins pass — identical to sequential per-row apply,
    since a key's final dict entry is exactly its LAST operation's effect
    (earlier sets/pops are overwritten) — and builds row tuples only for keys
    whose last op is an insert. ``deltas`` materializes row tuples only when a
    stream assertion actually reads them. The per-row dict loop this replaces
    was the single largest phase of the incremental bench (BASELINE
    §incremental: ~half the tick under churny groupby retract+insert output).
    """

    name = "capture"

    snapshot_attrs = ("current", "deltas")

    def exchange_key(self, port):
        return SOLO  # sources/sinks live on worker 0

    def __init__(self, columns: list[str]):
        super().__init__(n_inputs=1)
        self.columns = columns
        self._current: dict[int, tuple] = {}
        self._deltas: list[tuple[int, int, int, tuple]] = []  # (time, key, diff, row)
        self._batches: list[DeltaBatch] = []  # parked blocks, in arrival order
        self._cur_upto = 0  # _batches fold cursor for _current
        self._deltas_upto = 0  # _batches materialization cursor for _deltas

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None or batch.is_empty:
            return []
        self._batches.append(batch)
        return []

    def _fold_current(self) -> None:
        if self._cur_upto >= len(self._batches):
            return
        tok = _phases.start()
        bs = self._batches[self._cur_upto :]
        self._cur_upto = len(self._batches)
        if len(bs) == 1:
            keys, diffs, cols = bs[0].keys, bs[0].diffs, list(bs[0].data.values())
        else:
            keys = np.concatenate([b.keys for b in bs])
            diffs = np.concatenate([b.diffs for b in bs])
            cols = [
                concat_cols([b.data[n] for b in bs]) for n in bs[0].data.keys()
            ]
        n = len(keys)
        # last occurrence of each key across the concatenated (ordered) log
        uniq, rev_first = np.unique(keys[::-1], return_index=True)
        last = n - 1 - rev_first
        set_mask = diffs[last] > 0
        set_idx = last[set_mask]
        if len(set_idx):
            if cols:
                rows = zip(*(column_to_list(c[set_idx]) for c in cols))
            else:
                rows = iter([()] * len(set_idx))
            self._current.update(zip(uniq[set_mask].tolist(), rows))
        pops = uniq[~set_mask]
        if len(pops):
            cur = self._current
            for k in pops.tolist():
                cur.pop(k, None)
        self._prune_batches()
        _phases.stop(tok, "capture")

    def _materialize_deltas(self) -> None:
        if self._deltas_upto >= len(self._batches):
            return
        bs = self._batches[self._deltas_upto :]
        self._deltas_upto = len(self._batches)
        for batch in bs:
            keys = batch.keys.tolist()
            diffs = batch.diffs.tolist()
            if batch.data:
                rows = list(zip(*(column_to_list(c) for c in batch.data.values())))
            else:
                rows = [()] * len(keys)
            self._deltas.extend(zip([batch.time] * len(keys), keys, diffs, rows))
        self._prune_batches()

    def _prune_batches(self) -> None:
        """Drop parked blocks both folds have consumed — a long-running job
        that reads both ``current`` and ``deltas`` (e.g. every persistence
        snapshot) must not hold the delta log twice."""
        done = min(self._cur_upto, self._deltas_upto)
        if done:
            del self._batches[:done]
            self._cur_upto -= done
            self._deltas_upto -= done

    @property
    def current(self) -> dict[int, tuple]:
        self._fold_current()
        return self._current

    @property
    def deltas(self) -> list[tuple[int, int, int, tuple]]:
        self._materialize_deltas()
        return self._deltas

    def snapshot_state(self) -> dict | None:
        # materialized forms only: parked DeltaBatches stay out of snapshots
        return {"current": dict(self.current), "deltas": list(self.deltas)}

    def restore_state(self, state: dict) -> None:
        self._current = dict(state.get("current", {}))
        self._deltas = list(state.get("deltas", []))
        self._batches = []
        self._cur_upto = 0
        self._deltas_upto = 0


class CallbackOutputNode(Node):
    """Generic per-batch sink for io writers.

    ``sharded=True`` (r5) keeps each row's output on the worker owning its key
    shard instead of funneling everything to worker 0 — per-worker sink
    shards with an ordered merge-commit (see ``io/fs.py`` write(sharded=True);
    reference: per-worker writers, ``worker-architecture.md:36-47``)."""

    name = "output"

    is_sink = True  # flow controller SLO scope (see SubscribeNode)

    def exchange_key(self, port):
        if self.sharded:
            return lambda batch: batch.keys  # co-locate by row key shard
        return SOLO  # sources/sinks live on worker 0

    def __init__(
        self,
        columns: list[str],
        on_batch: Callable,
        on_done: Callable | None = None,
        sharded: bool = False,
        sink_state: Callable | None = None,
        restore_sink: Callable | None = None,
        service_class: str = "interactive",
    ):
        super().__init__(n_inputs=1)
        # flow plane SLO scope (see SubscribeNode): a bulk-class writer (e.g.
        # an fsync-bound audit mirror) must not drag the AIMD bucket down on
        # behalf of traffic that doesn't care about latency
        self.service_class = service_class
        self.columns = columns
        self.on_batch = on_batch
        self.on_done = on_done
        self.sharded = sharded
        # exactly-once hooks (r5, beating the reference's at-least-once OSS
        # tier, README.md:96 / src/persistence/state.rs:291): a sink that can
        # report a durable write position (sink_state) and rewind to it
        # (restore_sink) participates in operator snapshots — restart
        # truncates the output back to the snapshot cut, and the replayed
        # suffix re-emits each output row exactly once
        self.sink_state_fn = sink_state
        self.restore_sink_fn = restore_sink
        self._tick_buffer: list[DeltaBatch] = []

    def snapshot_state(self) -> dict | None:
        if self.sink_state_fn is None:
            return None
        return {"__sink__": self.sink_state_fn()}

    def restore_state(self, state: dict) -> None:
        if self.restore_sink_fn is not None and "__sink__" in state:
            self.restore_sink_fn(state["__sink__"])

    def process(self, inputs, time):
        # buffer within the tick; emission happens sorted at the frontier so the
        # written order is independent of worker count / block arrival order
        batch = inputs[0]
        if batch is not None and not batch.is_empty:
            aud = _audit.current()
            if aud is not None:
                aud.on_sink_delta(self, batch)  # raw-side digest (see SubscribeNode)
            self._tick_buffer.append(batch)
        return []

    def on_frontier(self, time):
        if self._tick_buffer:
            merged = concat_batches(self._tick_buffer)
            self._tick_buffer = []
            if merged is not None and not merged.is_empty:
                # net out same-tick churn (mid-tick corrections differ by worker
                # topology); consolidate returns canonical (key, diff) order, so
                # output is byte-identical for any thread/process layout
                merged = consolidate(merged)
            aud = _audit.current()
            if aud is not None:
                aud.on_sink_net(self, merged, time)
            if merged is not None and not merged.is_empty:
                self.on_batch(merged, self.columns)
                _observe_sink_latency(self, time)
        return []

    def on_end(self):
        self.on_frontier(END_OF_STREAM)
        if self.on_done is not None:
            self.on_done()
