"""Flag-gated jitted JAX kernels for the relational hot path.

SURVEY §7.1.1 bets that relational ops (map/filter/join/reduce) should become
jitted kernels over column blocks. This module makes that bet testable: it
holds device implementations of the two load-bearing kernels of the block
engine — the grouped segment-sum that powers ``GroupByNode`` and the sorted
probe that powers ``ColumnarMultimap``/``JoinNode`` — behind the
``PATHWAY_ENGINE_JAX`` flag. Integer results (keys, counts, int sums, probe
positions) are bit-identical to the numpy path (same stable ordering, same
dtypes); float sums match to accumulation order only (segment_sum does not
reduce strictly left-to-right the way ``np.add.reduceat`` does), which is one
more reason the groupby kernel stays opt-in while the integer-exact probe is
adopted by default.

Flag values:
  - unset / ``auto`` — adopt what measured faster: the **join probe runs on
    the XLA CPU backend** for large blocks (its multithreaded binary search
    beat numpy searchsorted 1.8-5.9x from 8k-row state up to 10M in
    ``benchmarks/jax_kernel_bench.py``); groupby stays numpy.
  - ``0`` — numpy everywhere.
  - ``1`` — both kernels on the default backend.
  - ``cpu`` / ``tpu`` — both kernels pinned to that backend.

Measured verdict (2026-07-30, this host + tunneled v5e — see
``benchmarks/jax_kernel_bench.py`` and BASELINE.md): the **probe kernel is a
win and is adopted by default**; the **groupby segment-sum is a measured
negative** — numpy argsort+reduceat runs 3.5M rows/s at 10M rows vs 1.9M
(XLA CPU) and 2.1M (TPU device-resident; u64 sort is 32-bit-emulated), and
0.47M host-fed through the tunnel. The relational plane therefore stays
host-columnar by design, with the MXU path reserved for the FLOP-dense ops
(encoder, KNN, reranker). Reference counterpart: the per-row interpreted
expression VM + differential arrangements (``src/engine/expression.rs``,
``src/engine/dataflow.rs``) have no device analogue at all.
"""

from __future__ import annotations

import os
import threading
import weakref
from functools import partial
from typing import Any

import numpy as np

from pathway_tpu import jax_compat

_MIN_ROWS = 32_768  # below this, dispatch overhead dominates any kernel win


def flag() -> str:
    return os.environ.get("PATHWAY_ENGINE_JAX", "auto").strip().lower() or "auto"


_AVAILABLE: bool | None = None


def available() -> bool:
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            import jax  # noqa: F401

            _AVAILABLE = True
        except Exception:  # pragma: no cover
            _AVAILABLE = False
    return _AVAILABLE


def enabled() -> bool:
    """Both kernels explicitly on (groupby included)."""
    return flag() not in ("auto", "0", "false") and available()


def _device(force_cpu: bool = False):
    import jax

    f = "cpu" if force_cpu else flag()
    if f in ("cpu", "tpu", "gpu"):
        try:
            return jax.local_devices(backend=f)[0]
        except RuntimeError:
            return None
    return None  # default backend


# ------------------------------------------------------------------ groupby


def _donate_active(dev) -> bool:
    """Buffer donation on tick-loop jit entry points (PATHWAY_ARRANGE_DONATE):
    per-tick inputs (probe queries, grouped keys/diffs/columns) are dead after
    the call, so XLA may reuse their device memory for outputs — a realloc+copy
    saved every tick. ``auto`` donates on tpu/gpu only: the CPU backend
    ignores donation and warns."""
    from pathway_tpu.internals.config import get_pathway_config

    mode = get_pathway_config().arrange_donate
    if mode == "off":
        return False
    if mode == "on":
        return True
    import jax

    platform = dev.platform if dev is not None else jax.default_backend()
    return platform in ("tpu", "gpu")


def _jit_grouped(n_cols: int, donate: bool = False):
    import jax
    import jax.numpy as jnp

    def kernel(keys, diffs, cols):
        order = jnp.argsort(keys, stable=True)
        ks = keys[order]
        n = keys.shape[0]
        newg = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), ks[1:] != ks[:-1]]
        )
        seg = jnp.cumsum(newg) - 1
        d = diffs[order]
        counts = jax.ops.segment_sum(d, seg, num_segments=n)
        sums = tuple(
            jax.ops.segment_sum(c[order] * d, seg, num_segments=n)
            for c in cols
        )
        return order, ks, newg, counts, sums

    jitted = (
        jax.jit(kernel, donate_argnums=(0, 1, 2)) if donate else jax.jit(kernel)
    )
    from pathway_tpu.observability import device as _dev_prof

    suffix = "/donated" if donate else ""
    return _dev_prof.traced_jit(f"engine.grouped/{n_cols}{suffix}", jitted)


_GROUPED_JIT: dict[tuple[int, bool], Any] = {}


def numpy_grouped_sums(
    gkeys: np.ndarray, diffs: np.ndarray, sum_cols: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, list[np.ndarray]]:
    """The numpy reference for :func:`grouped_sums` — the same
    argsort+reduceat recipe ``GroupByNode._process_columnar`` runs (shared
    here so benchmarks/tests compare against one implementation; the
    pipeline-level parity test in ``tests/test_jax_kernels.py`` guards the
    production path itself)."""
    from pathway_tpu.engine.blocks import group_starts

    order = np.argsort(gkeys, kind="stable")
    ks = gkeys[order]
    starts = group_starts(ks)
    counts = np.add.reduceat(diffs[order], starts) if len(ks) else np.empty(0, np.int64)
    sums = [np.add.reduceat(c[order] * diffs[order], starts) for c in sum_cols]
    return order, starts, ks[starts], counts, sums


def grouped_sums(
    gkeys: np.ndarray, diffs: np.ndarray, sum_cols: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, list[np.ndarray]]:
    """Device segment-sum groupby over one delta block.

    Returns ``(order, starts, u_gk, counts, partials)`` with the exact values
    (and stable first-occurrence ordering) of the numpy path:
    ``order = argsort(gkeys, stable)``, ``starts`` = sorted group boundaries,
    ``counts[i] = sum(diffs of group i)``, ``partials[c][i] = sum(col_c * diff)``.
    """
    import jax

    dev = _device()
    donate = _donate_active(dev)
    kern = _GROUPED_JIT.get((len(sum_cols), donate))
    if kern is None:
        kern = _GROUPED_JIT[(len(sum_cols), donate)] = _jit_grouped(
            len(sum_cols), donate
        )
    with jax_compat.enable_x64():
        args = (gkeys, diffs, tuple(sum_cols))
        if dev is not None:
            args = jax.device_put(args, dev)
        order, ks, newg, counts, sums = kern(*args)
        order = np.asarray(order)
        newg = np.asarray(newg)
        starts = np.flatnonzero(newg)
        g = len(starts)
        u_gk = np.asarray(ks)[starts]
        counts_np = np.asarray(counts)[:g]
        partials = [np.asarray(s)[:g] for s in sums]
    return order, starts, u_gk, counts_np, partials


def try_grouped(
    gkeys: np.ndarray, diffs: np.ndarray, reducer_specs, data: dict[str, np.ndarray]
):
    """Route a GroupByNode columnar block to the device kernel when eligible.

    Eligible = flag on, block large enough, and every reducer is a
    count/weighted-sum over a numeric column (the semigroup reducers whose
    partials are exactly a segment-sum). Returns
    ``(order, starts, u_gk, counts, partials)`` or None for the numpy path.
    """
    if not enabled() or len(gkeys) < _MIN_ROWS:
        return None
    from pathway_tpu.engine.reducers_impl import CountReducer, SumReducer

    cols: list[np.ndarray] = []
    kinds: list[tuple[str, str | None]] = []
    for (_, impl, colnames) in reducer_specs:
        if isinstance(impl, CountReducer):
            kinds.append(("count", None))
        elif isinstance(impl, SumReducer):
            col = data[colnames[0]]
            if col.dtype.kind not in "iufb":
                return None
            # match numpy promotion of col * int64-diffs exactly
            cols.append(col.astype(np.result_type(col.dtype, np.int64), copy=False))
            kinds.append(("sum", impl.kind))
        else:
            return None
    order, starts, u_gk, counts, sums = grouped_sums(gkeys, diffs, cols)
    partials: list[np.ndarray] = []
    si = 0
    for kind, sumkind in kinds:
        if kind == "count":
            partials.append(counts)
        else:
            p = sums[si]
            si += 1
            if sumkind == "float" and p.dtype.kind != "f":
                p = p.astype(np.float64)
            partials.append(p)
    return order, starts, u_gk, counts, partials


# ------------------------------------------------------------------ join probe


_CACHE_SET = False


def _persistent_cache() -> None:
    """XLA compiles one probe executable per (state, query) bucket pair; a
    fresh process would otherwise re-pay ~50-100 ms per pair, which on short
    runs erases the kernel's steady-state win (measured: the incremental
    engine bench dropped 488k→218k rows/s cold). The persistent cache makes
    that a once-per-machine cost."""
    global _CACHE_SET
    if _CACHE_SET:
        return
    _CACHE_SET = True
    import jax

    try:
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "pathway_tpu", "xla"
        )
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - cache is an optimization only
        pass


def _jit_probe(donate: bool = False):
    import jax
    import jax.numpy as jnp

    def kernel(sorted_keys, q):
        lo = jnp.searchsorted(sorted_keys, q, side="left")
        hi = jnp.searchsorted(sorted_keys, q, side="right")
        return lo, hi - lo

    # the query block is dead after the call (padded fresh per tick) — donate
    # it on accelerator backends; the STATE side is never donated, it is the
    # persistent arrangement re-probed across ticks
    jitted = jax.jit(kernel, donate_argnums=(1,)) if donate else jax.jit(kernel)
    from pathway_tpu.observability import device as _dev_prof

    suffix = "/donated" if donate else ""
    return _dev_prof.traced_jit(f"engine.join_probe{suffix}", jitted)


_PROBE_JIT: dict[bool, Any] = {}


_PAD_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)


def _bucket(n: int) -> int:
    b = 1024
    while b < n:
        b <<= 1
    return b


# Sorted state segments are immutable between compactions and probed many
# times; cache their padded copies so the pad memcpy is paid once, not per
# probe. Keyed by id() with a liveness weakref guard (ids recycle after GC).
# Locked: sharded-runtime worker threads probe concurrently.
_PAD_CACHE: dict[int, tuple[Any, np.ndarray]] = {}
_PAD_LOCK = threading.Lock()


def _padded_state(arr: np.ndarray, bs: int) -> np.ndarray:
    with _PAD_LOCK:
        ent = _PAD_CACHE.get(id(arr))
        if ent is not None and ent[0]() is arr and len(ent[1]) == bs:
            return ent[1]
    padded = np.concatenate([arr, np.full(bs - len(arr), _PAD_KEY, dtype=np.uint64)])
    with _PAD_LOCK:
        dead = [k for k, (r, _) in _PAD_CACHE.items() if r() is None]
        for k in dead:
            del _PAD_CACHE[k]
        try:
            _PAD_CACHE[id(arr)] = (weakref.ref(arr), padded)
        except TypeError:  # pragma: no cover - non-weakref-able array subclass
            pass
    return padded


# Persistent device-resident arrangements (PATHWAY_ARRANGE_CACHE): a sorted
# state segment is immutable between compactions, so its device copy is
# uploaded once per compaction generation and every later tick probes the
# SAME device buffer — the arrangement lives on device across ticks instead
# of riding PCIe every call. Keyed by id() of the (host) padded array with a
# liveness weakref (ids recycle after GC); one entry per (array, device).
_DEV_CACHE: dict[tuple[int, str], tuple[Any, Any]] = {}
_DEV_LOCK = threading.Lock()


def _device_state(arr: np.ndarray, dev) -> Any:
    from pathway_tpu.internals.config import get_pathway_config

    if not get_pathway_config().arrange_device_cache:
        import jax

        return jax.device_put(arr, dev) if dev is not None else arr
    import jax

    key = (id(arr), str(dev))
    with _DEV_LOCK:
        ent = _DEV_CACHE.get(key)
        if ent is not None and ent[0]() is arr:
            return ent[1]
    put = jax.device_put(arr, dev) if dev is not None else jax.device_put(arr)
    with _DEV_LOCK:
        dead = [k for k, (r, _) in _DEV_CACHE.items() if r() is None]
        for k in dead:
            del _DEV_CACHE[k]
        try:
            _DEV_CACHE[key] = (weakref.ref(arr), put)
        except TypeError:  # pragma: no cover - non-weakref-able array subclass
            pass
    return put


def join_probe(sorted_jk: np.ndarray, q_jk: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Masked sorted-array probe (the hash-join inner kernel): for each probe
    key, the ``(lo, count)`` range of matches in the sorted state array —
    identical to the numpy two-sided searchsorted.

    Streaming joins present a fresh ``(state_len, query_len)`` pair almost
    every tick, so both sides are padded to power-of-two buckets (state with
    the max key, which sorts after every real key and leaves lo/count of
    smaller probes untouched) to bound XLA recompiles at O(log² n) shapes.
    Probes equal to the pad key are corrected on the host (rare: one hash
    value in 2^64).
    """
    import jax

    n_state, n_q = len(sorted_jk), len(q_jk)
    bs, bq = _bucket(n_state), _bucket(n_q)
    if bs != n_state:
        sorted_jk = _padded_state(sorted_jk, bs)
    if bq != n_q:
        q_jk_padded = np.concatenate(
            [q_jk, np.zeros(bq - n_q, dtype=np.uint64)]
        )
    else:
        q_jk_padded = q_jk
    # auto mode adopts the probe on the CPU backend (the measured win);
    # explicit backends are honored as given
    dev = _device(force_cpu=flag() == "auto")
    donate = _donate_active(dev)
    kern = _PROBE_JIT.get(donate)
    if kern is None:
        _persistent_cache()
        kern = _PROBE_JIT[donate] = _jit_probe(donate)
    with jax_compat.enable_x64():
        state_arg = _device_state(sorted_jk, dev)
        q_arg = q_jk_padded
        if dev is not None:
            q_arg = jax.device_put(q_arg, dev)
        elif donate:
            # donation only reaches XLA for device-committed args; the numpy
            # fast path would silently copy anyway
            q_arg = jax.device_put(q_arg)
        lo, cnt = kern(state_arg, q_arg)
        # np.array (not asarray): JAX outputs are read-only; the pad
        # correction below mutates
        lo = np.array(lo[:n_q])
        cnt = np.array(cnt[:n_q])
    if bs != n_state:
        hit_pad = q_jk == _PAD_KEY
        if hit_pad.any():
            idx = np.flatnonzero(hit_pad)
            real = sorted_jk[:n_state]
            lo[idx] = np.searchsorted(real, q_jk[idx], side="left")
            cnt[idx] = np.searchsorted(real, q_jk[idx], side="right") - lo[idx]
        lo = np.minimum(lo, n_state)
    return lo, cnt


#: auto-adoption thresholds. Isolated steady-shape microbenchmarks show wins
#: from 8k-row state, but in-engine the per-call dispatch overhead and the
#: per-shape-bucket XLA compiles only amortize on big blocks (measured:
#: static 1M-row load 895k→1051k rows/s, while 20k-row incremental ticks
#: regressed 488k→255k when routed) — so auto only routes big probes.
_PROBE_STATE, _PROBE_QUERY = 131072, 32768


def disable() -> None:
    """Kill switch for callers that hit a JAX runtime failure mid-pipeline:
    the numpy path is always correct, so stop routing for good."""
    global _AVAILABLE
    _AVAILABLE = False


def probe_eligible(n_state: int, n_query: int) -> bool:
    f = flag()
    if f in ("0", "false") or not available():
        return False
    if f == "auto":
        return n_state >= _PROBE_STATE and n_query >= _PROBE_QUERY
    return n_state >= _MIN_ROWS and n_query >= 1024
