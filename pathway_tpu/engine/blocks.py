"""Columnar delta blocks — the engine's unit of data.

The reference streams per-record ``(key, tuple, time, diff)`` updates through
differential operators (``src/engine/dataflow.rs``). That shape is hostile to XLA, so
per SURVEY §7.1.1 the TPU engine's unit is a **delta block**: aligned uint64 key
array, int64 diff (±weight) array, and a dict of columnar value arrays, all sharing a
logical timestamp. Relational kernels are vectorized over whole blocks;
consolidation is a sort + segmented reduction over (key, row-digest).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.keys import hash_column, row_keys, splitmix64
from pathway_tpu.observability import engine_phases as _phases


def _audit_current():
    # late import: blocks is imported before the observability package's
    # audit module finishes loading in some import orders
    from pathway_tpu.observability.audit import current

    return current()


class DeltaBatch:
    __slots__ = ("keys", "diffs", "data", "time")

    def __init__(
        self,
        keys: np.ndarray,
        diffs: np.ndarray,
        data: Mapping[str, np.ndarray],
        time: int,
    ):
        self.keys = np.asarray(keys, dtype=np.uint64)
        self.diffs = np.asarray(diffs, dtype=np.int64)
        self.data = dict(data)
        self.time = time
        n = len(self.keys)
        assert len(self.diffs) == n, "diffs misaligned"
        for name, col in self.data.items():
            assert len(col) == n, f"column {name!r} misaligned: {len(col)} != {n}"

    def __len__(self) -> int:
        return len(self.keys)

    def __repr__(self) -> str:
        return f"DeltaBatch(n={len(self)}, t={self.time}, cols={list(self.data)})"

    @property
    def is_empty(self) -> bool:
        return len(self.keys) == 0

    def take(self, idx: np.ndarray) -> "DeltaBatch":
        return DeltaBatch(
            self.keys[idx],
            self.diffs[idx],
            {n: c[idx] for n, c in self.data.items()},
            self.time,
        )

    def with_data(self, data: Mapping[str, np.ndarray]) -> "DeltaBatch":
        return DeltaBatch(self.keys, self.diffs, data, self.time)

    def with_keys(self, keys: np.ndarray) -> "DeltaBatch":
        return DeltaBatch(keys, self.diffs, self.data, self.time)

    def select_columns(self, names: Iterable[str]) -> "DeltaBatch":
        return DeltaBatch(self.keys, self.diffs, {n: self.data[n] for n in names}, self.time)

    def negated(self) -> "DeltaBatch":
        return DeltaBatch(self.keys, -self.diffs, self.data, self.time)

    def rows(self) -> Iterable[tuple[int, int, tuple]]:
        # columnar → row tuples via one zip transpose (not a per-cell genexpr);
        # keys/diffs come out as python ints
        keys = self.keys.tolist()
        diffs = self.diffs.tolist()
        if self.data:
            yield from zip(keys, diffs, zip(*(column_to_list(c) for c in self.data.values())))
        else:
            empty = ()
            for k, d in zip(keys, diffs):
                yield k, d, empty

    def row_digest(self) -> np.ndarray:
        """uint64 digest of each row's values (keys excluded)."""
        n = len(self.keys)
        h = np.zeros(n, dtype=np.uint64)
        for name in sorted(self.data):
            with np.errstate(over="ignore"):
                h = splitmix64(h * np.uint64(0x100000001B3) ^ hash_column(self.data[name]))
        return h

    @staticmethod
    def empty(columns: Iterable[str], time: int) -> "DeltaBatch":
        return DeltaBatch(
            np.empty(0, dtype=np.uint64),
            np.empty(0, dtype=np.int64),
            {c: np.empty(0, dtype=object) for c in columns},
            time,
        )

    @staticmethod
    def from_rows(
        keys: Iterable[Any],
        rows: Iterable[tuple],
        columns: list[str],
        time: int,
        diffs: Iterable[int] | None = None,
        np_dtypes: Mapping[str, np.dtype] | None = None,
    ) -> "DeltaBatch":
        tok = _phases.start()
        keys_arr = (
            keys.astype(np.uint64, copy=False)
            if isinstance(keys, np.ndarray)
            else np.fromiter(keys, dtype=np.uint64)
        )
        n = len(keys_arr)
        rows = list(rows)
        data: dict[str, np.ndarray] = {}
        for j, name in enumerate(columns):
            npd = (np_dtypes or {}).get(name, np.dtype(object))
            data[name] = make_column([r[j] for r in rows], npd)
        diffs_arr = (
            np.ones(n, dtype=np.int64)
            if diffs is None
            else np.fromiter(diffs, dtype=np.int64, count=n)
        )
        _phases.stop(tok, "realloc")
        return DeltaBatch(keys_arr, diffs_arr, data, time)


def column_to_list(arr: np.ndarray) -> list:
    """Column → Python list for row-tuple assembly. datetime64/timedelta64 keep
    their numpy scalar form (``tolist()`` would yield raw ns integers)."""
    if arr.dtype.kind in ("M", "m"):
        return list(arr)
    return arr.tolist()


def make_column(values: list, np_dtype: np.dtype) -> np.ndarray:
    """Build a column array of the schema's storage dtype, falling back to object
    when values don't fit (None in an int column, etc.)."""
    if np_dtype == np.dtype(object):
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr
    if np_dtype.kind == "b":
        # np.asarray silently coerces None to False, so bool needs the explicit
        # None scan before the typed conversion
        if not any(v is None for v in values):
            try:
                return np.asarray(values, dtype=np_dtype)
            except (TypeError, ValueError):
                pass
    else:
        # direct conversion first: the common all-typed case needs no None scan
        # (None raises TypeError and lands in the fallback below)
        try:
            return np.asarray(values, dtype=np_dtype)
        except (TypeError, ValueError):
            pass
    try:
        if any(v is None for v in values):
            if np_dtype.kind == "f":
                return np.asarray(
                    [np.nan if v is None else v for v in values], dtype=np_dtype
                )
            if np_dtype.kind in ("M", "m"):
                return np.asarray(
                    [np.datetime64("NaT") if v is None else v for v in values], dtype=np_dtype
                )
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr
    except (TypeError, ValueError):
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr


def concat_cols(parts: list[np.ndarray]) -> np.ndarray:
    """Concatenate column arrays; mixed dtypes merge into an object array.
    list() keeps datetime64/timedelta64 scalars intact (direct slice-assign
    into an object array int-ifies them)."""
    if len(parts) == 1:
        return parts[0]
    if all(p.dtype == parts[0].dtype for p in parts):
        return np.concatenate(parts)
    merged = np.empty(sum(len(p) for p in parts), dtype=object)
    ofs = 0
    for p in parts:
        merged[ofs : ofs + len(p)] = list(p) if p.dtype.kind in ("M", "m") else p
        ofs += len(p)
    return merged


def group_starts(sorted_keys: np.ndarray) -> np.ndarray:
    """Boundary indices of equal-key runs in a sorted key array."""
    n = len(sorted_keys)
    if n == 0:
        return np.empty(0, dtype=np.int64)
    boundaries = np.empty(n, dtype=bool)
    boundaries[0] = True
    boundaries[1:] = sorted_keys[1:] != sorted_keys[:-1]
    return np.flatnonzero(boundaries)


def concat_batches(batches: list[DeltaBatch]) -> DeltaBatch | None:
    batches = [b for b in batches if not b.is_empty]
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    tok = _phases.start()
    time = batches[-1].time
    keys = np.concatenate([b.keys for b in batches])
    diffs = np.concatenate([b.diffs for b in batches])
    names = batches[0].data.keys()
    data = {n: concat_cols([b.data[n] for b in batches]) for n in names}
    _phases.stop(tok, "realloc")
    return DeltaBatch(keys, diffs, data, time)


def consolidate(batch: DeltaBatch, unique_hint: bool = False) -> DeltaBatch:
    """Sum diffs per (key, row-digest); drop rows with net diff 0.

    The block analogue of differential's arrangement consolidation. Canonical
    output order: sorted by key, then net diff ascending (retractions precede
    insertions), then row digest — deterministic for any input permutation.

    ``unique_hint=True``: the caller expects the batch's keys to be unique
    (e.g. an incremental join's per-tick output, keyed by (left, right) row
    pairs) — attempt the digest-free unique-key fast path even for
    mixed-sign batches. Purely a cost hint; a wrong hint costs one wasted
    argsort and falls through to the general path.
    """
    if len(batch) <= 1:
        if len(batch) == 1 and batch.diffs[0] == 0:
            return batch.take(np.empty(0, dtype=np.int64))
        return batch
    tok = _phases.start()
    out = _consolidate_impl(batch, unique_hint)
    _phases.stop(tok, "consolidate")
    aud = _audit_current()
    if aud is not None:
        # PATHWAY_AUDIT=full: verify the canonical/net-free contract on every
        # consolidated batch (no-op in "on" mode — see check_canonical)
        aud.check_canonical(out, "consolidate")
    return out


def _consolidate_impl(batch: DeltaBatch, unique_hint: bool = False) -> DeltaBatch:
    # fast path — unique keys. Netting and merging happen per (key, digest),
    # so a batch with no duplicate KEY cannot net or merge at all: the
    # canonical form is just the key sort (within-key diff/digest ordering
    # is vacuous for singleton groups) with zero diffs dropped, and the
    # per-column row-digest hash — the dominant cost of the general path —
    # is skipped entirely. Attempted when the batch is all-inserts (every
    # freshly-polled input block) or the caller hinted uniqueness (an
    # incremental join's per-tick output: unique (left, right)-pair keys,
    # mixed signs under churn — r15: its digest hash was ~1ms of every
    # churn tick). Duplicate-key batches without the hint (groupby
    # retract+insert emissions) skip straight to the general path, paying
    # no speculative sort.
    if unique_hint or bool((batch.diffs > 0).all()):
        order = np.argsort(batch.keys, kind="stable")
        k = batch.keys[order]
        if not bool((k[1:] == k[:-1]).any()):
            if bool((batch.diffs != 0).all()):
                return batch.take(order)
            kept = order[batch.diffs[order] != 0]
            return batch.take(kept)
    digests = batch.row_digest()
    order = np.lexsort((digests, batch.keys))
    k = batch.keys[order]
    d = digests[order]
    boundaries = np.empty(len(k), dtype=bool)
    boundaries[0] = True
    boundaries[1:] = (k[1:] != k[:-1]) | (d[1:] != d[:-1])
    group_starts = np.flatnonzero(boundaries)
    sums = np.add.reduceat(batch.diffs[order], group_starts)
    keep = sums != 0
    kept_idx = order[group_starts[keep]]
    kept_sums = sums[keep].astype(np.int64)
    kept_keys = k[group_starts[keep]]
    # canonical order: within a key, retractions precede insertions, so stateful
    # consumers (capture/combine/join state) can apply rows in batch order
    final = np.lexsort((kept_sums, kept_keys))
    out = batch.take(kept_idx[final])
    out.diffs = kept_sums[final]
    return out


def net_input_batch(batch: DeltaBatch) -> DeltaBatch:
    """Net a freshly-polled input block — ``consolidate`` semantics, minus the
    canonical key sort when the block provably cannot net: all inserts, no
    duplicate keys, the overwhelmingly common poll shape. Such a block is
    returned AS IS, in arrival order, removing an O(n log n) +
    full-block-copy tax from every streaming tick (BASELINE §incremental).

    Arrival order is deterministic (it is the connector log's order, polled
    on the owning worker), and consolidating sinks (subscribe, output
    writers, final captured state) re-canonicalize at emission, so results
    are unchanged. The one observable difference: the RAW per-tick update
    stream of a passthrough pipeline (``CaptureNode.deltas`` /
    ``compute_and_print_update_stream``) now lists a net-free input block's
    rows in arrival order rather than key-sorted — same multiset, same
    determinism, different within-tick order."""
    if len(batch) <= 1:
        if len(batch) == 1 and batch.diffs[0] == 0:
            return batch.take(np.empty(0, dtype=np.int64))
        return batch
    if bool((batch.diffs > 0).all()):
        k = np.sort(batch.keys)
        if not bool((k[1:] == k[:-1]).any()):
            return batch
    return consolidate(batch)


def _member(keys: np.ndarray, sorted_set: np.ndarray) -> np.ndarray:
    """bool[n]: is each key in the sorted unique ``sorted_set``."""
    if not len(sorted_set) or not len(keys):
        return np.zeros(len(keys), dtype=bool)
    pos = np.searchsorted(sorted_set, keys).clip(0, len(sorted_set) - 1)
    return sorted_set[pos] == keys


def interleave_positions(
    a_keys: np.ndarray, b_keys: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Merged positions for two SORTED key runs: ``(ia, ib)`` such that
    scattering ``a`` to ``ia`` and ``b`` to ``ib`` yields one sorted run.
    On ties, ``a``'s rows precede ``b``'s (side=left/right below) — the
    order a stable argsort over their concatenation would give. The shared
    primitive behind the groupby state merge, segment compaction and
    ``merge_consolidated``."""
    ia = np.arange(len(a_keys), dtype=np.int64) + np.searchsorted(
        b_keys, a_keys, side="left"
    )
    ib = np.arange(len(b_keys), dtype=np.int64) + np.searchsorted(
        a_keys, b_keys, side="right"
    )
    return ia, ib


def scatter_cols(parts: list[np.ndarray], positions: list[np.ndarray], total: int) -> np.ndarray:
    """Scatter column parts to their merged positions (concat_cols dtype
    discipline: same dtypes keep them, mixes degrade to object with
    datetime64 scalars kept intact)."""
    live = [p for p in parts if len(p)]
    if live and all(p.dtype == live[0].dtype for p in live):
        out = np.empty(total, dtype=live[0].dtype)
    else:
        out = np.empty(total, dtype=object)
    for p, pos in zip(parts, positions):
        if not len(p):
            continue
        if out.dtype == object and p.dtype.kind in ("M", "m"):
            out[pos] = list(p)
        else:
            out[pos] = p
    return out


def merge_consolidated(base: DeltaBatch | None, delta: DeltaBatch | None) -> DeltaBatch | None:
    """O(delta)-flavored consolidation: merge two **individually consolidated**
    batches into one consolidated batch, byte-identical to
    ``consolidate(concat_batches([base, delta]))``.

    Keys present on only one side pass through untouched — no re-sort, no
    re-hash of the disjoint bulk. Only the rows of keys present on BOTH sides
    (the actually-contended state) are re-consolidated at digest granularity;
    the three sorted runs are then interleaved by searchsorted positions.
    This is the block engine's analogue of differential's merge batching: an
    already-consolidated arrangement absorbs a consolidated delta at cost
    proportional to the overlap, not the world.
    """
    if base is None or base.is_empty:
        return delta
    if delta is None or delta.is_empty:
        return base
    tok = _phases.start()
    try:
        a_keys, b_keys = base.keys, delta.keys
        a_uk = a_keys[group_starts(a_keys)]
        b_uk = b_keys[group_starts(b_keys)]
        pos = np.searchsorted(b_uk, a_uk).clip(0, len(b_uk) - 1)
        shared = a_uk[b_uk[pos] == a_uk]
        a_sh = _member(a_keys, shared)
        b_sh = _member(b_keys, shared)
        parts: list[DeltaBatch] = []
        a_rest = base.take(np.flatnonzero(~a_sh)) if a_sh.any() else base
        b_rest = delta.take(np.flatnonzero(~b_sh)) if b_sh.any() else delta
        parts.append(a_rest)
        parts.append(b_rest)
        if len(shared):
            sub = concat_batches(
                [base.take(np.flatnonzero(a_sh)), delta.take(np.flatnonzero(b_sh))]
            )
            net = _consolidate_impl(sub) if sub is not None and len(sub) > 1 else sub
            if net is not None and len(net):
                parts.append(net)
        parts = [p for p in parts if p is not None and len(p)]
        if not parts:
            return DeltaBatch.empty(base.data.keys(), delta.time)
        if len(parts) == 1:
            only = parts[0]
            return DeltaBatch(only.keys, only.diffs, only.data, delta.time)
        # interleave: keys are disjoint ACROSS parts, so each row's merged
        # position is its own index plus the count of smaller keys elsewhere
        # (the k-part generalization of interleave_positions)
        key_parts = [p.keys for p in parts]
        total = sum(len(k) for k in key_parts)
        positions: list[np.ndarray] = []
        for i, ki in enumerate(key_parts):
            pos_i = np.arange(len(ki), dtype=np.int64)
            for j, kj in enumerate(key_parts):
                if i != j:
                    pos_i += np.searchsorted(kj, ki)
            positions.append(pos_i)
        out_keys = np.empty(total, dtype=np.uint64)
        out_diffs = np.empty(total, dtype=np.int64)
        for p, pos_i in zip(parts, positions):
            out_keys[pos_i] = p.keys
            out_diffs[pos_i] = p.diffs
        names = list(base.data.keys())
        data = {
            n: scatter_cols([p.data[n] for p in parts], positions, total)
            for n in names
        }
        return DeltaBatch(out_keys, out_diffs, data, delta.time)
    finally:
        _phases.stop(tok, "consolidate")


def apply_diffs_to_state(state: dict, batch: DeltaBatch) -> None:
    """Fold a delta batch into a key→row-tuple dict (last-write-wins per key,
    respecting diffs: -1 removes, +1 inserts)."""
    cols = list(batch.data.values())
    for i in range(len(batch.keys)):
        k = int(batch.keys[i])
        if batch.diffs[i] > 0:
            state[k] = tuple(c[i] for c in cols)
        else:
            state.pop(k, None)
