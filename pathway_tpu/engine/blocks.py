"""Columnar delta blocks — the engine's unit of data.

The reference streams per-record ``(key, tuple, time, diff)`` updates through
differential operators (``src/engine/dataflow.rs``). That shape is hostile to XLA, so
per SURVEY §7.1.1 the TPU engine's unit is a **delta block**: aligned uint64 key
array, int64 diff (±weight) array, and a dict of columnar value arrays, all sharing a
logical timestamp. Relational kernels are vectorized over whole blocks;
consolidation is a sort + segmented reduction over (key, row-digest).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.keys import hash_column, row_keys, splitmix64


class DeltaBatch:
    __slots__ = ("keys", "diffs", "data", "time")

    def __init__(
        self,
        keys: np.ndarray,
        diffs: np.ndarray,
        data: Mapping[str, np.ndarray],
        time: int,
    ):
        self.keys = np.asarray(keys, dtype=np.uint64)
        self.diffs = np.asarray(diffs, dtype=np.int64)
        self.data = dict(data)
        self.time = time
        n = len(self.keys)
        assert len(self.diffs) == n, "diffs misaligned"
        for name, col in self.data.items():
            assert len(col) == n, f"column {name!r} misaligned: {len(col)} != {n}"

    def __len__(self) -> int:
        return len(self.keys)

    def __repr__(self) -> str:
        return f"DeltaBatch(n={len(self)}, t={self.time}, cols={list(self.data)})"

    @property
    def is_empty(self) -> bool:
        return len(self.keys) == 0

    def take(self, idx: np.ndarray) -> "DeltaBatch":
        return DeltaBatch(
            self.keys[idx],
            self.diffs[idx],
            {n: c[idx] for n, c in self.data.items()},
            self.time,
        )

    def with_data(self, data: Mapping[str, np.ndarray]) -> "DeltaBatch":
        return DeltaBatch(self.keys, self.diffs, data, self.time)

    def with_keys(self, keys: np.ndarray) -> "DeltaBatch":
        return DeltaBatch(keys, self.diffs, self.data, self.time)

    def select_columns(self, names: Iterable[str]) -> "DeltaBatch":
        return DeltaBatch(self.keys, self.diffs, {n: self.data[n] for n in names}, self.time)

    def negated(self) -> "DeltaBatch":
        return DeltaBatch(self.keys, -self.diffs, self.data, self.time)

    def rows(self) -> Iterable[tuple[int, int, tuple]]:
        # columnar → row tuples via one zip transpose (not a per-cell genexpr);
        # keys/diffs come out as python ints
        keys = self.keys.tolist()
        diffs = self.diffs.tolist()
        if self.data:
            yield from zip(keys, diffs, zip(*(column_to_list(c) for c in self.data.values())))
        else:
            empty = ()
            for k, d in zip(keys, diffs):
                yield k, d, empty

    def row_digest(self) -> np.ndarray:
        """uint64 digest of each row's values (keys excluded)."""
        n = len(self.keys)
        h = np.zeros(n, dtype=np.uint64)
        for name in sorted(self.data):
            with np.errstate(over="ignore"):
                h = splitmix64(h * np.uint64(0x100000001B3) ^ hash_column(self.data[name]))
        return h

    @staticmethod
    def empty(columns: Iterable[str], time: int) -> "DeltaBatch":
        return DeltaBatch(
            np.empty(0, dtype=np.uint64),
            np.empty(0, dtype=np.int64),
            {c: np.empty(0, dtype=object) for c in columns},
            time,
        )

    @staticmethod
    def from_rows(
        keys: Iterable[Any],
        rows: Iterable[tuple],
        columns: list[str],
        time: int,
        diffs: Iterable[int] | None = None,
        np_dtypes: Mapping[str, np.dtype] | None = None,
    ) -> "DeltaBatch":
        keys_arr = (
            keys.astype(np.uint64, copy=False)
            if isinstance(keys, np.ndarray)
            else np.fromiter(keys, dtype=np.uint64)
        )
        n = len(keys_arr)
        rows = list(rows)
        data: dict[str, np.ndarray] = {}
        for j, name in enumerate(columns):
            npd = (np_dtypes or {}).get(name, np.dtype(object))
            data[name] = make_column([r[j] for r in rows], npd)
        diffs_arr = (
            np.ones(n, dtype=np.int64)
            if diffs is None
            else np.fromiter(diffs, dtype=np.int64, count=n)
        )
        return DeltaBatch(keys_arr, diffs_arr, data, time)


def column_to_list(arr: np.ndarray) -> list:
    """Column → Python list for row-tuple assembly. datetime64/timedelta64 keep
    their numpy scalar form (``tolist()`` would yield raw ns integers)."""
    if arr.dtype.kind in ("M", "m"):
        return list(arr)
    return arr.tolist()


def make_column(values: list, np_dtype: np.dtype) -> np.ndarray:
    """Build a column array of the schema's storage dtype, falling back to object
    when values don't fit (None in an int column, etc.)."""
    if np_dtype == np.dtype(object):
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr
    if np_dtype.kind == "b":
        # np.asarray silently coerces None to False, so bool needs the explicit
        # None scan before the typed conversion
        if not any(v is None for v in values):
            try:
                return np.asarray(values, dtype=np_dtype)
            except (TypeError, ValueError):
                pass
    else:
        # direct conversion first: the common all-typed case needs no None scan
        # (None raises TypeError and lands in the fallback below)
        try:
            return np.asarray(values, dtype=np_dtype)
        except (TypeError, ValueError):
            pass
    try:
        if any(v is None for v in values):
            if np_dtype.kind == "f":
                return np.asarray(
                    [np.nan if v is None else v for v in values], dtype=np_dtype
                )
            if np_dtype.kind in ("M", "m"):
                return np.asarray(
                    [np.datetime64("NaT") if v is None else v for v in values], dtype=np_dtype
                )
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr
    except (TypeError, ValueError):
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr


def concat_cols(parts: list[np.ndarray]) -> np.ndarray:
    """Concatenate column arrays; mixed dtypes merge into an object array.
    list() keeps datetime64/timedelta64 scalars intact (direct slice-assign
    into an object array int-ifies them)."""
    if len(parts) == 1:
        return parts[0]
    if all(p.dtype == parts[0].dtype for p in parts):
        return np.concatenate(parts)
    merged = np.empty(sum(len(p) for p in parts), dtype=object)
    ofs = 0
    for p in parts:
        merged[ofs : ofs + len(p)] = list(p) if p.dtype.kind in ("M", "m") else p
        ofs += len(p)
    return merged


def group_starts(sorted_keys: np.ndarray) -> np.ndarray:
    """Boundary indices of equal-key runs in a sorted key array."""
    n = len(sorted_keys)
    if n == 0:
        return np.empty(0, dtype=np.int64)
    boundaries = np.empty(n, dtype=bool)
    boundaries[0] = True
    boundaries[1:] = sorted_keys[1:] != sorted_keys[:-1]
    return np.flatnonzero(boundaries)


def concat_batches(batches: list[DeltaBatch]) -> DeltaBatch | None:
    batches = [b for b in batches if not b.is_empty]
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    time = batches[-1].time
    keys = np.concatenate([b.keys for b in batches])
    diffs = np.concatenate([b.diffs for b in batches])
    names = batches[0].data.keys()
    data = {n: concat_cols([b.data[n] for b in batches]) for n in names}
    return DeltaBatch(keys, diffs, data, time)


def consolidate(batch: DeltaBatch) -> DeltaBatch:
    """Sum diffs per (key, row-digest); drop rows with net diff 0.

    The block analogue of differential's arrangement consolidation.
    """
    if len(batch) <= 1:
        if len(batch) == 1 and batch.diffs[0] == 0:
            return batch.take(np.empty(0, dtype=np.int64))
        return batch
    digests = batch.row_digest()
    order = np.lexsort((digests, batch.keys))
    k = batch.keys[order]
    d = digests[order]
    boundaries = np.empty(len(k), dtype=bool)
    boundaries[0] = True
    boundaries[1:] = (k[1:] != k[:-1]) | (d[1:] != d[:-1])
    group_starts = np.flatnonzero(boundaries)
    sums = np.add.reduceat(batch.diffs[order], group_starts)
    keep = sums != 0
    kept_idx = order[group_starts[keep]]
    kept_sums = sums[keep].astype(np.int64)
    kept_keys = k[group_starts[keep]]
    # canonical order: within a key, retractions precede insertions, so stateful
    # consumers (capture/combine/join state) can apply rows in batch order
    final = np.lexsort((kept_sums, kept_keys))
    out = batch.take(kept_idx[final])
    out.diffs = kept_sums[final]
    return out


def apply_diffs_to_state(state: dict, batch: DeltaBatch) -> None:
    """Fold a delta batch into a key→row-tuple dict (last-write-wins per key,
    respecting diffs: -1 removes, +1 inserts)."""
    cols = list(batch.data.values())
    for i in range(len(batch.keys)):
        k = int(batch.keys[i])
        if batch.diffs[i] > 0:
            state[k] = tuple(c[i] for c in cols)
        else:
            state.pop(k, None)
