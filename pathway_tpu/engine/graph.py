"""Engine dataflow graph and the tick scheduler.

Role of the reference's worker main loop (``src/engine/dataflow.rs:6202-6255``:
``loop { probers; flushers; pollers; worker.step_or_park }``): a topologically-ordered
DAG of engine nodes processes **delta blocks** tick by tick. Each logical timestamp is
one tick; within a tick the scheduler sweeps nodes in topo order until quiescent, then
advances the frontier (notifying temporal operators: buffers, forget, windows), then
sweeps again — so all downstream consequences of a timestamp are drained before the
next timestamp starts, giving the reference's "every output reflects a known prefix of
inputs" consistency model.
"""

from __future__ import annotations

import heapq
import time as _time
from typing import Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch, concat_batches
from pathway_tpu.internals.trace import run_annotated as _run_annotated
from pathway_tpu.observability import audit as _audit
from pathway_tpu.observability import device as _device_prof
from pathway_tpu.observability import engine_phases as _phases
from pathway_tpu.observability import requests as _requests
from pathway_tpu.resilience import faults as _faults

END_OF_STREAM = np.iinfo(np.int64).max  # frontier value after all input closed


SOLO = "solo"  # exchange marker: route every row to worker 0 (serial operator)

BROADCAST = "broadcast"  # exchange marker: deliver every row to EVERY worker
# (replicated consumers, e.g. index queries fanned out over doc shards)


class Node:
    """Engine operator. Subclasses implement ``process`` and optionally
    ``on_frontier``.

    ``exchange_key(port)`` declares how a multi-worker runtime must partition
    this node's input rows (the reference's exchange-by-shard contract,
    ``src/engine/dataflow/shard.rs``): ``None`` = no co-location requirement
    (stateless; process rows where they are produced), a callable
    ``batch -> uint64[n]`` = co-locate rows by that key's shard, ``SOLO`` =
    the operator is serial (global watermark / external index / output order) and
    runs entirely on worker 0."""

    name: str = "node"

    #: attribute names that constitute this node's operator state; empty =
    #: stateless. The operator-persistence layer (``persistence/snapshots.py``,
    #: reference ``src/persistence/operator_snapshot.rs:21-342``) pickles these
    #: at snapshot ticks and restores them on restart, making recovery
    #: O(state) instead of O(history).
    snapshot_attrs: tuple[str, ...] = ()

    def snapshot_state(self) -> dict | None:
        """Operator state for persistence, or None when stateless."""
        if not self.snapshot_attrs:
            return None
        return {a: getattr(self, a) for a in self.snapshot_attrs}

    def restore_state(self, state: dict) -> None:
        for a, v in state.items():
            setattr(self, a, v)

    #: True when this node's state keys live on the worker the shard map says
    #: owns them (keyed-exchange discipline) — an O(moved-state) migration may
    #: then read only the old shards whose ranges overlap the new worker's.
    #: Nodes whose state placement follows something OTHER than key ownership
    #: (e.g. a partitioned source's per-partition slice) set this False and a
    #: migration reads every old shard for them instead.
    migrate_aligned: bool = True

    def migrate_mode(self) -> str | None:
        """How an O(moved-state) rescale may move this node's persisted shard:
        ``"keyed"`` — state is key-addressed; merge overlapping old shards via
        :meth:`migrate_restore`. ``"solo"`` — the node runs serially on global
        worker 0 under every shape, so its single shard restores positionally.
        ``None`` — neither holds; the whole restore must fall back to
        reshard-by-replay."""
        if type(self).migrate_restore is not Node.migrate_restore:
            return "keyed"
        if self.exchange_key(0) == SOLO:
            return "solo"
        return None

    def migrate_restore(self, shards: list[dict], keep) -> dict | None:
        """Merge old per-worker snapshot states into THIS worker's state for an
        O(moved-state) rescale (``PATHWAY_SHARDMAP_MIGRATION``).

        ``shards`` are the ``snapshot_state()`` dicts of every old worker whose
        owned key ranges overlap this worker's new ranges; ``keep`` maps a
        ``uint64`` key array to a boolean mask of keys this worker owns under
        the NEW shard map. Returns a state dict for :meth:`restore_state`, or
        ``None`` when the merged state is empty.

        The default (this method not overridden) means the node does NOT
        support keyed migration — the restore falls back to reshard-by-replay
        for the whole pipeline (``persistence/snapshots.py``)."""
        raise NotImplementedError

    def exchange_key(self, port: int):
        # stateful nodes keyed by row key need co-location by row key; stateless
        # subclasses override with None, specially-keyed ones with their key fn
        return lambda batch: batch.keys

    def __init__(self, n_inputs: int = 1):
        self.n_inputs = n_inputs
        self.node_index: int = -1  # set by EngineGraph
        self._buffers: list[list[DeltaBatch]] = [[] for _ in range(n_inputs)]
        self.stats_rows_in = 0
        self.stats_rows_out = 0
        self.stats_time_ns = 0
        # per-operator probes (reference: Prober / OperatorStats{latency,lag},
        # src/engine/dataflow.rs:678-806, graph.rs:497-527): queue latency =
        # wall time a pending input set waited before this node drained it;
        # last processed logical time feeds the lag computation in monitoring
        self.stats_latency_ms = 0.0  # last drain
        self.stats_latency_ewma_ms = 0.0
        self.stats_last_time = -1
        self._pending_since: int | None = None

    # -- scheduler interface --
    def accept(self, port: int, batch: DeltaBatch) -> None:
        if not batch.is_empty:
            if self._pending_since is None:
                self._pending_since = _time.perf_counter_ns()
            self._buffers[port].append(batch)

    def has_pending(self) -> bool:
        return any(self._buffers)

    def drain(self) -> list[DeltaBatch | None]:
        if self._pending_since is not None:
            lat = (_time.perf_counter_ns() - self._pending_since) / 1e6
            self.stats_latency_ms = lat
            self.stats_latency_ewma_ms = (
                lat
                if self.stats_latency_ewma_ms == 0.0
                else 0.8 * self.stats_latency_ewma_ms + 0.2 * lat
            )
            self._pending_since = None
        out: list[DeltaBatch | None] = []
        for port in range(self.n_inputs):
            out.append(concat_batches(self._buffers[port]))
            self._buffers[port] = []
        for b in out:
            if (
                b is not None
                and b.time is not None
                and b.time != END_OF_STREAM  # the close tick is not a logical time
                and b.time > self.stats_last_time
            ):
                self.stats_last_time = b.time
        return out

    # -- operator interface --
    def poll(self, time: int) -> list[DeltaBatch]:
        """Called at tick start; source nodes emit their pending input here."""
        return []

    def process(self, inputs: list[DeltaBatch | None], time: int) -> list[DeltaBatch]:
        """Consume one round of input batches, return emissions (all at ``time``)."""
        return []

    def on_frontier(self, time: int) -> list[DeltaBatch]:
        """Called when the frontier passes ``time`` (end of tick). May emit."""
        return []

    def on_tick_complete(self, time: int) -> None:
        """Called once per tick AFTER the frontier loop settles — everything
        emitted at ``time`` has been routed. Side effects only (sinks,
        callbacks); emissions are not possible here."""

    def on_end(self) -> None:
        """Stream closed — release resources, fire final callbacks."""


class EngineGraph:
    def __init__(self) -> None:
        self.nodes: list[Node] = []
        # edges[i] = list of (consumer_index, port)
        self.edges: dict[int, list[tuple[int, int]]] = {}

    def add_node(self, node: Node, inputs: list[Node]) -> Node:
        node.node_index = len(self.nodes)
        self.nodes.append(node)
        assert len(inputs) == node.n_inputs, f"{node.name}: wrong input arity"
        for port, src in enumerate(inputs):
            assert src.node_index >= 0 and src.node_index < node.node_index, (
                f"{node.name}: inputs must be added before consumers (topo order)"
            )
            self.edges.setdefault(src.node_index, []).append((node.node_index, port))
        return node


class Scheduler:
    """Drives the engine graph tick by tick.

    r15: the sweep is PLAN-driven (``engine/fusion.py``). Fused chains
    execute as single steps, idle nodes are never visited — routing marks
    the consumer's step dirty, and a sweep drains the dirty set in
    topological order (edges only point forward, so one drain reaches
    quiescence). The tick's poll/frontier/complete loops visit only nodes
    that actually override those hooks."""

    def __init__(self, graph: EngineGraph, transient: bool = False):
        self.graph = graph
        self.current_time = 0
        self.on_tick_done: list[Callable[[int], None]] = []
        # live tracing (observability plane): None when PATHWAY_TRACE=off —
        # the hot loops below pay exactly one is-not-None test per guard
        self.tracer = None
        self._trace_active = False
        self.transient = transient
        # request-scoped tracing (observability/requests.py): the installed
        # plane while a request is in flight this tick, else None — sweep
        # steps pay one is-None test
        self._rp = None
        from pathway_tpu.engine import fusion as _fusion

        # transient = a short-lived inner graph rebuilt per use (iterate's
        # fixed-point runner): chain fusion still applies, but the jitted
        # segment tier is disabled — a fresh jax.jit per rebuild would
        # re-trace its kernel every tick
        self.plan = _fusion.build_plan(graph, exchange_aware=False, transient=transient)
        # dirty step positions; during a sweep, forward marks go straight
        # onto the active heap (all edges point forward, so a marked step is
        # always still ahead of the cursor)
        self._dirty: set[int] = set()
        self._heap: list[int] | None = None

    def _mark(self, pos: int) -> None:
        h = self._heap
        if h is not None:
            heapq.heappush(h, pos)
        else:
            self._dirty.add(pos)

    def _route(self, producer: Node, batches: list[DeltaBatch]) -> bool:
        routed = False
        consumers = self.graph.edges.get(producer.node_index, [])
        plan = self.plan
        for batch in batches:
            if batch is None or batch.is_empty:
                continue
            producer.stats_rows_out += len(batch)
            for ci, port in consumers:
                self.graph.nodes[ci].accept(port, batch)
                if plan is not None:
                    self._mark(plan.pos_of[ci])
                routed = True
        return routed

    def _sweep_legacy(self, time: int) -> bool:
        """The r14 sweep, verbatim: one full topo scan, one node per step.
        Active under ``PATHWAY_FUSE=off`` (plan is None)."""
        any_work = False
        trace = self._trace_active
        rp = self._rp
        aud = _audit.current()
        aud_note = aud is not None and aud.edge_sampled
        for node in self.graph.nodes:
            if not node.has_pending():
                continue
            inputs = node.drain()
            rows_in = sum(len(b) for b in inputs if b is not None)
            node.stats_rows_in += rows_in
            if trace or rp is not None:
                w0 = _time.time_ns()
                dev0 = _device_prof.thread_device_wait_ns() if trace else 0
            t0 = _time.perf_counter_ns()
            out = _run_annotated(node, node.process, inputs, time)
            elapsed_ns = _time.perf_counter_ns() - t0
            node.stats_time_ns += elapsed_ns
            if trace or rp is not None:
                w1 = _time.time_ns()
                if rp is not None and (
                    rows_in
                    or any(b is not None and not b.is_empty for b in out)
                ):
                    # a no-op visit (nothing drained, nothing emitted) touched
                    # no request's rows — don't spend the per-tick ring budget
                    rp.note_stage(time, f"sweep/{node.name}", w0, w1, rows_in)
            if trace:
                dev_ns = _device_prof.thread_device_wait_ns() - dev0
                self.tracer.span(
                    f"sweep/{node.name}",
                    w0,
                    w1,
                    {
                        "pathway.operator.id": node.node_index,
                        "pathway.rows_in": rows_in,
                        "pathway.rows_out": sum(len(b) for b in out if b is not None),
                        "pathway.device_ms": round(dev_ns / 1e6, 3),
                    },
                )
                if dev_ns:
                    _device_prof.stats().note_span_split(
                        f"sweep/{node.name}", max(0, elapsed_ns - dev_ns), dev_ns
                    )
            if aud_note:
                aud.note_edge(node, inputs, out)
            self._route(node, out)
            any_work = True
        return any_work

    def _sweep(self, time: int) -> bool:
        """Drain the dirty steps in topo order; returns True if any step did
        work. Quiescence check is O(1): an empty dirty set."""
        if self.plan is None:
            return self._sweep_legacy(time)
        dirty = self._dirty
        if not dirty:
            return False
        heap = sorted(dirty)
        dirty.clear()
        self._heap = heap
        any_work = False
        trace = self._trace_active
        rp = self._rp
        aud = _audit.current()
        # edge cardinality recording rides the audit plane's deterministic
        # tick sample — unsampled ticks pay only this flag read
        aud_note = aud is not None and aud.edge_sampled
        by_pos = self.plan.by_pos
        last = -1
        try:
            while heap:
                pos = heapq.heappop(heap)
                if pos == last:
                    continue  # duplicate marks collapse (ascending pops)
                last = pos
                step = by_pos[pos]
                chain = step.chain
                if chain is not None:
                    if self._run_chain(chain, time, trace, aud if aud_note else None):
                        any_work = True
                    continue
                node = step.node
                if not node.has_pending():
                    continue
                inputs = node.drain()
                rows_in = sum(len(b) for b in inputs if b is not None)
                node.stats_rows_in += rows_in
                if trace or rp is not None:
                    w0 = _time.time_ns()
                    # host/device split: traced dispatches inside this node
                    # accumulate their block_until_ready wait on sampled ticks
                    dev0 = _device_prof.thread_device_wait_ns() if trace else 0
                t0 = _time.perf_counter_ns()
                out = _run_annotated(node, node.process, inputs, time)
                elapsed_ns = _time.perf_counter_ns() - t0
                node.stats_time_ns += elapsed_ns
                if trace or rp is not None:
                    w1 = _time.time_ns()
                    if rp is not None and (
                        rows_in
                        or any(b is not None and not b.is_empty for b in out)
                    ):
                        # a no-op visit (nothing drained, nothing emitted) touched
                        # no request's rows — don't spend the per-tick ring budget
                        rp.note_stage(time, f"sweep/{node.name}", w0, w1, rows_in)
                if trace:
                    dev_ns = _device_prof.thread_device_wait_ns() - dev0
                    self.tracer.span(
                        f"sweep/{node.name}",
                        w0,
                        w1,
                        {
                            "pathway.operator.id": node.node_index,
                            "pathway.rows_in": rows_in,
                            "pathway.rows_out": sum(
                                len(b) for b in out if b is not None
                            ),
                            "pathway.device_ms": round(dev_ns / 1e6, 3),
                        },
                    )
                    if dev_ns:
                        _device_prof.stats().note_span_split(
                            f"sweep/{node.name}", max(0, elapsed_ns - dev_ns), dev_ns
                        )
                if aud_note:
                    # audit plane: per-edge cardinality/selectivity counters
                    aud.note_edge(node, inputs, out)
                self._route(node, out)
                any_work = True
        finally:
            self._heap = None
        return any_work

    def _run_chain(self, chain, time: int, trace: bool, aud) -> bool:
        """One fused-chain step: drain, hand off member to member, route the
        tail. Span + host/device attribution is per CHAIN — the device wait
        AND any inner traced-jit cold (compile) wall are subtracted from the
        host share so compile seconds stay counted once (r10 discipline)."""
        rp = self._rp
        if trace or rp is not None:
            w0 = _time.time_ns()
            dev0 = _device_prof.thread_device_wait_ns() if trace else 0
            cold0 = _device_prof.thread_cold_s() if trace else 0.0
        t0 = _time.perf_counter_ns()
        tok = _phases.start()
        try:
            out, processed, rows_in, rows_out = chain.execute(time, None, aud)
        finally:
            _phases.stop(tok, "fused")
        if not processed:
            return False
        elapsed_ns = _time.perf_counter_ns() - t0
        chain.tail.stats_time_ns += elapsed_ns
        if rp is not None:
            rp.note_stage(
                time, f"sweep/chain{{{chain.label}}}", w0, _time.time_ns(), rows_in
            )
        if trace:
            dev_ns = _device_prof.thread_device_wait_ns() - dev0
            cold_ns = int((_device_prof.thread_cold_s() - cold0) * 1e9)
            name = f"sweep/chain{{{chain.label}}}"
            attrs = {
                "pathway.operator.id": chain.operator_ids(),
                "pathway.chain.nodes": len(chain.members),
                "pathway.rows_in": rows_in,
                "pathway.rows_out": rows_out,
                "pathway.device_ms": round(dev_ns / 1e6, 3),
            }
            if cold_ns:
                attrs["pathway.compile_ms"] = round(cold_ns / 1e6, 3)
            self.tracer.span(name, w0, _time.time_ns(), attrs)
            if dev_ns:
                _device_prof.stats().note_span_split(
                    name, max(0, elapsed_ns - dev_ns - cold_ns), dev_ns
                )
        self._route(chain.tail, out)
        return True

    def run_tick(self, time: int) -> None:
        """Process everything pending at logical ``time`` to quiescence, then
        advance the frontier past it."""
        self.current_time = time
        # device plane: steps an armed jax.profiler window, stamps the flight
        # recorder's tick ring (two global reads when profiling is off)
        _device_prof.tick_hook(time)
        tracer = self.tracer
        tick_token = tracer.begin_tick(time) if tracer is not None else None
        self._trace_active = tick_token is not None
        # request plane: active for this tick only while a request is in
        # flight (one global read + one flag read); transient inner graphs
        # (iterate bodies) keep their own tick numbering out of the ring
        rp = None if self.transient else _requests.current()
        if rp is not None and (not rp.hot or time == END_OF_STREAM):
            rp = None
        self._rp = rp
        if rp is not None:
            rp.note_tick(time)
        aud = _audit.current()
        if aud is not None:
            aud.begin_tick(time)
        plan = self.plan
        pollers = self.graph.nodes if plan is None else plan.pollers
        for node in pollers:
            polled = _run_annotated(node, node.poll, time)
            if polled:
                # fault plan (flip_diff/drop_retract) corrupts BEFORE the
                # audit monitors observe — the tripwire sees exactly what the
                # engine will
                polled = _faults.corrupt_polled(0, time, polled)
                if aud is not None:
                    aud.observe_input(node, polled, time)
            self._route(node, polled)
        while self._sweep(time):
            pass
        # frontier phase: notify in topo order; emissions re-enter the same
        # tick (only nodes that override on_frontier are visited)
        frontier = self.graph.nodes if plan is None else plan.frontier_nodes
        progressed = True
        while progressed:
            progressed = False
            for node in frontier:
                out = _run_annotated(node, node.on_frontier, time)
                if self._route(node, out):
                    progressed = True
            if progressed:
                while self._sweep(time):
                    pass
        complete = self.graph.nodes if plan is None else plan.tick_complete_nodes
        for node in complete:
            _run_annotated(node, node.on_tick_complete, time)
        for cb in self.on_tick_done:
            cb(time)
        if tick_token is not None:
            self._trace_active = False
            tracer.end_tick(time, tick_token)

    def close(self) -> None:
        """Input exhausted: flush temporal buffers and fire end callbacks."""
        self.run_tick(END_OF_STREAM)
        for node in self.graph.nodes:
            node.on_end()
