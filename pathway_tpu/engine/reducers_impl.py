"""Reducer accumulators for incremental group-by.

Engine counterpart of the reference's ``src/engine/reduce.rs:22-38`` reducer set
(Count, IntSum/FloatSum/ArraySum, Unique, Min/ArgMin, Max/ArgMax, SortedTuple, Tuple,
Any, Stateful, Earliest, Latest), keeping its two styles: **semigroup** reducers
(commutative, retraction = subtraction — ``reduce.rs:40``) update from vectorized
per-batch partial aggregates; **multiset** reducers (``reduce.rs:50``) maintain a
value multiset and re-extract on change.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.errors import ERROR
from pathway_tpu.internals.keys import _canonical_bytes


class ReducerImpl:
    """Per-group accumulator protocol."""

    #: semigroup reducers support vectorized batch partials
    semigroup = False

    def make(self) -> Any:
        raise NotImplementedError

    def update(self, state: Any, values: tuple, diff: int, time: int, seq: int) -> None:
        raise NotImplementedError

    def extract(self, state: Any) -> Any:
        raise NotImplementedError

    # semigroup only: partial over a slice of column arrays, then merge
    def batch_partial(self, cols: list[np.ndarray], diffs: np.ndarray, sl: slice) -> Any:
        raise NotImplementedError

    def merge_partial(self, state: Any, partial: Any) -> Any:
        raise NotImplementedError

    def grouped_partials(
        self,
        cols: list[np.ndarray],
        diffs: np.ndarray,
        order: np.ndarray,
        starts: np.ndarray,
    ) -> Any | None:
        """All-groups partials in one vectorized pass (``order`` sorts rows by
        group, ``starts`` marks group boundaries). Returns an indexable of one
        partial per group, or None to fall back to per-group ``batch_partial``."""
        return None

    #: accumulator representable as a flat numeric array, merged by addition —
    #: lets GroupByNode keep its whole state columnar (no per-group Python)
    columnar = False

    def grouped_partials_np(
        self,
        cols: list[np.ndarray],
        diffs: np.ndarray,
        order: np.ndarray,
        starts: np.ndarray,
    ) -> np.ndarray | None:
        """Columnar variant of ``grouped_partials``: one numeric array with a
        partial per group, or None when this batch's columns can't vectorize
        (object dtype)."""
        return None


class CountReducer(ReducerImpl):
    semigroup = True
    columnar = True

    def make(self):
        return 0

    def update(self, state, values, diff, time, seq):
        return state + diff

    def extract(self, state):
        return state

    def batch_partial(self, cols, diffs, sl):
        return int(diffs[sl].sum())

    def merge_partial(self, state, partial):
        return state + partial

    def grouped_partials(self, cols, diffs, order, starts):
        return np.add.reduceat(diffs[order], starts).tolist()

    def grouped_partials_np(self, cols, diffs, order, starts):
        return np.add.reduceat(diffs[order], starts)


class SumReducer(ReducerImpl):
    semigroup = True
    columnar = True

    def __init__(self, kind: str = "int"):
        self.kind = kind

    def make(self):
        return 0 if self.kind == "int" else 0.0

    def update(self, state, values, diff, time, seq):
        v = values[0]
        if v is ERROR or v is None:
            return state
        return state + diff * v

    def extract(self, state):
        return state

    def batch_partial(self, cols, diffs, sl):
        col = cols[0][sl]
        d = diffs[sl]
        if col.dtype == object:
            total = 0
            for v, dd in zip(col, d):
                if v is not ERROR and v is not None:
                    total += dd * v
            return total
        return (col * d).sum()

    def merge_partial(self, state, partial):
        return state + partial

    def grouped_partials(self, cols, diffs, order, starts):
        col = cols[0]
        if col.dtype == object:
            return None
        weighted = col[order] * diffs[order]
        return np.add.reduceat(weighted, starts).tolist()

    def grouped_partials_np(self, cols, diffs, order, starts):
        col = cols[0]
        if col.dtype == object or col.dtype.kind not in "iufb":
            return None
        weighted = col[order] * diffs[order]
        out = np.add.reduceat(weighted, starts)
        if self.kind == "float" and out.dtype.kind != "f":
            out = out.astype(np.float64)
        return out


class ArraySumReducer(ReducerImpl):
    def make(self):
        return None

    def update(self, state, values, diff, time, seq):
        v = values[0]
        contrib = np.asarray(v) * diff
        return contrib if state is None else state + contrib

    def extract(self, state):
        return state


class _MultisetState:
    __slots__ = ("items", "total")

    def __init__(self):
        # canonical-bytes -> [value, count, first_seq, extra]
        self.items: dict[bytes, list] = {}
        self.total = 0


class MultisetReducer(ReducerImpl):
    """Base for reducers re-extracted from a value multiset."""

    def make(self):
        return _MultisetState()

    def _key_values(self, values: tuple):
        return values

    def update(self, state: _MultisetState, values, diff, time, seq):
        v = self._key_values(values)
        ck = _canonical_bytes(v)
        ent = state.items.get(ck)
        if ent is None:
            ent = [v, 0, (time, seq)]
            state.items[ck] = ent
        ent[1] += diff
        if ent[1] == 0:
            del state.items[ck]
        state.total += diff
        return state


class MinReducer(MultisetReducer):
    def extract(self, state):
        return min(e[0][0] for e in state.items.values())


class MaxReducer(MultisetReducer):
    def extract(self, state):
        return max(e[0][0] for e in state.items.values())


class ArgMinReducer(MultisetReducer):
    """values = (cmp_value, id); ties broken by smallest key for determinism."""

    def extract(self, state):
        return min((e[0][0], e[0][1]) for e in state.items.values())[1]


class ArgMaxReducer(MultisetReducer):
    def extract(self, state):
        best = None
        for e in state.items.values():
            cand = (e[0][0], e[0][1])
            # max by value, min by id on ties
            if best is None or cand[0] > best[0] or (cand[0] == best[0] and cand[1] < best[1]):
                best = cand
        return best[1]


class UniqueReducer(MultisetReducer):
    def extract(self, state):
        if len(state.items) != 1:
            from pathway_tpu.internals.errors import report_error

            return report_error(
                "unique reducer: group holds more than one distinct value"
            )
        return next(iter(state.items.values()))[0][0]


class AnyReducer(MultisetReducer):
    def extract(self, state):
        # deterministic: smallest canonical encoding
        ck = min(state.items.keys())
        return state.items[ck][0][0]


class TupleReducer(MultisetReducer):
    """Collect values; ordered by arrival (time, seq) for stability. With
    ``sort_by`` values are (value, sort_key) pairs ordered by sort_key."""

    def __init__(self, skip_nones: bool = False, with_sort_key: bool = False):
        self.skip_nones = skip_nones
        self.with_sort_key = with_sort_key

    def extract(self, state):
        if self.with_sort_key:
            entries = sorted(state.items.values(), key=lambda e: (e[0][1], e[2]))
        else:
            entries = sorted(state.items.values(), key=lambda e: e[2])
        out = []
        for e in entries:
            v = e[0][0]
            if self.skip_nones and v is None:
                continue
            out.extend([v] * max(e[1], 0))
        return tuple(out)


class SortedTupleReducer(MultisetReducer):
    def __init__(self, skip_nones: bool = False):
        self.skip_nones = skip_nones

    def extract(self, state):
        vals = []
        for e in state.items.values():
            v = e[0][0]
            if self.skip_nones and v is None:
                continue
            vals.extend([v] * max(e[1], 0))
        return tuple(sorted(vals))


class NdarrayReducer(MultisetReducer):
    """values = (value, sort_key); returns np.ndarray sorted by sort_key."""

    def extract(self, state):
        entries = sorted(state.items.values(), key=lambda e: (e[0][1], e[2]))
        vals = []
        for e in entries:
            vals.extend([e[0][0]] * max(e[1], 0))
        return np.asarray(vals)


class EarliestReducer(MultisetReducer):
    def extract(self, state):
        return min(state.items.values(), key=lambda e: e[2])[0][0]


class LatestReducer(MultisetReducer):
    def extract(self, state):
        return max(state.items.values(), key=lambda e: e[2])[0][0]


class StatefulReducer(ReducerImpl):
    """``stateful_single/many`` — append-only fold with a user combine fn
    (reference: ``Reducer::Stateful`` + ``custom_reducers.py``)."""

    def __init__(self, combine_fn: Callable, many: bool = False):
        self.combine_fn = combine_fn
        self.many = many

    def make(self):
        return None

    def update(self, state, values, diff, time, seq):
        if diff < 0:
            raise RuntimeError("stateful reducers don't support retractions")
        if self.many:
            return self.combine_fn(state, [(*values, diff)])
        return self.combine_fn(state, *values)

    def extract(self, state):
        return state


class CustomAccumulatorReducer(ReducerImpl):
    """``pw.reducers.udf_reducer`` over a BaseCustomAccumulator subclass
    (reference: ``internals/custom_reducers.py``)."""

    def __init__(self, acc_cls):
        self.acc_cls = acc_cls

    def make(self):
        return None

    def update(self, state, values, diff, time, seq):
        neutral = self.acc_cls.from_row(list(values))
        if diff > 0:
            return neutral if state is None else state.update(neutral) or state
        if state is None:
            raise RuntimeError("retraction before any accumulation")
        if not hasattr(state, "retract"):
            raise RuntimeError(f"{self.acc_cls.__name__} does not support retractions")
        state.retract(neutral)
        return state

    def extract(self, state):
        return state.compute_result()
