"""Run loop: ticks, autocommit, connector lifecycle.

Role of the reference's ``run_with_new_dataflow_graph`` main loop
(``src/engine/dataflow.rs:6111-6324``): build the engine graph from requested
outputs, then either run one batch tick (static mode) or loop — poll connector
threads, advance the logical time on autocommit ticks (``autocommit_duration_ms``),
drain the dataflow — until every input is exhausted, then flush and close.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Protocol

from pathway_tpu.engine.graph import Scheduler
from pathway_tpu.internals.logical import LogicalNode, build_engine_graph


class TickWakeup:
    """Arrival-driven tick scheduling (the serving plane's latency lever).

    The streaming loops sleep the remainder of the autocommit period between
    ticks, so before r14 a REST query arriving right after a tick waited the
    whole poll interval before the engine even saw it. Connectors call
    :meth:`request` when work arrives: ``delay_s=0`` wakes the loop NOW (a
    full coalesce bucket is waiting), a positive delay bounds how long the
    arrival may coalesce with concurrent requests
    (``PATHWAY_SERVE_COALESCE_MS``) before a tick is forced. The loop's
    :meth:`wait` replaces its fixed sleep — an un-requested wait degrades to
    exactly the old autocommit sleep, so non-serving pipelines are unchanged.
    """

    __slots__ = ("_cond", "_immediate", "_deadline")

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._immediate = False
        #: perf_counter deadline of the earliest delayed request, or None
        self._deadline: float | None = None

    def request(self, delay_s: float = 0.0) -> None:
        """Schedule a tick at most ``delay_s`` seconds from now (0 = now).
        Called from connector/handler threads; never blocks. A delayed
        request landing while the loop is already asleep re-arms the sleep
        with the shorter target (the condition variable wakes it to
        recompute), so the coalesce bound holds regardless of arrival phase."""
        with self._cond:
            if delay_s <= 0.0:
                self._immediate = True
            else:
                deadline = _time.perf_counter() + delay_s
                if self._deadline is not None and deadline >= self._deadline:
                    return  # an earlier wakeup is already armed
                self._deadline = deadline
            self._cond.notify_all()

    def wait(self, timeout: float) -> None:
        """Sleep until ``timeout`` elapses, a pending coalesce deadline
        passes, or an immediate tick is requested — whichever is first. Both
        request states are consumed on return: the tick that follows this
        wait drains every queue, satisfying all requests made before it."""
        end = _time.perf_counter() + timeout
        with self._cond:
            while not self._immediate:
                now = _time.perf_counter()
                target = end if self._deadline is None else min(end, self._deadline)
                remaining = target - now
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            self._immediate = False
            self._deadline = None


class ConnectorDriver(Protocol):
    """A live input source. ``start`` may spawn a thread pushing events into its
    StreamInputNode; ``is_finished`` signals the source is exhausted (bounded
    sources); unbounded sources stay alive until ``stop``."""

    def start(self) -> None: ...

    def is_finished(self) -> bool: ...

    def stop(self) -> None: ...


def check_connector_failures(connectors) -> None:
    """Surface captured connector-thread exceptions in the run loop (the
    reference's ErrorReporter channel → driver abort, SURVEY §5.3)."""
    for d in connectors:
        fail = getattr(d, "failure", None)
        if fail is None:
            continue
        e = fail()
        if e is not None:
            raise RuntimeError(f"input connector failed: {e!r}") from e


class Runtime:
    def __init__(
        self,
        monitoring_level: Any = None,
        autocommit_duration_ms: int | None = 20,
    ):
        self.connectors: list[ConnectorDriver] = []
        self.autocommit_duration_ms = autocommit_duration_ms
        self.monitoring_level = monitoring_level
        self.scheduler: Scheduler | None = None
        self.persistence: Any = None  # set by pathway_tpu.persistence.attach
        self._stop_requested = False
        # arrival-driven tick scheduling: connectors (the REST serving plane)
        # request a wakeup instead of waiting out the autocommit poll
        self.wakeup = TickWakeup()
        #: set once the graph is built: live-connector runs tick repeatedly, so
        #: cross-tick accumulators (microbatch UDF buffers) may hold rows until
        #: their autocommit deadline; static runs have exactly one tick and
        #: must flush at its frontier
        self.streaming = False

    def register_connector(self, driver: ConnectorDriver) -> None:
        self.connectors.append(driver)

    def request_stop(self) -> None:
        self._stop_requested = True

    def run(self, outputs: list[LogicalNode]) -> Scheduler:
        from pathway_tpu import flow as _flow
        from pathway_tpu import observability as _obs
        from pathway_tpu.resilience import faults as _faults

        _faults.install_from_env()
        _obs.install_from_env(self)
        # flow plane before the graph builds: ingest gates attach as the
        # StreamInputNodes are constructed
        _flow.install_from_env(self)
        try:
            return self._run(outputs, _obs.current())
        except BaseException as e:
            # flight recorder post-mortem (device plane): recent ticks +
            # device events dumped to PATHWAY_FLIGHT_DIR before the error
            # propagates (terminate_on_error aborts, dead-peer errors)
            _obs.device.on_run_error(e, self)
            raise
        finally:
            _obs.shutdown()
            # closing the gates wakes producers blocked on credit, so
            # connector threads can exit even after a failed run
            _flow.shutdown()

    def _run(self, outputs: list[LogicalNode], tracer) -> Scheduler:
        from pathway_tpu.resilience import faults as _faults

        ctx = build_engine_graph(outputs, runtime=self)
        self.streaming = bool(self.connectors)
        scheduler = Scheduler(ctx.graph)
        scheduler.tracer = tracer
        self.scheduler = scheduler

        if self.persistence is None and any(
            getattr(node, "delivery_writer", None) is not None
            for _lnode, node in ctx.build_order
        ):
            raise RuntimeError(
                "delivery='exactly_once' sinks need persistence: the ledger "
                "stages output in the persistence backend and publishes at "
                "operator-snapshot recovery points — pass "
                "persistence_config=pw.persistence.Config(..., "
                "persistence_mode='operator_persisting') to pw.run"
            )
        if self.persistence is not None:
            # replay snapshots into input nodes before live reads (reference:
            # rewind to sentinel, then seek, src/connectors/mod.rs:100-105)
            self.persistence.on_graph_built(ctx)
            scheduler.on_tick_done.append(self.persistence.on_tick_done)

        from pathway_tpu import flow as _flow

        plane = _flow.current()
        if plane is not None:
            # after the tick settles: replenish ingest credits, step the AIMD
            # controller, plan the next tick's admission budgets
            scheduler.on_tick_done.append(
                lambda t: plane.on_tick_complete(self, t)
            )

        for driver in self.connectors:
            driver.start()
        # connectors are live and the graph is built: this door may now
        # receive traffic (health plane: starting → ready)
        from pathway_tpu.observability import health as _health

        _health.mark_ready()

        if not self.connectors:
            # static mode: single batch tick
            _faults.on_tick_start(0, 0)
            scheduler.run_tick(0)
            scheduler.close()
            if self.persistence is not None:
                self.persistence.on_close()
            return scheduler

        tick = 0
        period = (self.autocommit_duration_ms or 20) / 1000.0
        all_virtual = all(getattr(d, "virtual", False) for d in self.connectors)
        try:
            while not self._stop_requested:
                t0 = _time.perf_counter()
                if _faults.on_tick_start(0, tick):
                    # drop_poll fault: this tick is skipped entirely — events
                    # keep buffering in the input nodes for the next tick
                    tick += 1
                    _time.sleep(period)
                    continue
                scheduler.run_tick(tick)
                tick += 1
                check_connector_failures(self.connectors)
                if all(d.is_finished() for d in self.connectors):
                    scheduler.run_tick(tick)  # drain any final events
                    break
                if not all_virtual:
                    elapsed = _time.perf_counter() - t0
                    if elapsed < period:
                        self.wakeup.wait(period - elapsed)
        finally:
            # doors answer 503 + Retry-After from here on: drain before the
            # connector stop flushes pending request futures
            _health.mark_draining("shutdown")
            for driver in self.connectors:
                driver.stop()
        # a subject may error and close between the failure check and the
        # all(is_finished) break within one iteration — re-check so the run
        # can't exit cleanly on silently truncated input
        check_connector_failures(self.connectors)
        scheduler.close()
        if self.persistence is not None:
            self.persistence.on_close()
        return scheduler
