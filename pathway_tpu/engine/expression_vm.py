"""Columnar expression evaluator.

The engine counterpart of the reference's interpreted per-row expression VM
(``src/engine/expression.rs``: typed enum variants evaluated row by row). Here every
AST node evaluates over a **whole delta block** at once: numpy ufuncs for numeric
columns, per-row python fallbacks only for object columns and ``pw.apply`` UDFs.
Async applies run batched through an event loop — the microbatch replacement for the
reference's one-boxed-future-per-row dispatch (``src/engine/dataflow.rs:1924-1962``).
"""

from __future__ import annotations

import operator as _op
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.errors import ERROR, report_error
from pathway_tpu.internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    BatchApplyExpression,
    BinOpExpression,
    CastExpression,
    CoalesceExpression,
    ColumnExpression,
    ColumnReference,
    ConstExpression,
    ConvertExpression,
    DeclareTypeExpression,
    FillErrorExpression,
    GetExpression,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    MakeTupleExpression,
    MethodCallExpression,
    PointerExpression,
    ReducerExpression,
    RequireExpression,
    UnOpExpression,
    UnwrapExpression,
)
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import row_keys


class EvalContext:
    """Resolves column references to arrays for one block."""

    def __init__(
        self,
        lookup: Callable[[ColumnReference], np.ndarray],
        n: int,
    ):
        self.lookup = lookup
        self.n = n


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


def _none_mask(arr: np.ndarray) -> np.ndarray:
    kind = arr.dtype.kind
    if kind == "f":
        return np.isnan(arr)
    if kind in ("M", "m"):
        return np.isnat(arr)
    if kind == "O":
        return np.fromiter((_is_missing(v) for v in arr), dtype=bool, count=len(arr))
    return np.zeros(len(arr), dtype=bool)


_BINOPS_NUM = {
    "+": _op.add,
    "-": _op.sub,
    "*": _op.mul,
    "/": np.true_divide,
    "//": np.floor_divide,
    "%": np.mod,
    "**": np.power,
    "@": np.matmul,
    "==": _op.eq,
    "!=": _op.ne,
    "<": _op.lt,
    "<=": _op.le,
    ">": _op.gt,
    ">=": _op.ge,
    "&": _op.and_,
    "|": _op.or_,
    "^": _op.xor,
}

_BINOPS_PY = dict(_BINOPS_NUM)
_BINOPS_PY.update({"/": _op.truediv, "//": _op.floordiv, "%": _op.mod, "**": _op.pow})


def _obj_binop(op: str, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    fn = _BINOPS_PY[op]
    out = np.empty(len(a), dtype=object)
    for i in range(len(a)):
        x, y = a[i], b[i]
        if x is ERROR or y is ERROR:
            out[i] = ERROR
        elif op in ("==", "!="):
            out[i] = fn(x, y)
        elif _is_missing(x) or _is_missing(y):
            out[i] = None
        else:
            try:
                out[i] = fn(x, y)
            except Exception as e:
                out[i] = report_error(f"{op}: {e!r}")
    return out


def eval_expr(expr: ColumnExpression, ctx: EvalContext) -> np.ndarray:
    """Evaluate an expression over a block; returns an array of length ctx.n."""
    n = ctx.n

    if isinstance(expr, ColumnReference):
        return ctx.lookup(expr)

    if isinstance(expr, ConstExpression):
        v = expr.value
        d = dt.dtype_of_value(v)
        npd = d.np_dtype
        if npd == np.dtype(object):
            arr = np.empty(n, dtype=object)
            arr[:] = [v] * n
            return arr
        return np.full(n, v, dtype=npd)

    if isinstance(expr, BinOpExpression):
        a = eval_expr(expr.left, ctx)
        b = eval_expr(expr.right, ctx)
        return _eval_binop(expr.op, a, b)

    if isinstance(expr, UnOpExpression):
        a = eval_expr(expr.operand, ctx)
        if a.dtype == object:
            fn = _op.neg if expr.op == "-" else _op.invert
            return np.array(
                [ERROR if v is ERROR else (None if v is None else fn(v)) for v in a],
                dtype=object,
            )
        if expr.op == "-":
            return -a
        if a.dtype.kind == "b":
            return ~a
        return np.invert(a)

    if isinstance(expr, IsNotNoneExpression):
        return ~_none_mask(eval_expr(expr.operand, ctx))

    if isinstance(expr, IsNoneExpression):
        return _none_mask(eval_expr(expr.operand, ctx))

    if isinstance(expr, IfElseExpression):
        c = eval_expr(expr.if_, ctx)
        t = eval_expr(expr.then, ctx)
        e = eval_expr(expr.else_, ctx)
        if c.dtype == object:
            c = np.array([bool(v) if v is not None and v is not ERROR else False for v in c])
        if t.dtype != e.dtype:
            t = t.astype(object) if t.dtype == object or e.dtype == object else t.astype(np.result_type(t, e))
            e = e.astype(t.dtype)
        return np.where(c, t, e)

    if isinstance(expr, CoalesceExpression):
        out = eval_expr(expr.args[0], ctx)
        mask = _none_mask(out)
        i = 1
        while mask.any() and i < len(expr.args):
            nxt = eval_expr(expr.args[i], ctx)
            if out.dtype != nxt.dtype:
                out = out.astype(object)
                nxt = nxt.astype(object)
            out = np.where(mask, nxt, out)
            mask = _none_mask(out)
            i += 1
        # tighten dtype if fully filled
        if out.dtype == object and not mask.any():
            try:
                tight = np.asarray(list(out))
                if tight.dtype.kind in "ifb":
                    return tight
            except Exception:
                pass
        return out

    if isinstance(expr, RequireExpression):
        val = eval_expr(expr.val, ctx)
        bad = np.zeros(n, dtype=bool)
        for c in expr.conds:
            bad |= _none_mask(eval_expr(c, ctx))
        if bad.any():
            out = val.astype(object)
            out[bad] = None
            return out
        return val

    if isinstance(expr, AsyncApplyExpression):
        return _eval_async_apply(expr, ctx)

    if isinstance(expr, BatchApplyExpression):
        return _eval_batch_apply(expr, ctx)

    if isinstance(expr, ApplyExpression):
        return _eval_apply(expr, ctx)

    if isinstance(expr, CastExpression):
        a = eval_expr(expr.expr, ctx)
        return _cast_array(a, expr.target)

    if isinstance(expr, ConvertExpression):
        a = eval_expr(expr.expr, ctx)
        return _convert_array(a, expr.target, unwrap=expr.unwrap_)

    if isinstance(expr, DeclareTypeExpression):
        return eval_expr(expr.expr, ctx)

    if isinstance(expr, UnwrapExpression):
        a = eval_expr(expr.expr, ctx)
        mask = _none_mask(a)
        if mask.any():
            if a.dtype != object:
                a = a.astype(object)
            a[mask] = ERROR
        return a

    if isinstance(expr, FillErrorExpression):
        a = eval_expr(expr.expr, ctx)
        if a.dtype == object:
            repl = eval_expr(expr.replacement, ctx)
            bad = np.fromiter((v is ERROR for v in a), dtype=bool, count=len(a))
            if bad.any():
                out = a.copy()
                out[bad] = repl[bad]
                return out
        return a

    if isinstance(expr, MakeTupleExpression):
        arrays = [eval_expr(a, ctx) for a in expr.args]
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = tuple(arr[i] for arr in arrays)
        return out

    if isinstance(expr, GetExpression):
        return _eval_get(expr, ctx)

    if isinstance(expr, MethodCallExpression):
        from pathway_tpu.engine.namespaces import call_method

        args = [eval_expr(a, ctx) for a in expr.args]
        return call_method(expr.namespace, expr.name, args)

    if isinstance(expr, PointerExpression):
        if not expr.args:
            # zero-arg pointer = the global-reduce singleton row
            # (``total.ix_ref(context=t)`` after ``t.reduce(...)``)
            from pathway_tpu.engine.operators import GroupByNode

            return np.full(n, GroupByNode.GLOBAL_KEY, dtype=np.uint64)
        cols = [np.asarray(eval_expr(a, ctx)) for a in expr.args]
        salt = 0 if expr.instance is None else hash(expr.instance) & 0xFFFF
        return row_keys(cols, n=n, salt=salt)

    if isinstance(expr, ReducerExpression):
        raise RuntimeError(
            "reducer used outside groupby().reduce(...) — reducers are not row-wise"
        )

    raise NotImplementedError(f"cannot evaluate {type(expr).__name__}")


def _eval_binop(op: str, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if a.dtype == object or b.dtype == object:
        if a.dtype != object:
            a = a.astype(object)
        if b.dtype != object:
            b = b.astype(object)
        return _obj_binop(op, a, b)
    # uint64 pointers: numpy handles ==/!= fine; arithmetic not meaningful
    if op in ("//", "%", "/") and b.dtype.kind in ("i", "u"):
        if (b == 0).any():
            return _obj_binop(op, a.astype(object), b.astype(object))
    if op == "/" and a.dtype.kind in ("i", "u") and b.dtype.kind in ("i", "u"):
        return np.true_divide(a, b)
    if op in ("&", "|", "^") and (a.dtype.kind == "b") != (b.dtype.kind == "b"):
        a = a.astype(np.int64) if a.dtype.kind == "b" else a
        b = b.astype(np.int64) if b.dtype.kind == "b" else b
    try:
        with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
            return _BINOPS_NUM[op](a, b)
    except TypeError:
        return _obj_binop(op, a.astype(object), b.astype(object))


def _eval_apply(expr: ApplyExpression, ctx: EvalContext) -> np.ndarray:
    arrays = [eval_expr(a, ctx) for a in expr.args_]
    kw_names = list(expr.kwargs_.keys())
    kw_arrays = [eval_expr(expr.kwargs_[k], ctx) for k in kw_names]
    out = np.empty(ctx.n, dtype=object)
    fn = expr.fn
    for i in range(ctx.n):
        args = [arr[i] for arr in arrays]
        kwargs = {k: arr[i] for k, arr in zip(kw_names, kw_arrays)}
        if any(v is ERROR for v in args) or any(v is ERROR for v in kwargs.values()):
            out[i] = ERROR
            continue
        if expr.propagate_none and (any(v is None for v in args) or any(v is None for v in kwargs.values())):
            out[i] = None
            continue
        try:
            out[i] = fn(*args, **kwargs)
        except Exception as e:
            out[i] = report_error(f"apply {getattr(fn, '__name__', fn)!s}: {e!r}")
    return _tighten(out, expr.return_type)


def _eval_batch_apply(expr: "BatchApplyExpression", ctx: EvalContext) -> np.ndarray:
    """One call over the whole block: fn(col0_list, col1_list, ...) -> list."""
    arrays = [eval_expr(a, ctx) for a in expr.args_]
    kw_names = list(expr.kwargs_.keys())
    kw_arrays = [eval_expr(expr.kwargs_[k], ctx) for k in kw_names]
    all_arrays = list(arrays) + kw_arrays
    out = np.empty(ctx.n, dtype=object)
    run: list[int] = []
    for i in range(ctx.n):
        if any(a[i] is ERROR for a in all_arrays):
            out[i] = ERROR
        elif expr.propagate_none and any(a[i] is None for a in all_arrays):
            out[i] = None
        else:
            run.append(i)
    idx = np.asarray(run, dtype=np.int64)
    if len(idx):
        args = [[arr[i] for i in idx] for arr in arrays]
        kwargs = {k: [arr[i] for i in idx] for k, arr in zip(kw_names, kw_arrays)}
        try:
            results = expr.fn(*args, **kwargs)
            if len(results) != len(idx):
                raise ValueError(
                    f"batch UDF returned {len(results)} results for {len(idx)} rows"
                )
            for j, i in enumerate(idx):
                out[i] = results[j]
        except Exception:
            # row isolation: retry each row alone so one bad input doesn't error
            # the whole block (matches per-row ApplyExpression semantics; the
            # batch is already on the failing path so the cost is irrelevant)
            for i in idx:
                try:
                    r = expr.fn(
                        *[[arr[i]] for arr in arrays],
                        **{k: [arr[i]] for k, arr in zip(kw_names, kw_arrays)},
                    )
                    out[i] = r[0]
                except Exception as e:
                    out[i] = report_error(
                        f"apply {getattr(expr.fn, '__name__', expr.fn)!s}: {e!r}"
                    )
    return _tighten(out, expr.return_type)


def _eval_async_apply(expr: AsyncApplyExpression, ctx: EvalContext) -> np.ndarray:
    """Batched dispatch of async UDFs: one gather per block."""
    import asyncio

    arrays = [eval_expr(a, ctx) for a in expr.args_]
    kw_names = list(expr.kwargs_.keys())
    kw_arrays = [eval_expr(expr.kwargs_[k], ctx) for k in kw_names]
    fn = expr.fn

    async def run_all():
        async def one(i):
            try:
                return await fn(
                    *[arr[i] for arr in arrays],
                    **{k: arr[i] for k, arr in zip(kw_names, kw_arrays)},
                )
            except Exception as e:
                return report_error(
                    f"async apply {getattr(fn, '__name__', fn)!s}: {e!r}"
                )

        return await asyncio.gather(*[one(i) for i in range(ctx.n)])

    results = _run_coro(run_all())
    out = np.empty(ctx.n, dtype=object)
    out[:] = results
    return _tighten(out, expr.return_type)


def _run_coro(coro):
    import asyncio

    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    # already inside a loop (rest_connector handlers) — run in a helper thread
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        return pool.submit(asyncio.run, coro).result()


def _tighten(out: np.ndarray, return_type: dt.DType) -> np.ndarray:
    npd = return_type.np_dtype
    if npd != np.dtype(object):
        try:
            if not any(v is ERROR or v is None for v in out):
                return out.astype(npd)
        except Exception:
            pass
    return out


def _cast_array(a: np.ndarray, target: dt.DType) -> np.ndarray:
    npd = target.np_dtype
    if a.dtype == object:
        conv = {dt.INT: int, dt.FLOAT: float, dt.BOOL: bool, dt.STR: str}.get(
            dt.unoptionalize(target)
        )
        if conv is None:
            return a
        out = np.empty(len(a), dtype=object)
        for i, v in enumerate(a):
            if v is None or v is ERROR:
                out[i] = v
            else:
                try:
                    out[i] = conv(v)
                except (ValueError, TypeError) as e:
                    out[i] = report_error(f"cast to {target}: {e!r}")
        return _tighten(out, target)
    if npd == np.dtype(object):
        if dt.unoptionalize(target) == dt.STR:
            return np.array([str(v) for v in a], dtype=object)
        return a.astype(object)
    if a.dtype.kind == "f" and npd.kind == "i":
        return np.trunc(a).astype(npd)  # cast float→int truncates toward zero
    return a.astype(npd)


def _convert_array(a: np.ndarray, target: dt.DType, unwrap: bool) -> np.ndarray:
    """Json/Any → typed conversion (``as_int``/``as_float``/…)."""
    t = dt.unoptionalize(target)
    conv = {dt.INT: int, dt.FLOAT: float, dt.BOOL: bool, dt.STR: str}.get(t)
    out = np.empty(len(a), dtype=object)
    for i, v in enumerate(a):
        if isinstance(v, Json):
            v = v.value
        if v is None or v is ERROR:
            out[i] = ERROR if (unwrap and v is None) else v
            continue
        try:
            if conv is str and not isinstance(v, str):
                out[i] = ERROR  # json as_str only converts strings
            else:
                out[i] = conv(v) if conv else v
        except (ValueError, TypeError):
            out[i] = ERROR
    return _tighten(out, target)


def _eval_get(expr: GetExpression, ctx: EvalContext) -> np.ndarray:
    obj = eval_expr(expr.obj, ctx)
    idx = eval_expr(expr.index, ctx)
    default = eval_expr(expr.default, ctx) if expr.default is not None else None
    out = np.empty(ctx.n, dtype=object)
    for i in range(ctx.n):
        o, j = obj[i], idx[i]
        if o is ERROR or j is ERROR:
            out[i] = ERROR
            continue
        try:
            if isinstance(o, Json):
                v = o.value[j]
                out[i] = Json(v) if isinstance(v, (dict, list)) else v
            else:
                out[i] = o[j]
        except (KeyError, IndexError, TypeError):
            if expr.check_if_exists:
                out[i] = default[i] if default is not None else None
            else:
                out[i] = ERROR
    return out


def compile_rowwise(
    exprs: dict[str, ColumnExpression],
    lookup_factory: Callable[["Any"], Callable[[ColumnReference], np.ndarray]],
) -> Callable:
    """Compile a dict of named expressions into a block program.

    ``lookup_factory(batch)`` must return a resolver for column references.
    """

    def program(batch) -> dict[str, np.ndarray]:
        ctx = EvalContext(lookup_factory(batch), len(batch))
        return {name: np.asarray(eval_expr(e, ctx)) for name, e in exprs.items()}

    return program
