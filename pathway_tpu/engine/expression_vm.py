"""Columnar expression evaluator.

The engine counterpart of the reference's interpreted per-row expression VM
(``src/engine/expression.rs``: typed enum variants evaluated row by row). Here every
AST node evaluates over a **whole delta block** at once: numpy ufuncs for numeric
columns, per-row python fallbacks only for object columns and ``pw.apply`` UDFs.
Async applies run batched through an event loop — the microbatch replacement for the
reference's one-boxed-future-per-row dispatch (``src/engine/dataflow.rs:1924-1962``).
"""

from __future__ import annotations

import operator as _op
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.errors import ERROR, report_error
from pathway_tpu.internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    BatchApplyExpression,
    BinOpExpression,
    CastExpression,
    CoalesceExpression,
    ColumnExpression,
    ColumnReference,
    ConstExpression,
    ConvertExpression,
    DeclareTypeExpression,
    FillErrorExpression,
    GetExpression,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    MakeTupleExpression,
    MethodCallExpression,
    PointerExpression,
    ReducerExpression,
    RequireExpression,
    UnOpExpression,
    UnwrapExpression,
)
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import row_keys


class EvalContext:
    """Resolves column references to arrays for one block."""

    def __init__(
        self,
        lookup: Callable[[ColumnReference], np.ndarray],
        n: int,
    ):
        self.lookup = lookup
        self.n = n


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


def _none_mask(arr: np.ndarray) -> np.ndarray:
    kind = arr.dtype.kind
    if kind == "f":
        return np.isnan(arr)
    if kind in ("M", "m"):
        return np.isnat(arr)
    if kind == "O":
        return np.fromiter((_is_missing(v) for v in arr), dtype=bool, count=len(arr))
    return np.zeros(len(arr), dtype=bool)


_BINOPS_NUM = {
    "+": _op.add,
    "-": _op.sub,
    "*": _op.mul,
    "/": np.true_divide,
    "//": np.floor_divide,
    "%": np.mod,
    "**": np.power,
    "@": np.matmul,
    "==": _op.eq,
    "!=": _op.ne,
    "<": _op.lt,
    "<=": _op.le,
    ">": _op.gt,
    ">=": _op.ge,
    "&": _op.and_,
    "|": _op.or_,
    "^": _op.xor,
}

_BINOPS_PY = dict(_BINOPS_NUM)
_BINOPS_PY.update({"/": _op.truediv, "//": _op.floordiv, "%": _op.mod, "**": _op.pow})


def _obj_binop(op: str, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    fn = _BINOPS_PY[op]
    out = np.empty(len(a), dtype=object)
    for i in range(len(a)):
        x, y = a[i], b[i]
        if x is ERROR or y is ERROR:
            out[i] = ERROR
        elif op in ("==", "!="):
            out[i] = fn(x, y)
        elif _is_missing(x) or _is_missing(y):
            out[i] = None
        else:
            try:
                out[i] = fn(x, y)
            except Exception as e:
                out[i] = report_error(f"{op}: {e!r}")
    return out


def eval_expr(expr: ColumnExpression, ctx: EvalContext) -> np.ndarray:
    """Evaluate an expression over a block; returns an array of length ctx.n."""
    n = ctx.n

    if isinstance(expr, ColumnReference):
        return ctx.lookup(expr)

    if isinstance(expr, ConstExpression):
        v = expr.value
        d = dt.dtype_of_value(v)
        npd = d.np_dtype
        if npd == np.dtype(object):
            arr = np.empty(n, dtype=object)
            arr[:] = [v] * n
            return arr
        return np.full(n, v, dtype=npd)

    if isinstance(expr, BinOpExpression):
        a = eval_expr(expr.left, ctx)
        b = eval_expr(expr.right, ctx)
        return _eval_binop(expr.op, a, b)

    if isinstance(expr, UnOpExpression):
        a = eval_expr(expr.operand, ctx)
        if a.dtype == object:
            fn = _op.neg if expr.op == "-" else _op.invert
            return np.array(
                [ERROR if v is ERROR else (None if v is None else fn(v)) for v in a],
                dtype=object,
            )
        if expr.op == "-":
            return -a
        if a.dtype.kind == "b":
            return ~a
        return np.invert(a)

    if isinstance(expr, IsNotNoneExpression):
        return ~_none_mask(eval_expr(expr.operand, ctx))

    if isinstance(expr, IsNoneExpression):
        return _none_mask(eval_expr(expr.operand, ctx))

    if isinstance(expr, IfElseExpression):
        c = eval_expr(expr.if_, ctx)
        t = eval_expr(expr.then, ctx)
        e = eval_expr(expr.else_, ctx)
        if c.dtype == object:
            c = np.array([bool(v) if v is not None and v is not ERROR else False for v in c])
        if t.dtype != e.dtype:
            t = t.astype(object) if t.dtype == object or e.dtype == object else t.astype(np.result_type(t, e))
            e = e.astype(t.dtype)
        return np.where(c, t, e)

    if isinstance(expr, CoalesceExpression):
        out = eval_expr(expr.args[0], ctx)
        mask = _none_mask(out)
        i = 1
        while mask.any() and i < len(expr.args):
            nxt = eval_expr(expr.args[i], ctx)
            if out.dtype != nxt.dtype:
                out = out.astype(object)
                nxt = nxt.astype(object)
            out = np.where(mask, nxt, out)
            mask = _none_mask(out)
            i += 1
        # tighten dtype if fully filled
        if out.dtype == object and not mask.any():
            try:
                tight = np.asarray(list(out))
                if tight.dtype.kind in "ifb":
                    return tight
            except Exception:
                pass
        return out

    if isinstance(expr, RequireExpression):
        val = eval_expr(expr.val, ctx)
        bad = np.zeros(n, dtype=bool)
        for c in expr.conds:
            bad |= _none_mask(eval_expr(c, ctx))
        if bad.any():
            out = val.astype(object)
            out[bad] = None
            return out
        return val

    if isinstance(expr, AsyncApplyExpression):
        return _eval_async_apply(expr, ctx)

    if isinstance(expr, BatchApplyExpression):
        return _eval_batch_apply(expr, ctx)

    if isinstance(expr, ApplyExpression):
        return _eval_apply(expr, ctx)

    if isinstance(expr, CastExpression):
        a = eval_expr(expr.expr, ctx)
        return _cast_array(a, expr.target)

    if isinstance(expr, ConvertExpression):
        a = eval_expr(expr.expr, ctx)
        return _convert_array(a, expr.target, unwrap=expr.unwrap_)

    if isinstance(expr, DeclareTypeExpression):
        return eval_expr(expr.expr, ctx)

    if isinstance(expr, UnwrapExpression):
        a = eval_expr(expr.expr, ctx)
        mask = _none_mask(a)
        if mask.any():
            if a.dtype != object:
                a = a.astype(object)
            a[mask] = ERROR
        return a

    if isinstance(expr, FillErrorExpression):
        a = eval_expr(expr.expr, ctx)
        if a.dtype == object:
            repl = eval_expr(expr.replacement, ctx)
            bad = np.fromiter((v is ERROR for v in a), dtype=bool, count=len(a))
            if bad.any():
                out = a.copy()
                out[bad] = repl[bad]
                return out
        return a

    if isinstance(expr, MakeTupleExpression):
        arrays = [eval_expr(a, ctx) for a in expr.args]
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = tuple(arr[i] for arr in arrays)
        return out

    if isinstance(expr, GetExpression):
        return _eval_get(expr, ctx)

    if isinstance(expr, MethodCallExpression):
        from pathway_tpu.engine.namespaces import call_method

        args = [eval_expr(a, ctx) for a in expr.args]
        return call_method(expr.namespace, expr.name, args)

    if isinstance(expr, PointerExpression):
        if not expr.args:
            # zero-arg pointer = the global-reduce singleton row
            # (``total.ix_ref(context=t)`` after ``t.reduce(...)``)
            from pathway_tpu.engine.operators import GroupByNode

            return np.full(n, GroupByNode.GLOBAL_KEY, dtype=np.uint64)
        cols = [np.asarray(eval_expr(a, ctx)) for a in expr.args]
        salt = 0 if expr.instance is None else hash(expr.instance) & 0xFFFF
        return row_keys(cols, n=n, salt=salt)

    if isinstance(expr, ReducerExpression):
        raise RuntimeError(
            "reducer used outside groupby().reduce(...) — reducers are not row-wise"
        )

    raise NotImplementedError(f"cannot evaluate {type(expr).__name__}")


def _eval_binop(op: str, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if a.dtype == object or b.dtype == object:
        if a.dtype != object:
            a = a.astype(object)
        if b.dtype != object:
            b = b.astype(object)
        return _obj_binop(op, a, b)
    # uint64 pointers: numpy handles ==/!= fine; arithmetic not meaningful
    if op in ("//", "%", "/") and b.dtype.kind in ("i", "u"):
        if (b == 0).any():
            return _obj_binop(op, a.astype(object), b.astype(object))
    if op == "/" and a.dtype.kind in ("i", "u") and b.dtype.kind in ("i", "u"):
        return np.true_divide(a, b)
    if op in ("&", "|", "^") and (a.dtype.kind == "b") != (b.dtype.kind == "b"):
        a = a.astype(np.int64) if a.dtype.kind == "b" else a
        b = b.astype(np.int64) if b.dtype.kind == "b" else b
    try:
        with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
            return _BINOPS_NUM[op](a, b)
    except TypeError:
        return _obj_binop(op, a.astype(object), b.astype(object))


def _eval_apply(expr: ApplyExpression, ctx: EvalContext) -> np.ndarray:
    arrays = [eval_expr(a, ctx) for a in expr.args_]
    kw_names = list(expr.kwargs_.keys())
    kw_arrays = [eval_expr(expr.kwargs_[k], ctx) for k in kw_names]
    out = np.empty(ctx.n, dtype=object)
    fn = expr.fn
    for i in range(ctx.n):
        args = [arr[i] for arr in arrays]
        kwargs = {k: arr[i] for k, arr in zip(kw_names, kw_arrays)}
        if any(v is ERROR for v in args) or any(v is ERROR for v in kwargs.values()):
            out[i] = ERROR
            continue
        if expr.propagate_none and (any(v is None for v in args) or any(v is None for v in kwargs.values())):
            out[i] = None
            continue
        try:
            out[i] = fn(*args, **kwargs)
        except Exception as e:
            out[i] = report_error(f"apply {getattr(fn, '__name__', fn)!s}: {e!r}")
    return _tighten(out, expr.return_type)


def _eval_batch_apply(expr: "BatchApplyExpression", ctx: EvalContext) -> np.ndarray:
    """One call over the whole block: fn(col0_list, col1_list, ...) -> list."""
    arrays = [eval_expr(a, ctx) for a in expr.args_]
    kw_names = list(expr.kwargs_.keys())
    kw_arrays = [eval_expr(expr.kwargs_[k], ctx) for k in kw_names]
    all_arrays = list(arrays) + kw_arrays
    out = np.empty(ctx.n, dtype=object)
    run: list[int] = []
    for i in range(ctx.n):
        if any(a[i] is ERROR for a in all_arrays):
            out[i] = ERROR
        elif expr.propagate_none and any(a[i] is None for a in all_arrays):
            out[i] = None
        else:
            run.append(i)
    idx = np.asarray(run, dtype=np.int64)
    if len(idx):
        args = [[arr[i] for i in idx] for arr in arrays]
        kwargs = {k: [arr[i] for i in idx] for k, arr in zip(kw_names, kw_arrays)}
        try:
            results = expr.fn(*args, **kwargs)
            if len(results) != len(idx):
                raise ValueError(
                    f"batch UDF returned {len(results)} results for {len(idx)} rows"
                )
            for j, i in enumerate(idx):
                out[i] = results[j]
        except Exception:
            # row isolation: retry each row alone so one bad input doesn't error
            # the whole block (matches per-row ApplyExpression semantics; the
            # batch is already on the failing path so the cost is irrelevant)
            for i in idx:
                try:
                    r = expr.fn(
                        *[[arr[i]] for arr in arrays],
                        **{k: [arr[i]] for k, arr in zip(kw_names, kw_arrays)},
                    )
                    out[i] = r[0]
                except Exception as e:
                    out[i] = report_error(
                        f"apply {getattr(expr.fn, '__name__', expr.fn)!s}: {e!r}"
                    )
    return _tighten(out, expr.return_type)


def _eval_async_apply(expr: AsyncApplyExpression, ctx: EvalContext) -> np.ndarray:
    """Batched dispatch of async UDFs: one gather per block."""
    import asyncio

    arrays = [eval_expr(a, ctx) for a in expr.args_]
    kw_names = list(expr.kwargs_.keys())
    kw_arrays = [eval_expr(expr.kwargs_[k], ctx) for k in kw_names]
    fn = expr.fn

    async def run_all():
        async def one(i):
            try:
                return await fn(
                    *[arr[i] for arr in arrays],
                    **{k: arr[i] for k, arr in zip(kw_names, kw_arrays)},
                )
            except Exception as e:
                return report_error(
                    f"async apply {getattr(fn, '__name__', fn)!s}: {e!r}"
                )

        return await asyncio.gather(*[one(i) for i in range(ctx.n)])

    results = _run_coro(run_all())
    out = np.empty(ctx.n, dtype=object)
    out[:] = results
    return _tighten(out, expr.return_type)


def _run_coro(coro):
    import asyncio

    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    # already inside a loop (rest_connector handlers) — run in a helper thread
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        return pool.submit(asyncio.run, coro).result()


def _tighten(out: np.ndarray, return_type: dt.DType) -> np.ndarray:
    npd = return_type.np_dtype
    if npd != np.dtype(object):
        try:
            if not any(v is ERROR or v is None for v in out):
                return out.astype(npd)
        except Exception:
            pass
    return out


def _cast_array(a: np.ndarray, target: dt.DType) -> np.ndarray:
    npd = target.np_dtype
    if a.dtype == object:
        conv = {dt.INT: int, dt.FLOAT: float, dt.BOOL: bool, dt.STR: str}.get(
            dt.unoptionalize(target)
        )
        if conv is None:
            return a
        out = np.empty(len(a), dtype=object)
        for i, v in enumerate(a):
            if v is None or v is ERROR:
                out[i] = v
            else:
                try:
                    out[i] = conv(v)
                except (ValueError, TypeError) as e:
                    out[i] = report_error(f"cast to {target}: {e!r}")
        return _tighten(out, target)
    if npd == np.dtype(object):
        if dt.unoptionalize(target) == dt.STR:
            return np.array([str(v) for v in a], dtype=object)
        return a.astype(object)
    if a.dtype.kind == "f" and npd.kind == "i":
        return np.trunc(a).astype(npd)  # cast float→int truncates toward zero
    return a.astype(npd)


def _convert_array(a: np.ndarray, target: dt.DType, unwrap: bool) -> np.ndarray:
    """Json/Any → typed conversion (``as_int``/``as_float``/…)."""
    t = dt.unoptionalize(target)
    conv = {dt.INT: int, dt.FLOAT: float, dt.BOOL: bool, dt.STR: str}.get(t)
    out = np.empty(len(a), dtype=object)
    for i, v in enumerate(a):
        if isinstance(v, Json):
            v = v.value
        if v is None or v is ERROR:
            out[i] = ERROR if (unwrap and v is None) else v
            continue
        try:
            if conv is str and not isinstance(v, str):
                out[i] = ERROR  # json as_str only converts strings
            else:
                out[i] = conv(v) if conv else v
        except (ValueError, TypeError):
            out[i] = ERROR
    return _tighten(out, target)


def _eval_get(expr: GetExpression, ctx: EvalContext) -> np.ndarray:
    obj = eval_expr(expr.obj, ctx)
    idx = eval_expr(expr.index, ctx)
    default = eval_expr(expr.default, ctx) if expr.default is not None else None
    out = np.empty(ctx.n, dtype=object)
    for i in range(ctx.n):
        o, j = obj[i], idx[i]
        if o is ERROR or j is ERROR:
            out[i] = ERROR
            continue
        try:
            if isinstance(o, Json):
                v = o.value[j]
                out[i] = Json(v) if isinstance(v, (dict, list)) else v
            else:
                out[i] = o[j]
        except (KeyError, IndexError, TypeError):
            if expr.check_if_exists:
                out[i] = default[i] if default is not None else None
            else:
                out[i] = ERROR
    return out


# ---------------------------------------------------------------- fused tracing
#
# The chain-fusion pass (``engine/fusion.py``) lowers runs of filter/map
# expressions into ONE jitted tick kernel. Only a whitelisted subset lowers:
# every op must be bit-identical between the numpy path above and XLA
# (elementwise IEEE float ops, exact integer ops, comparisons) and must have
# NO value-dependent fallback (integer division routes to the object path on
# a zero divisor, so it can never fuse). ``infer_fused_dtype`` is the static
# eligibility check — it mirrors the dtype flow of ``eval_expr`` and returns
# None the moment an expression leaves the whitelist; ``trace_fused`` is the
# jax-traceable mirror of ``eval_expr`` for exactly that subset.

#: binops that lower: elementwise, value-independent, bit-identical on XLA
_FUSE_CMP = {"==", "!=", "<", "<=", ">", ">="}
_FUSE_ARITH = {"+", "-", "*"}
_FUSE_BITS = {"&", "|", "^"}


def infer_fused_dtype(
    expr: ColumnExpression, dtypes: dict[str, np.dtype]
) -> np.dtype | None:
    """The numpy dtype ``expr`` evaluates to under the fused-kernel
    whitelist given input column dtypes, or None when it cannot lower."""
    if isinstance(expr, ColumnReference):
        if expr.name == "id":
            return np.dtype(np.uint64)
        d = dtypes.get(expr.name)
        return d if d is not None and d.kind in "iufb" else None

    if isinstance(expr, ConstExpression):
        d = dt.dtype_of_value(expr.value).np_dtype
        return d if d.kind in "ifb" else None

    if isinstance(expr, DeclareTypeExpression):
        return infer_fused_dtype(expr.expr, dtypes)

    if isinstance(expr, BinOpExpression):
        a = infer_fused_dtype(expr.left, dtypes)
        b = infer_fused_dtype(expr.right, dtypes)
        if a is None or b is None:
            return None
        op = expr.op
        if op in _FUSE_CMP:
            if a.kind == "b" or b.kind == "b":
                # bool comparisons only against bool, and only for equality
                ok = a.kind == "b" and b.kind == "b" and op in ("==", "!=")
                return np.dtype(bool) if ok else None
            if {"u", "i"} <= {a.kind, b.kind}:
                return None  # numpy promotes u64 vs i64 through float64
            return np.dtype(bool)
        if op in _FUSE_ARITH:
            if a.kind not in "if" or b.kind not in "if":
                return None  # uints / bools take numpy-specific promotions
            return np.result_type(a, b)
        if op in _FUSE_BITS:
            # eval_expr casts a lone bool operand to int64 before the op
            if a.kind == "b" and b.kind == "b":
                return np.dtype(bool)
            aa = np.dtype(np.int64) if a.kind == "b" else a
            bb = np.dtype(np.int64) if b.kind == "b" else b
            if aa.kind not in "iu" or bb.kind not in "iu" or aa.kind != bb.kind:
                return None
            return np.result_type(aa, bb)
        return None

    if isinstance(expr, UnOpExpression):
        a = infer_fused_dtype(expr.operand, dtypes)
        if a is None:
            return None
        if expr.op == "-":
            return a if a.kind in "if" else None
        return a if a.kind in "bi" else None  # ~

    if isinstance(expr, (IsNoneExpression, IsNotNoneExpression)):
        a = infer_fused_dtype(expr.operand, dtypes)
        return np.dtype(bool) if a is not None else None

    if isinstance(expr, IfElseExpression):
        c = infer_fused_dtype(expr.if_, dtypes)
        t = infer_fused_dtype(expr.then, dtypes)
        e = infer_fused_dtype(expr.else_, dtypes)
        if c is None or c.kind != "b" or t is None or t != e:
            return None
        return t

    return None


def compile_fast(
    expr: ColumnExpression, dtypes: dict[str, np.dtype], slots: dict[str, int]
) -> Callable:
    """Compile a whitelisted expression into a flat numpy closure
    ``fn(regs, keys) -> array | numpy scalar`` over a REGISTER list
    (``slots`` maps visible column names to register indices) — the
    byte-identical fast lane of the composed-segment numpy path. Call only
    after :func:`infer_fused_dtype` accepted the expression under
    ``dtypes``.

    Values are identical to :func:`eval_expr`: constants become TYPED numpy
    scalars (numpy treats a typed scalar operand exactly like the full
    const array ``eval_expr`` materializes), ops are the same ufuncs, the
    bool→int64 cast of a mixed bitwise op is baked in at compile time. The
    closure skips the recursion, isinstance dispatch and per-op errstate of
    the generic VM — callers wrap one ``np.errstate`` around the whole
    segment instead."""
    if isinstance(expr, ColumnReference):
        if expr.name == "id":
            return lambda regs, keys: keys
        i = slots[expr.name]
        return lambda regs, keys: regs[i]

    if isinstance(expr, ConstExpression):
        npd = dt.dtype_of_value(expr.value).np_dtype
        const = npd.type(expr.value)
        return lambda regs, keys: const

    if isinstance(expr, DeclareTypeExpression):
        return compile_fast(expr.expr, dtypes, slots)

    if isinstance(expr, BinOpExpression):
        fa = compile_fast(expr.left, dtypes, slots)
        fb = compile_fast(expr.right, dtypes, slots)
        op = expr.op
        if op in _FUSE_BITS:
            da = infer_fused_dtype(expr.left, dtypes)
            db = infer_fused_dtype(expr.right, dtypes)
            if (da.kind == "b") != (db.kind == "b"):
                # eval_expr casts a lone bool operand to int64 first
                if da.kind == "b":
                    fa = _fast_to_i64(fa)
                else:
                    fb = _fast_to_i64(fb)
        fn = _FAST_UFUNCS[op]
        return lambda regs, keys: fn(fa(regs, keys), fb(regs, keys))

    if isinstance(expr, UnOpExpression):
        fa = compile_fast(expr.operand, dtypes, slots)
        fn = np.negative if expr.op == "-" else np.invert
        return lambda regs, keys: fn(fa(regs, keys))

    if isinstance(expr, IsNotNoneExpression):
        fa = compile_fast(expr.operand, dtypes, slots)
        if infer_fused_dtype(expr.operand, dtypes).kind == "f":
            return lambda regs, keys: ~np.isnan(fa(regs, keys))
        return lambda regs, keys: np.ones(len(keys), dtype=bool)

    if isinstance(expr, IsNoneExpression):
        fa = compile_fast(expr.operand, dtypes, slots)
        if infer_fused_dtype(expr.operand, dtypes).kind == "f":
            return lambda regs, keys: np.isnan(fa(regs, keys))
        return lambda regs, keys: np.zeros(len(keys), dtype=bool)

    if isinstance(expr, IfElseExpression):
        fc = compile_fast(expr.if_, dtypes, slots)
        ft = compile_fast(expr.then, dtypes, slots)
        fe = compile_fast(expr.else_, dtypes, slots)
        return lambda regs, keys: np.where(
            fc(regs, keys), ft(regs, keys), fe(regs, keys)
        )

    raise NotImplementedError(
        f"compile_fast: {type(expr).__name__} is outside the fused whitelist"
    )


def _fast_to_i64(f: Callable) -> Callable:
    def g(env, keys):
        v = f(env, keys)
        return v.astype(np.int64) if isinstance(v, np.ndarray) else np.int64(v)

    return g


#: the ufuncs behind _BINOPS_NUM's operators, called directly (operator.gt
#: on arrays dispatches to the same ufunc; naming them skips a bounce)
_FAST_UFUNCS = {
    "+": np.add,
    "-": np.subtract,
    "*": np.multiply,
    "==": np.equal,
    "!=": np.not_equal,
    "<": np.less,
    "<=": np.less_equal,
    ">": np.greater,
    ">=": np.greater_equal,
    "&": np.bitwise_and,
    "|": np.bitwise_or,
    "^": np.bitwise_xor,
}


def trace_fused(expr: ColumnExpression, env: dict[str, Any], keys: Any) -> Any:
    """jax-traceable mirror of :func:`eval_expr` for the fused whitelist.
    ``env`` maps column names to traced arrays; ``keys`` is the traced key
    column (``id`` references). Must be called only after
    :func:`infer_fused_dtype` accepted the expression."""
    import jax.numpy as jnp

    if isinstance(expr, ColumnReference):
        return keys if expr.name == "id" else env[expr.name]

    if isinstance(expr, ConstExpression):
        npd = dt.dtype_of_value(expr.value).np_dtype
        return jnp.full(keys.shape, expr.value, dtype=npd)

    if isinstance(expr, DeclareTypeExpression):
        return trace_fused(expr.expr, env, keys)

    if isinstance(expr, BinOpExpression):
        a = trace_fused(expr.left, env, keys)
        b = trace_fused(expr.right, env, keys)
        op = expr.op
        if op in _FUSE_BITS and (a.dtype.kind == "b") != (b.dtype.kind == "b"):
            a = a.astype(jnp.int64) if a.dtype.kind == "b" else a
            b = b.astype(jnp.int64) if b.dtype.kind == "b" else b
        return _BINOPS_NUM[op](a, b)

    if isinstance(expr, UnOpExpression):
        a = trace_fused(expr.operand, env, keys)
        if expr.op == "-":
            return -a
        return ~a

    if isinstance(expr, IsNotNoneExpression):
        a = trace_fused(expr.operand, env, keys)
        if a.dtype.kind == "f":
            return ~jnp.isnan(a)
        return jnp.ones(a.shape, dtype=bool)

    if isinstance(expr, IsNoneExpression):
        a = trace_fused(expr.operand, env, keys)
        if a.dtype.kind == "f":
            return jnp.isnan(a)
        return jnp.zeros(a.shape, dtype=bool)

    if isinstance(expr, IfElseExpression):
        c = trace_fused(expr.if_, env, keys)
        t = trace_fused(expr.then, env, keys)
        e = trace_fused(expr.else_, env, keys)
        return jnp.where(c, t, e)

    raise NotImplementedError(
        f"trace_fused: {type(expr).__name__} is outside the fused whitelist"
    )


def compile_rowwise(
    exprs: dict[str, ColumnExpression],
    lookup_factory: Callable[["Any"], Callable[[ColumnReference], np.ndarray]],
) -> Callable:
    """Compile a dict of named expressions into a block program.

    ``lookup_factory(batch)`` must return a resolver for column references.
    """

    def program(batch) -> dict[str, np.ndarray]:
        ctx = EvalContext(lookup_factory(batch), len(batch))
        return {name: np.asarray(eval_expr(e, ctx)) for name, e in exprs.items()}

    return program
