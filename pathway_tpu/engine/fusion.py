"""Chain-fusion pass: whole-tick compiled dataflow (ROADMAP #3).

Before r15 every operator in a tick launched separately from Python —
``Scheduler._sweep`` walked nodes one at a time, and at small (64–1k row)
ticks the per-node dispatch (drain / stats / route / accept bookkeeping plus
the O(all nodes) quiescence scans) dominated the tick budget. This module
inverts the execution model: **chains become the unit of dispatch**.

At graph finalization :func:`build_plan` identifies maximal linear operator
chains — runs of nodes where each link is single-producer/single-consumer on
its port, every member uses the scheduler's default ``poll``/``on_frontier``
(no self-scheduled emissions outside ``process``), and, on exchange-aware
runtimes (sharded/cluster), every interior link is exchange-free (the rows
would have stayed on the producing worker anyway). Each chain executes as
**one sweep step**: batches hand off member to member in-process, with no
intermediate ``accept``/``drain``/``_route`` round-trips. A chain step runs
at its *tail's* topological position, which makes the execution order —
and therefore the raw delta stream — byte-identical to the unfused sweep
(all producers of any member have already run when the step fires; interior
links are single-consumer so nothing else can observe the handoff).

Within a chain, consecutive *expression* members (``FilterNode`` /
``RowwiseNode`` / ``SelectColumnsNode`` whose ASTs ride on the node) further
collapse into a :class:`ComposedSegment`: one program over ``(keys, diffs,
columns)`` with no intermediate ``DeltaBatch`` construction, and — for the
whitelisted numeric expression subset (``expression_vm.infer_fused_dtype``)
— one **jitted, buffer-donating tick kernel** (``PATHWAY_FUSE_JAX``):
filters accumulate a lane mask, maps evaluate over the padded block, and a
single XLA launch replaces the member-by-member numpy walk. Inputs are
padded to the power-of-two buckets of ``jax_kernels._bucket`` so the jit
shape set stays closed under row-count churn, and per-chain compile
telemetry rides the r10 ``traced_jit`` machinery under the
``engine.fused_chain/*`` labels.

``PATHWAY_FUSE=off`` restores the one-node-per-step sweep exactly.

The plan also precomputes which nodes actually override ``poll`` /
``on_frontier`` / ``on_tick_complete`` so the tick loops visit only those —
the empty-tick short-circuit: a quiescent graph no longer pays a
run-annotated no-op call per node per phase.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch, concat_batches
from pathway_tpu.engine.graph import END_OF_STREAM, Node
from pathway_tpu.internals.trace import annotate as _annotate
from pathway_tpu.internals.trace import run_annotated as _run_annotated


def _overrides(node: Node, method: str) -> bool:
    """Does this node override ``method`` (class- or instance-level)?"""
    return (
        getattr(type(node), method, None) is not getattr(Node, method)
        or method in node.__dict__
    )


def _chain_member_ok(node: Node, interior: bool = False) -> bool:
    """May this node belong to a fused chain? It must be a processing node
    (not a polled source) whose only emission path is ``process`` — a node
    that emits from ``poll`` or ``on_frontier`` schedules itself outside the
    sweep and must keep its own dispatch slot. A non-HEAD member
    additionally must not override ``accept``: the in-process carry handoff
    bypasses accept entirely, so a node that filters or latches inside it
    (e.g. iterate's port-tag gate) would silently lose that logic."""
    return (
        node.n_inputs >= 1
        and not _overrides(node, "poll")
        and not _overrides(node, "on_frontier")
        and not (interior and _overrides(node, "accept"))
    )


def _composable(node: Node) -> bool:
    """Can this member lower into a ComposedSegment stage (its expression
    AST is attached, or it is a pure column re-pick)?"""
    from pathway_tpu.engine import operators as ops

    if isinstance(node, ops.FilterNode):
        return node.expr is not None
    if isinstance(node, ops.RowwiseNode):
        return node.exprs is not None
    return isinstance(node, ops.SelectColumnsNode)


# --------------------------------------------------------------- composed segment


class ComposedSegment:
    """A run of >=2 consecutive expression members compiled into one block
    program. The numpy path evaluates stage by stage over bare
    ``(keys, diffs, columns)`` — same ``eval_expr`` calls as the member
    nodes, minus the per-member DeltaBatch construction — so values are
    byte-identical to member-by-member execution. The jax path lowers the
    whole segment into a single jitted kernel when every stage is in the
    traceable whitelist and the batch's column dtypes are numeric
    (``expression_vm.infer_fused_dtype``); the whitelist is chosen so XLA
    results are bit-identical to the numpy path (elementwise IEEE ops,
    exact integer ops, no value-dependent fallbacks), and any kernel failure
    falls back to numpy for good."""

    __slots__ = (
        "nodes",
        "stages",
        "label",
        "_kernels",
        "_jax_dead",
        "_jax_cfg",
    )

    def __init__(self, nodes: list[Node]):
        from pathway_tpu.engine import operators as ops

        self.nodes = nodes
        self.stages: list[tuple] = []
        for n in nodes:
            if isinstance(n, ops.FilterNode):
                self.stages.append(("filter", n, n.expr))
            elif isinstance(n, ops.RowwiseNode):
                self.stages.append(("rowwise", n, list(n.exprs.items())))
            else:  # SelectColumnsNode
                self.stages.append(("select", n, n.columns, n.rename))
        self.label = "+".join(n.name for n in nodes)
        # dtype signature -> _CompiledSegment | None (None = ineligible)
        self._kernels: dict[tuple, Any] = {}
        self._jax_dead = False
        self._jax_cfg = None

    # ---------------------------------------------------------------- execute
    def run(self, batch: DeltaBatch, time: int, aud: Any = None) -> DeltaBatch:
        """Execute the segment over one block. ``aud`` non-None = this tick
        is audit-edge-sampled: per-member (keys, diffs) edge recordings are
        emitted exactly as the member-by-member sweep would (the monitors
        read only keys/diffs/len of each edge batch)."""
        if not len(batch):
            return batch
        names = list(batch.data.keys())
        sig = tuple((c, batch.data[c].dtype.char) for c in names)
        ent = self._kernels.get(sig, _MISSING)
        if ent is _MISSING:
            ent = self._compile(names, {c: batch.data[c].dtype for c in names})
            self._kernels[sig] = ent
        if ent is None:
            # outside the whitelist (object columns, UDFs, excluded ops):
            # stage-by-stage eval_expr, still one sweep step
            return self._run_numpy(batch, time, aud)
        if aud is None and self._jax_wanted(len(batch)):
            # audited ticks stay on the host program: the fused kernel's
            # single lane mask cannot attribute per-member edge counts
            kern = ent.jax_kernel(self)
            if kern is not None:
                out = self._run_jax(kern, batch, time)
                if out is not None:
                    return out
        return self._run_fast(ent.fast, batch, time, aud)

    def _jax_wanted(self, n: int) -> bool:
        if self._jax_dead:
            return False
        mode, min_rows, avail = self._jax_mode()
        if mode == "off" or not avail:
            return False
        return mode == "on" or n >= min_rows

    def _jax_mode(self):
        # resolved once per segment per process run-phase: three env reads
        # per tick showed up in the small-tick profile
        mode = self._jax_cfg
        if mode is None:
            from pathway_tpu.engine import jax_kernels
            from pathway_tpu.internals.config import get_pathway_config

            cfg = get_pathway_config()
            mode = self._jax_cfg = (
                cfg.fuse_jax,
                cfg.fuse_jax_min_rows,
                jax_kernels.available(),
            )
        return mode

    def _run_fast(
        self, prog, batch: DeltaBatch, time: int, aud: Any = None
    ) -> DeltaBatch:
        """Flat compiled register program: same ufuncs and values as the
        generic VM, none of its recursion, per-op errstate, per-stage dict
        rebuilds or per-filter compactions. Filters fold into ONE lane mask
        (the jitted kernel's discipline — later stages compute over excluded
        lanes too, safe because the whitelist has no value-dependent failure
        modes) and the block compacts once at the end, over the output
        columns only. Surviving lanes keep their values and order, so the
        result is byte-identical to compact-at-every-filter."""
        from pathway_tpu.engine import operators as ops

        from pathway_tpu.internals import trace as _trace

        keys = batch.keys
        diffs = batch.diffs
        data = batch.data
        n = len(keys)
        regs: list = [data[c] for c in prog.in_names]
        mask: np.ndarray | None = None
        masks: list | None = [] if aud is not None else None
        counts: list[int] = [n]  # survivor count at each filter boundary
        # each instruction carries its owning member node: a raise inside the
        # compiled program (the whitelist should preclude one, but numpy can
        # still fail structurally) must attribute to the MEMBER, not fall
        # through to whatever node label the thread last ran (the
        # run_annotated discipline, same as _run_numpy's per-stage pin)
        prev_node = getattr(_trace._tls, "node", None)
        try:
            with np.errstate(all="ignore"):
                for kind, fns, owner in prog.instrs:
                    _trace._tls.node = owner
                    if kind == 0:  # rowwise batch of expr evaluations
                        for fn in fns:
                            regs.append(fn(regs, keys))
                    else:  # filter: fold into the lane mask
                        m = fns(regs, keys)
                        if not isinstance(m, np.ndarray):
                            m = np.full(n, bool(m))
                        mask = m if mask is None else mask & m
                        counts.append(int(mask.sum()))
                        if masks is not None:
                            masks.append(mask)
        except Exception as e:
            owner = getattr(_trace._tls, "node", None)
            if owner is not None and owner is not prev_node:
                _annotate(e, owner.name, getattr(owner, "user_trace", None))
            raise
        finally:
            _trace._tls.node = prev_node
        if mask is not None:
            idx = np.flatnonzero(mask)
            out = {
                name: (
                    regs[j][idx]
                    if isinstance(regs[j], np.ndarray)
                    else np.full(len(idx), regs[j])
                )
                for name, j in prog.out_pairs
            }
            out_keys = keys[idx]
            out_diffs = diffs[idx]
        else:
            out = {name: _as_col(regs[j], n) for name, j in prog.out_pairs}
            out_keys = keys
            out_diffs = diffs
        # stats: exact per-member counts, reconstructed from the filter
        # boundary survivor counts (the r12 cardinality gauges read these
        # as exact rows — a member behind a 1%-selective filter must not
        # report the whole block as its input)
        ci = 0
        for node in self.nodes:
            node.stats_rows_in += counts[ci]
            if isinstance(node, ops.FilterNode):
                ci += 1
            if node is not self.nodes[-1] and counts[ci]:
                node.stats_rows_out += counts[ci]
        if masks is not None:
            edges = [(keys, diffs)]
            for m in masks:
                i = np.flatnonzero(m)
                edges.append((keys[i], diffs[i]))
            self._note_edges(aud, edges)
        return DeltaBatch(out_keys, out_diffs, out, time)

    def _note_edges(self, aud, edges: list) -> None:
        """Per-member edge recordings for an audit-sampled tick: members
        between two filters all see the post-filter (keys, diffs)."""
        from pathway_tpu.engine import operators as ops

        i = 0
        cur = _EdgeView(*edges[0])
        for st in self.stages:
            node = st[1]
            ins = [cur]
            if isinstance(node, ops.FilterNode):
                i += 1
                cur = _EdgeView(*edges[min(i, len(edges) - 1)])
            aud.note_edge(node, ins, [cur])

    def _run_numpy(
        self, batch: DeltaBatch, time: int, aud: Any = None
    ) -> DeltaBatch:
        from pathway_tpu.engine.expression_vm import EvalContext, eval_expr
        from pathway_tpu.internals import trace as _trace

        keys = batch.keys
        diffs = batch.diffs
        data = batch.data
        n = len(keys)
        prev_node = getattr(_trace._tls, "node", None)
        edges: list | None = [(keys, diffs)] if aud is not None else None
        try:
            for st in self.stages:
                node = st[1]
                # row-level error reports attribute to the member whose
                # stage is executing (the run_annotated discipline)
                _trace._tls.node = node
                node.stats_rows_in += n
                try:
                    if st[0] == "filter":
                        ctx = EvalContext(_make_lookup(data, keys), n)
                        mask = np.asarray(eval_expr(st[2], ctx))
                        if mask.dtype != np.bool_:
                            from pathway_tpu.internals.errors import ERROR

                            mask = np.fromiter(
                                (
                                    v is not None and v is not ERROR and bool(v)
                                    for v in mask
                                ),
                                dtype=bool,
                                count=len(mask),
                            )
                        idx = np.flatnonzero(mask)
                        keys = keys[idx]
                        diffs = diffs[idx]
                        data = {c: a[idx] for c, a in data.items()}
                        n = len(keys)
                        if edges is not None:
                            edges.append((keys, diffs))
                    elif st[0] == "rowwise":
                        ctx = EvalContext(_make_lookup(data, keys), n)
                        data = {
                            name: np.asarray(eval_expr(e, ctx)) for name, e in st[2]
                        }
                    else:  # select
                        _, _, columns, rename = st
                        data = {rename.get(c, c): data[c] for c in columns}
                except Exception as e:
                    _annotate(e, node.name, getattr(node, "user_trace", None))
                    raise
                if n and node is not self.nodes[-1]:
                    # the final stage's emission count is booked by the chain
                    # executor / router, exactly once
                    node.stats_rows_out += n
        finally:
            _trace._tls.node = prev_node
        if edges is not None:
            self._note_edges(aud, edges)
        return DeltaBatch(keys, diffs, data, time)

    # ------------------------------------------------------------ compilation
    def _compile(self, in_names: list[str], dtypes: dict[str, np.dtype]):
        """Check the segment against the fused whitelist under these input
        dtypes; returns a :class:`_CompiledSegment` (flat register program +
        lazily-built jax kernel) or None when any stage leaves the
        whitelist. Selects/renames compile away entirely (a register
        remapping); filters fold into one lane mask applied at the end
        (see _run_fast)."""
        from pathway_tpu.engine.expression_vm import compile_fast, infer_fused_dtype

        cur = dict(dtypes)
        slots = {name: i for i, name in enumerate(in_names)}
        nregs = len(in_names)
        instrs: list[tuple] = []
        for st in self.stages:
            if st[0] == "filter":
                d = infer_fused_dtype(st[2], cur)
                if d is None or d.kind != "b":
                    return None
                instrs.append((1, compile_fast(st[2], cur, slots), st[1]))
            elif st[0] == "rowwise":
                from pathway_tpu.internals.expression import ColumnReference

                nxt_d: dict[str, np.dtype] = {}
                nxt_s: dict[str, int] = {}
                fns: list = []
                for name, e in st[2]:
                    d = infer_fused_dtype(e, cur)
                    if d is None:
                        return None
                    nxt_d[name] = d
                    if isinstance(e, ColumnReference) and e.name != "id":
                        # bare column pass-through (the bulk of every select
                        # and all of rename): alias the existing register —
                        # no instruction, no runtime cost
                        nxt_s[name] = slots[e.name]
                        continue
                    fns.append(compile_fast(e, cur, slots))
                    nxt_s[name] = nregs
                    nregs += 1
                if fns:
                    instrs.append((0, fns, st[1]))
                cur, slots = nxt_d, nxt_s
            else:
                _, _, columns, rename = st
                if any(c not in cur for c in columns):
                    return None
                cur = {rename.get(c, c): cur[c] for c in columns}
                slots = {rename.get(c, c): slots[c] for c in columns}
        prog = _FastProgram(
            list(in_names), instrs, [(name, j) for name, j in slots.items()]
        )
        return _CompiledSegment(prog, list(in_names), list(cur.keys()))


class _FastProgram:
    __slots__ = ("in_names", "instrs", "out_pairs")

    def __init__(self, in_names, instrs, out_pairs):
        self.in_names = in_names
        self.instrs = instrs
        self.out_pairs = out_pairs


class _CompiledSegment:
    """One (segment, input dtype signature) compilation: the flat numpy
    program plus the lazily-built jitted kernel for the same stages."""

    __slots__ = ("fast", "in_names", "out_names", "_jax")

    def __init__(self, fast: list[tuple], in_names: list[str], out_names: list[str]):
        self.fast = fast
        self.in_names = in_names
        self.out_names = out_names
        self._jax: Any = _MISSING

    def jax_kernel(self, seg: "ComposedSegment"):
        if self._jax is not _MISSING:
            return self._jax
        in_names, out_names = self.in_names, self.out_names
        try:
            import jax

            from pathway_tpu.engine.expression_vm import trace_fused
            from pathway_tpu.engine.jax_kernels import _donate_active
            from pathway_tpu.observability import device as _dev_prof

            stages = seg.stages

            def kernel(keys, cols):
                import jax.numpy as jnp

                env = dict(zip(in_names, cols))
                mask = None
                for st in stages:
                    if st[0] == "filter":
                        m = trace_fused(st[2], env, keys)
                        mask = m if mask is None else mask & m
                    elif st[0] == "rowwise":
                        env = {
                            name: trace_fused(e, env, keys) for name, e in st[2]
                        }
                    else:
                        _, _, columns, rename = st
                        env = {rename.get(c, c): env[c] for c in columns}
                    # filtered-out lanes keep computing downstream stages —
                    # the whitelist has no value-dependent failure modes, and
                    # masked lanes are dropped on the host
                if mask is None:
                    mask = jnp.ones(keys.shape, dtype=bool)
                return mask, tuple(env[c] for c in out_names)

            # per-tick blocks are dead after the launch: donate them on
            # accelerator backends so XLA reuses their buffers for outputs
            # (the PATHWAY_ARRANGE_DONATE discipline; CPU ignores donation)
            if _donate_active(None):
                jitted = jax.jit(kernel, donate_argnums=(0, 1))
            else:
                jitted = jax.jit(kernel)
            wrapped = _dev_prof.traced_jit(f"engine.fused_chain/{seg.label}", jitted)
            self._jax = (wrapped, in_names, out_names)
        except Exception:  # pragma: no cover - jax import/trace failure
            self._jax = None
        return self._jax


def _seg_run_jax(self, kern, batch: DeltaBatch, time: int) -> DeltaBatch | None:
    wrapped, in_names, out_names = kern
    from pathway_tpu.engine.jax_kernels import _bucket
    from pathway_tpu import jax_compat

    n = len(batch)
    bs = _bucket(n)
    try:
        keys = batch.keys
        if bs != n:
            keys = np.concatenate(
                [keys, np.zeros(bs - n, dtype=np.uint64)]
            )
        cols = []
        for c in in_names:
            a = batch.data[c]
            if bs != n:
                a = np.concatenate([a, np.zeros(bs - n, dtype=a.dtype)])
            cols.append(a)
        with jax_compat.enable_x64():
            mask, outs = wrapped(keys, tuple(cols))
            mask = np.asarray(mask)[:n]
            outs = [np.asarray(o)[:n] for o in outs]
    except Exception:
        import logging

        logging.getLogger(__name__).warning(
            "fused chain kernel %s failed; falling back to numpy for "
            "this process",
            self.label,
            exc_info=True,
        )
        self._jax_dead = True
        return None
    # stats: the single fused lane mask can't attribute per-member
    # intermediate counts — block-in is booked for every member (the jax
    # tier only engages on large blocks / explicit opt-in; the register
    # program and the unfused sweep keep the r12 gauges exact)
    for node in self.nodes:
        node.stats_rows_in += n
    idx = np.flatnonzero(mask)
    data = {name: o[idx] for name, o in zip(out_names, outs)}
    out = DeltaBatch(batch.keys[idx], batch.diffs[idx], data, time)
    if len(out):
        for node in self.nodes[:-1]:
            node.stats_rows_out += len(out)
    return out


# attached here rather than inline so the jit plumbing (_CompiledSegment)
# reads as one block above
ComposedSegment._run_jax = _seg_run_jax

_MISSING = object()


class _EdgeView:
    """Lightweight (keys, diffs) view handed to the audit plane's edge
    monitors for fused-segment members — ``_EdgeStats.note`` reads exactly
    ``keys``/``diffs``/``len`` of each edge batch."""

    __slots__ = ("keys", "diffs")

    def __init__(self, keys: np.ndarray, diffs: np.ndarray):
        self.keys = keys
        self.diffs = diffs

    def __len__(self) -> int:
        return len(self.keys)


def _as_col(v, n: int) -> np.ndarray:
    """A fast-program result as a column: arrays pass through, a scalar
    (pure-const expression) broadcasts to the block length — the array
    ``eval_expr`` would have built for the same constant."""
    if isinstance(v, np.ndarray):
        return v
    return np.full(n, v)


def _make_lookup(data: dict, keys: np.ndarray) -> Callable:
    def lookup(ref):
        if ref.name == "id":
            return keys
        return data[ref.name]

    return lookup


# -------------------------------------------------------------------- fused chain


class FusedChain:
    """One maximal linear chain, executed as a single sweep step at the
    tail's topological position."""

    __slots__ = ("members", "in_ports", "pos", "label", "units", "tail")

    def __init__(self, members: list[Node], in_ports: dict[int, int]):
        self.members = members
        self.in_ports = in_ports  # node_index -> chain-fed port (heads absent)
        self.tail = members[-1]
        self.pos = self.tail.node_index
        self.label = "+".join(m.name for m in members)
        # units: composable runs collapsed into ComposedSegments (segments
        # serve audit-sampled ticks too — they reconstruct exact per-member
        # edge recordings, see ComposedSegment._note_edges)
        self.units = self._build_units(members)

    def _build_units(self, members: list[Node]) -> list[tuple]:
        units: list[tuple] = []
        run: list[Node] = []

        def flush() -> None:
            if not run:
                return
            if len(run) >= 2:
                units.append(("seg", ComposedSegment(list(run))))
            else:
                units.append(("node", run[0]))
            run.clear()

        for m in members:
            if _composable(m):
                run.append(m)
                continue
            flush()
            units.append(("node", m))
        flush()
        return units

    def operator_ids(self) -> str:
        return "+".join(str(m.node_index) for m in self.members)

    @staticmethod
    def _stamp(node: Node, time: int, lat: float | None) -> None:
        """Monitoring probes for a member fed by in-process hand-off (it
        never drains): advance its last-processed logical time and carry
        the step's measured queue latency, so the /status latency/lag
        fields stay live under fusion."""
        if time is not None and time != END_OF_STREAM and time > node.stats_last_time:
            node.stats_last_time = time
        if lat is not None:
            node.stats_latency_ms = lat
            node.stats_latency_ewma_ms = (
                lat
                if node.stats_latency_ewma_ms == 0.0
                else 0.8 * node.stats_latency_ewma_ms + 0.2 * lat
            )

    def execute(
        self,
        time: int,
        lock: "threading.Lock | None",
        aud: Any,
    ) -> tuple[list[DeltaBatch], bool, int, int]:
        """Run the chain to its tail; returns ``(tail_out, processed,
        rows_in, rows_out)``. ``aud`` non-None = this tick is edge-sampled:
        every unit emits the per-member edge recordings the unfused sweep
        would (node units via ``note_edge`` directly, segments via their
        stage-boundary (keys, diffs) views)."""
        units = self.units
        carry: DeltaBatch | None = None
        processed = False
        rows_in_total = 0
        out: list[DeltaBatch] = []
        last = len(units) - 1
        step_lat: float | None = None
        for ui, unit in enumerate(units):
            kind, payload = unit
            first = payload.nodes[0] if kind == "seg" else payload
            if first.has_pending():
                if lock is None:
                    ins = first.drain()
                else:
                    with lock:
                        ins = first.drain()
                step_lat = first.stats_latency_ms
            else:
                ins = None
            if ins is None and carry is None:
                continue  # quiet here; a later member may still have pending
            processed = True
            if kind == "seg":
                seg: ComposedSegment = payload
                batch_in = ins[0] if ins is not None else None
                if carry is not None:
                    batch_in = (
                        carry
                        if batch_in is None
                        else concat_batches([batch_in, carry])
                    )
                carry = None
                if batch_in is not None and len(batch_in):
                    rows_in_total += len(batch_in)
                    for n_ in seg.nodes if ins is None else seg.nodes[1:]:
                        self._stamp(n_, batch_in.time, step_lat)
                    result = seg.run(batch_in, time, aud)
                    if len(result):
                        carry = result
                        if ui == last:
                            out = [result]
                        else:
                            seg.nodes[-1].stats_rows_out += len(result)
            else:
                node: Node = payload
                if ins is None:
                    ins = [None] * node.n_inputs
                if carry is not None:
                    p = self.in_ports.get(node.node_index, 0)
                    ins[p] = (
                        carry if ins[p] is None else concat_batches([ins[p], carry])
                    )
                    self._stamp(node, carry.time, step_lat)
                    carry = None
                rows_in = sum(len(b) for b in ins if b is not None)
                rows_in_total += rows_in
                node.stats_rows_in += rows_in
                emitted = _run_annotated(node, node.process, ins, time)
                if aud is not None:
                    aud.note_edge(node, ins, emitted)
                emitted = [b for b in emitted if b is not None and not b.is_empty]
                if ui == last:
                    out = emitted
                elif emitted:
                    for b in emitted:
                        node.stats_rows_out += len(b)
                    carry = concat_batches(emitted)
        rows_out = sum(len(b) for b in out)
        return out, processed, rows_in_total, rows_out


# --------------------------------------------------------------------------- plan


class Step:
    __slots__ = ("pos", "node", "chain")

    def __init__(self, pos: int, node: Node | None, chain: FusedChain | None):
        self.pos = pos
        self.node = node
        self.chain = chain


class Plan:
    """Execution plan for one engine graph: sweep steps ordered by position
    (a chain runs at its tail's index), plus the poll/frontier/tick-complete
    visit lists (only nodes that actually override those hooks)."""

    __slots__ = (
        "steps",
        "by_pos",
        "pos_of",
        "pollers",
        "frontier_nodes",
        "tick_complete_nodes",
        "chains",
    )

    def __init__(self, graph) -> None:
        nodes = graph.nodes
        self.pollers = [n for n in nodes if _overrides(n, "poll")]
        self.frontier_nodes = [n for n in nodes if _overrides(n, "on_frontier")]
        self.tick_complete_nodes = [
            n for n in nodes if _overrides(n, "on_tick_complete")
        ]
        self.steps: list[Step] = []
        self.by_pos: dict[int, Step] = {}
        self.pos_of: list[int] = [0] * len(nodes)
        self.chains: list[FusedChain] = []

    def _finish(self, graph, chains: list[FusedChain]) -> None:
        in_chain: dict[int, FusedChain] = {}
        for ch in chains:
            for m in ch.members:
                in_chain[m.node_index] = ch
        for node in graph.nodes:
            ch = in_chain.get(node.node_index)
            if ch is None:
                step = Step(node.node_index, node, None)
                self.steps.append(step)
                self.pos_of[node.node_index] = node.node_index
            else:
                self.pos_of[node.node_index] = ch.pos
                if node is ch.tail:
                    self.steps.append(Step(ch.pos, None, ch))
        self.steps.sort(key=lambda s: s.pos)
        self.by_pos = {s.pos: s for s in self.steps}
        self.chains = chains


def build_plan(graph, exchange_aware: bool, transient: bool = False) -> Plan | None:
    """Compute the sweep plan for ``graph``, or **None** when
    ``PATHWAY_FUSE=off`` — the escape hatch disables the whole r15
    execution model (chains, dirty-step scheduling, hook visit lists) and
    the runtimes fall back to their r14 full-scan loops verbatim.
    ``exchange_aware=True`` (sharded/cluster runtimes) restricts interior
    links to exchange-free consumers — fusing across an exchange would move
    rows off the worker the unfused routing would have placed them on.
    ``transient=True`` (short-lived inner graphs rebuilt per use, e.g.
    iterate's fixed-point body) pins the segments' jax tier off — a fresh
    ``jax.jit`` per rebuild would re-trace per tick."""
    from pathway_tpu.internals.config import get_pathway_config

    if get_pathway_config().fuse != "on":
        return None
    plan = Plan(graph)
    chains: list[FusedChain] = []
    nodes = graph.nodes
    in_count: dict[tuple[int, int], int] = {}
    for pi, cons in graph.edges.items():
        for ci, port in cons:
            key = (ci, port)
            in_count[key] = in_count.get(key, 0) + 1
    assigned = [False] * len(nodes)
    for h in range(len(nodes)):
        if assigned[h] or not _chain_member_ok(nodes[h]):
            continue
        chain = [h]
        ports: dict[int, int] = {}
        cur = h
        while True:
            edges = graph.edges.get(cur, [])
            if len(edges) != 1:
                break
            ci, port = edges[0]
            nxt = nodes[ci]
            if ci <= cur or assigned[ci] or not _chain_member_ok(nxt, interior=True):
                break
            if in_count.get((ci, port), 0) != 1:
                break
            if exchange_aware and nxt.exchange_key(port) is not None:
                break
            chain.append(ci)
            ports[ci] = port
            cur = ci
        if len(chain) >= 2:
            for i in chain:
                assigned[i] = True
            chains.append(FusedChain([nodes[i] for i in chain], ports))
    plan._finish(graph, chains)
    if transient:
        for ch in chains:
            for kind, payload in ch.units:
                if kind == "seg":
                    payload._jax_cfg = ("off", 0, False)
    return plan
