"""Vectorized implementations of the ``.dt`` / ``.str`` / ``.num`` expression
namespaces.

Covers the engine surface of the reference's datetime/duration/string expression
variants (``src/engine/expression.rs``, listed in ``python/pathway/engine.pyi:226-440``)
with columnar kernels: datetime math via numpy datetime64/pandas, string ops via
vectorized object-array ufuncs.
"""

from __future__ import annotations

from typing import Callable

import numpy as np
import pandas as pd

from pathway_tpu.internals import dtype as dt

_REGISTRY: dict[tuple[str, str], Callable] = {}


def register(ns: str, name: str):
    def deco(fn):
        _REGISTRY[(ns, name)] = fn
        return fn

    return deco


def call_method(ns: str, name: str, args: list[np.ndarray]) -> np.ndarray:
    fn = _REGISTRY.get((ns, name))
    if fn is None:
        raise NotImplementedError(f"method {ns}.{name} not implemented")
    return fn(*args)


def method_result_dtype(ns: str, name: str, arg_dtypes: list[dt.DType]) -> dt.DType:
    if ns == "num":
        return arg_dtypes[0]
    if ns == "dt" and name in ("round", "floor"):
        return arg_dtypes[0]
    if ns == "gen" and name == "to_string":
        return dt.STR
    return dt.ANY


def _scalar(arr):
    """Extract the scalar of a broadcast const column."""
    return arr[0] if len(arr) else None


# ---------------------------------------------------------------- dt namespace

_DT_FIELDS = {
    "nanosecond": lambda s: s.dt.nanosecond + s.dt.microsecond * 1000,
    "microsecond": lambda s: s.dt.microsecond,
    "millisecond": lambda s: s.dt.microsecond // 1000,
    "second": lambda s: s.dt.second,
    "minute": lambda s: s.dt.minute,
    "hour": lambda s: s.dt.hour,
    "day": lambda s: s.dt.day,
    "month": lambda s: s.dt.month,
    "year": lambda s: s.dt.year,
    "day_of_week": lambda s: s.dt.dayofweek,
}

for _name, _fn in _DT_FIELDS.items():

    def _make(fn):
        def impl(arr):
            s = pd.Series(arr.astype("datetime64[ns]"))
            return fn(s).to_numpy(dtype=np.int64)

        return impl

    register("dt", _name)(_make(_fn))


@register("dt", "timestamp")
def _dt_timestamp(arr, unit_arr):
    unit = _scalar(unit_arr) or "ns"
    ns = arr.astype("datetime64[ns]").astype(np.int64)
    div = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}[unit]
    if div == 1:
        return ns
    return ns / div


@register("dt", "strftime")
def _dt_strftime(arr, fmt_arr):
    fmt = _scalar(fmt_arr)
    s = pd.Series(arr.astype("datetime64[ns]"))
    return s.dt.strftime(fmt).to_numpy(dtype=object)


@register("dt", "strptime")
def _dt_strptime(arr, fmt_arr):
    fmt = _scalar(fmt_arr)
    s = pd.to_datetime(pd.Series(arr, dtype=object), format=fmt, utc=False)
    try:
        s = s.dt.tz_convert(None)
    except TypeError:
        pass
    return s.to_numpy(dtype="datetime64[ns]")


@register("dt", "to_utc")
def _dt_to_utc(arr, tz_arr):
    tz = _scalar(tz_arr)
    s = pd.Series(arr.astype("datetime64[ns]")).dt.tz_localize(tz, ambiguous="NaT")
    return s.dt.tz_convert("UTC").dt.tz_localize(None).to_numpy(dtype="datetime64[ns]")


@register("dt", "to_naive_in_timezone")
def _dt_to_naive(arr, tz_arr):
    tz = _scalar(tz_arr)
    s = pd.Series(arr.astype("datetime64[ns]")).dt.tz_localize("UTC").dt.tz_convert(tz)
    return s.dt.tz_localize(None).to_numpy(dtype="datetime64[ns]")


def _dur_ns(arr) -> np.ndarray:
    return arr.astype("timedelta64[ns]").astype(np.int64)


@register("dt", "round")
def _dt_round(arr, dur_arr):
    dur = _scalar(dur_arr)
    dur_ns = int(np.timedelta64(dur).astype("timedelta64[ns]").astype(np.int64))
    if arr.dtype.kind == "M":
        ns = arr.astype("datetime64[ns]").astype(np.int64)
        out = ((ns + dur_ns // 2) // dur_ns) * dur_ns
        return out.astype("datetime64[ns]")
    ns = _dur_ns(arr)
    return (((ns + dur_ns // 2) // dur_ns) * dur_ns).astype("timedelta64[ns]")


@register("dt", "floor")
def _dt_floor(arr, dur_arr):
    dur = _scalar(dur_arr)
    dur_ns = int(np.timedelta64(dur).astype("timedelta64[ns]").astype(np.int64))
    if arr.dtype.kind == "M":
        ns = arr.astype("datetime64[ns]").astype(np.int64)
        return ((ns // dur_ns) * dur_ns).astype("datetime64[ns]")
    ns = _dur_ns(arr)
    return ((ns // dur_ns) * dur_ns).astype("timedelta64[ns]")


_DUR_DIVS = {
    "nanoseconds": 1,
    "microseconds": 1_000,
    "milliseconds": 1_000_000,
    "seconds": 1_000_000_000,
    "minutes": 60 * 1_000_000_000,
    "hours": 3600 * 1_000_000_000,
    "days": 86400 * 1_000_000_000,
    "weeks": 7 * 86400 * 1_000_000_000,
}

for _name, _div in _DUR_DIVS.items():

    def _make_dur(div):
        def impl(arr):
            return _dur_ns(arr) // div

        return impl

    register("dt", _name)(_make_dur(_div))


@register("dt", "from_timestamp")
def _dt_from_timestamp(arr, unit_arr):
    unit = _scalar(unit_arr)
    mul = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}[unit]
    vals = np.asarray(arr, dtype=np.float64) * mul
    return vals.astype(np.int64).astype("datetime64[ns]")


register("dt", "utc_from_timestamp")(_REGISTRY[("dt", "from_timestamp")])


# --------------------------------------------------------------- str namespace


def _obj_map(fn, *arrays):
    out = np.empty(len(arrays[0]), dtype=object)
    for i, row in enumerate(zip(*arrays)):
        out[i] = fn(*row)
    return out


def _str_method(name: str):
    def impl(arr, *extras):
        def fn(v, *ex):
            if v is None:
                return None
            return getattr(v, name)(*ex)

        return _obj_map(fn, arr, *extras)

    return impl


def _str_strip_method(name: str):
    def impl(arr, chars):
        def fn(v, c):
            if v is None:
                return None
            return getattr(v, name)(c)  # chars=None strips whitespace

        return _obj_map(fn, arr, chars)

    return impl


register("str", "lower")(_str_method("lower"))
register("str", "upper")(_str_method("upper"))
register("str", "title")(_str_method("title"))
register("str", "swapcase")(_str_method("swapcase"))
register("str", "strip")(_str_strip_method("strip"))
register("str", "lstrip")(_str_strip_method("lstrip"))
register("str", "rstrip")(_str_strip_method("rstrip"))


@register("str", "len")
def _str_len(arr):
    return np.fromiter((len(v) if v is not None else -1 for v in arr), dtype=np.int64, count=len(arr))


@register("str", "reversed")
def _str_reversed(arr):
    return _obj_map(lambda v: v[::-1] if v is not None else None, arr)


@register("str", "startswith")
def _str_startswith(arr, pre):
    return _obj_map(lambda v, p: v.startswith(p), arr, pre).astype(bool)


@register("str", "endswith")
def _str_endswith(arr, suf):
    return _obj_map(lambda v, s: v.endswith(s), arr, suf).astype(bool)


@register("str", "count")
def _str_count(arr, sub):
    return _obj_map(lambda v, s: v.count(s), arr, sub).astype(np.int64)


@register("str", "find")
def _str_find(arr, sub):
    return _obj_map(lambda v, s: v.find(s), arr, sub).astype(np.int64)


@register("str", "rfind")
def _str_rfind(arr, sub):
    return _obj_map(lambda v, s: v.rfind(s), arr, sub).astype(np.int64)


@register("str", "replace")
def _str_replace(arr, old, new):
    return _obj_map(lambda v, o, n: v.replace(o, n), arr, old, new)


@register("str", "split")
def _str_split(arr, sep, maxsplit):
    return _obj_map(lambda v, s, m: tuple(v.split(s, m)), arr, sep, maxsplit)


@register("str", "slice")
def _str_slice(arr, start, end):
    return _obj_map(lambda v, s, e: v[s:e], arr, start, end)


def _parse_impl(conv, np_dtype):
    def impl(arr, optional_arr):
        optional = bool(_scalar(optional_arr))
        from pathway_tpu.internals.errors import ERROR

        def fn(v):
            try:
                return conv(v)
            except (ValueError, TypeError):
                return None if optional else ERROR

        out = _obj_map(fn, arr)
        if not optional and not any(o is ERROR or o is None for o in out):
            return out.astype(np_dtype)
        return out

    return impl


def _parse_bool_scalar(v: str) -> bool:
    lv = v.strip().lower()
    if lv in ("true", "yes", "1", "on", "t", "y"):
        return True
    if lv in ("false", "no", "0", "off", "f", "n"):
        return False
    raise ValueError(f"cannot parse {v!r} as bool")


register("str", "parse_int")(_parse_impl(int, np.int64))
register("str", "parse_float")(_parse_impl(float, np.float64))
register("str", "parse_bool")(_parse_impl(_parse_bool_scalar, np.bool_))


# --------------------------------------------------------------- num namespace


@register("num", "abs")
def _num_abs(arr):
    return np.abs(arr)


@register("num", "round")
def _num_round(arr, dec):
    d = _scalar(dec)
    return np.round(arr, int(d) if d is not None else 0)


@register("num", "fill_na")
def _num_fill_na(arr, default):
    d = _scalar(default)
    if arr.dtype.kind == "f":
        return np.where(np.isnan(arr), d, arr)
    if arr.dtype == object:
        return _obj_map(lambda v: d if v is None or (isinstance(v, float) and np.isnan(v)) else v, arr)
    return arr


# --------------------------------------------------------------- gen namespace


@register("gen", "to_string")
def _gen_to_string(arr):
    from pathway_tpu.internals.json import Json

    def fn(v):
        if v is None:
            return "None"
        if isinstance(v, Json):
            return str(v)
        if isinstance(v, (np.bool_, bool)):
            return "True" if v else "False"
        return str(v)

    return _obj_map(fn, arr)
