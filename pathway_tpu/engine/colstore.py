"""Columnar incremental-operator state.

The reference keeps operator state in differential arrangements — sorted
(key, value, time, diff) tries maintained by merge batching
(``external/differential-dataflow/src/trace``). The block engine's analogue is a
**sorted-segment columnar multimap**: state lives in numpy arrays (LSM-style
segments with tombstones, compacted on churn), so every delta block — not just
the first load — is applied with searchsorted/repeat-expansion vectorized
kernels instead of per-row dict updates. Segments are sorted *lazily*: an
insert only parks the arrays; a probe against a still-unsorted segment sorts
the (usually much smaller) query side instead, and a segment is sorted in
place only once it keeps being probed. This keeps the incremental path within
a constant factor of the static path (VERDICT r2 #6).
"""

from __future__ import annotations

import numpy as np

from pathway_tpu.engine import jax_kernels
from pathway_tpu.engine.blocks import (
    concat_cols,
    group_starts,
    interleave_positions,
    scatter_cols,
)
from pathway_tpu.observability import engine_phases as _phases


class _Segment:
    __slots__ = ("jk", "rk", "cols", "dead", "n_dead", "sorted", "probes")

    def __init__(
        self, jk: np.ndarray, rk: np.ndarray, cols: list[np.ndarray], is_sorted: bool
    ):
        self.jk = jk
        self.rk = rk
        self.cols = cols
        self.dead: np.ndarray | None = None  # bool mask, lazily allocated
        self.n_dead = 0
        self.sorted = is_sorted
        self.probes = 0

    def __len__(self) -> int:
        return len(self.jk)

    @property
    def n_live(self) -> int:
        return len(self.jk) - self.n_dead

    def sort(self) -> None:
        tok = _phases.start()
        order = np.argsort(self.jk, kind="stable")
        self.jk = self.jk[order]
        self.rk = self.rk[order]
        self.cols = [c[order] for c in self.cols]
        if self.dead is not None:
            self.dead = self.dead[order]
        self.sorted = True
        _phases.stop(tok, "rehash")


def _merge_sorted_segments(a: "_Segment", b: "_Segment", n_cols: int) -> "_Segment":
    """Interleave two sorted segments by searchsorted positions (no argsort).
    Equal join keys keep part order: ``a``'s rows precede ``b``'s — the same
    tie discipline a stable argsort over their concatenation would give."""
    na, nb = len(a), len(b)
    ia, ib = interleave_positions(a.jk, b.jk)
    total = na + nb
    jk = np.empty(total, dtype=np.uint64)
    jk[ia] = a.jk
    jk[ib] = b.jk
    rk = np.empty(total, dtype=np.uint64)
    rk[ia] = a.rk
    rk[ib] = b.rk
    positions = [ia, ib]
    cols = [
        scatter_cols([a.cols[i], b.cols[i]], positions, total) for i in range(n_cols)
    ]
    return _Segment(jk, rk, cols, is_sorted=True)


def _expand_ranges(lo: np.ndarray, cnt: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(probe_idx, offset) pairs for searchsorted range hits: probe ``i``
    expands to offsets ``lo[i] .. lo[i]+cnt[i]``."""
    total = int(cnt.sum())
    probe_idx = np.repeat(np.arange(len(cnt)), cnt)
    csum = np.cumsum(cnt) - cnt
    ofs = np.repeat(lo, cnt) + np.arange(total) - np.repeat(csum, cnt)
    return probe_idx, ofs


class ColumnarMultimap:
    """Multimap join-key → rows, vectorized for whole-block probe/insert/delete.

    Rows are (jk, rk, col-values...) with rk unique across the map. Inserts
    append a segment; deletes set tombstones; probes run
    searchsorted + repeat-expansion over every segment (sorting whichever of
    segment/query is cheaper). Compaction merges segments once they multiply
    or tombstones dominate.
    """

    MAX_SEGMENTS = 12
    # insert-time backstop: an arrangement that is never probed or deleted
    # (a one-sided-quiet join store) must still not fragment without bound
    MAX_SEGMENTS_HARD = 64
    # segments at most this size are sorted eagerly on first probe
    SMALL_SEGMENT = 4096

    def __init__(self, n_cols: int):
        self.n_cols = n_cols
        self.segments: list[_Segment] = []
        self.n_live = 0

    def __len__(self) -> int:
        return self.n_live

    # ------------------------------------------------------------------ writes

    def insert(self, jk: np.ndarray, rk: np.ndarray, cols: list[np.ndarray]) -> None:
        if not len(jk):
            return
        seg = _Segment(jk, rk, list(cols), is_sorted=False)
        self.segments.append(seg)
        self.n_live += len(seg)
        # segment-count compaction normally triggers on the next probe/delete
        # (see match/delete) — an arrangement that only ever absorbs inserts
        # (an insert-mostly join side whose opposite side went quiet) pays
        # nothing until something actually reads it. The HARD bound is the
        # memory-fragmentation backstop for exactly that never-read shape.
        if len(self.segments) > self.MAX_SEGMENTS_HARD:
            self._compact()

    def delete(self, jk: np.ndarray, rk: np.ndarray) -> None:
        """Tombstone the rows with the given (jk, rk) pairs (rk decides)."""
        if not len(jk):
            return
        tok = _phases.start()
        try:
            self._delete_impl(jk, rk)
        finally:
            _phases.stop(tok, "rehash")

    def _delete_impl(self, jk: np.ndarray, rk: np.ndarray) -> None:
        if len(self.segments) > self.MAX_SEGMENTS:
            self._compact_impl()
        removed = 0
        d_order: np.ndarray | None = None  # lazy sort of the delete keys
        for seg in self.segments:
            if not seg.n_live:
                continue
            if seg.sorted:
                lo = np.searchsorted(seg.jk, jk, side="left")
                hi = np.searchsorted(seg.jk, jk, side="right")
                q_idx, ofs = _expand_ranges(lo, hi - lo)
            else:
                if d_order is None:
                    d_order = np.argsort(jk, kind="stable")
                    d_sorted = jk[d_order]
                lo = np.searchsorted(d_sorted, seg.jk, side="left")
                hi = np.searchsorted(d_sorted, seg.jk, side="right")
                ofs, into_d = _expand_ranges(lo, hi - lo)
                q_idx = d_order[into_d]
            if not len(ofs):
                continue
            hit = seg.rk[ofs] == rk[q_idx]
            if seg.dead is not None:
                hit &= ~seg.dead[ofs]
            # unique: duplicate delete requests in ONE call match the same
            # still-alive offset twice — counting it twice corrupts
            # n_dead/n_live (rows turn invisible, compaction drops live
            # segments). Dedup keeps the kill-all-matching-copies semantics
            # while counting each physical row once.
            kill = np.unique(ofs[hit])
            if len(kill):
                if seg.dead is None:
                    seg.dead = np.zeros(len(seg), dtype=bool)
                seg.dead[kill] = True
                seg.n_dead += len(kill)
                removed += len(kill)
        self.n_live -= removed
        total_rows = sum(len(s) for s in self.segments)
        if total_rows and total_rows > 2 * self.n_live:
            self._compact()

    def _compact(self) -> None:
        tok = _phases.start()
        try:
            self._compact_impl()
        finally:
            _phases.stop(tok, "rehash")

    def _compact_impl(self) -> None:
        live_parts: list[_Segment] = []
        for seg in self.segments:
            if seg.n_dead == 0:
                live_parts.append(seg)
            elif seg.n_live > 0:
                keep = ~seg.dead
                live_parts.append(
                    _Segment(
                        seg.jk[keep],
                        seg.rk[keep],
                        [c[keep] for c in seg.cols],
                        bool(seg.sorted),
                    )
                )
        if not live_parts:
            self.segments = []
            return
        # O(delta) re-arrangement: the already-sorted base segment(s) are
        # MERGED, not re-sorted — only the fresh (unsorted) churn pays an
        # argsort, at its own size. Runs of consecutive unsorted parts are
        # concat+argsorted together (stable: equal keys keep part order),
        # then the sorted runs fold-merge by searchsorted positions, which
        # also keeps equal keys in part order — byte-identical to the old
        # whole-arrangement stable argsort.
        runs: list[_Segment] = []
        pending: list[_Segment] = []

        def _flush_pending() -> None:
            if not pending:
                return
            if len(pending) == 1:
                part = pending[0]
            else:
                jk = np.concatenate([s.jk for s in pending])
                rk = np.concatenate([s.rk for s in pending])
                cols = [
                    concat_cols([s.cols[i] for s in pending])
                    for i in range(self.n_cols)
                ]
                part = _Segment(jk, rk, cols, is_sorted=False)
            if not part.sorted:
                part.sort()
            runs.append(part)
            pending.clear()

        for part in live_parts:
            if part.sorted:
                _flush_pending()
                runs.append(part)
            else:
                pending.append(part)
        _flush_pending()
        merged = runs[0]
        for nxt in runs[1:]:
            merged = _merge_sorted_segments(merged, nxt, self.n_cols)
        # no-tombstone invariant for the compacted base: live_parts strips
        # dead rows before merging, and merges never introduce tombstones
        assert merged.dead is None
        self.segments = [merged]

    # ------------------------------------------------------------------ probes

    @staticmethod
    def _probe_sorted(seg: _Segment, q_jk: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(lo, count) match ranges of each probe key in a sorted segment —
        the jitted device kernel for big probes, numpy searchsorted otherwise."""
        if jax_kernels.probe_eligible(len(seg), len(q_jk)):
            tok = _phases.start()
            try:
                return jax_kernels.join_probe(seg.jk, q_jk)
            except Exception:  # jax runtime failure → numpy, stop routing
                import logging

                logging.getLogger(__name__).warning(
                    "JAX join-probe kernel failed; falling back to "
                    "numpy and disabling kernel routing for this "
                    "process",
                    exc_info=True,
                )
                jax_kernels.disable()
            finally:
                _phases.stop(tok, "kernel")
        lo = np.searchsorted(seg.jk, q_jk, side="left")
        cnt = np.searchsorted(seg.jk, q_jk, side="right") - lo
        return lo, cnt

    def _empty_match(self) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        return (
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=np.uint64),
            [np.empty(0, dtype=object) for _ in range(self.n_cols)],
        )

    def match(
        self, q_jk: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        """All live rows matching each probe key.

        Returns ``(q_idx, rk, cols)`` where ``q_idx[i]`` is the index into
        ``q_jk`` that row ``i`` matched.
        """
        if not len(q_jk) or not self.segments:
            return self._empty_match()
        tok = _phases.start()
        try:
            return self._match_impl(q_jk)
        finally:
            _phases.stop(tok, "probe")

    def _match_impl(
        self, q_jk: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        if len(self.segments) > self.MAX_SEGMENTS:
            self._compact()
        # fast path — the steady state after compaction: one sorted,
        # tombstone-free segment. Probe and gather directly, no per-segment
        # parts lists and no concat (BASELINE §incremental micro-bench).
        if len(self.segments) == 1:
            seg = self.segments[0]
            if seg.sorted and seg.dead is None and seg.n_live:
                lo, cnt = self._probe_sorted(seg, q_jk)
                q_idx, ofs = _expand_ranges(lo, cnt)
                if not len(ofs):
                    return self._empty_match()
                return (
                    q_idx,
                    seg.rk[ofs],
                    [seg.cols[i][ofs] for i in range(self.n_cols)],
                )
        q_parts: list[np.ndarray] = []
        rk_parts: list[np.ndarray] = []
        col_parts: list[list[np.ndarray]] = [[] for _ in range(self.n_cols)]
        q_order: np.ndarray | None = None  # lazy sort of the probe keys
        for seg in self.segments:
            if not seg.n_live:
                continue
            if not seg.sorted:
                seg.probes += 1
                # a repeatedly-probed or small segment earns its own sort;
                # otherwise sort the (smaller) query side instead
                if seg.probes >= 2 or len(seg) <= max(self.SMALL_SEGMENT, len(q_jk)):
                    seg.sort()
            if seg.sorted:
                lo, cnt = self._probe_sorted(seg, q_jk)
                q_idx, ofs = _expand_ranges(lo, cnt)
            else:
                if q_order is None:
                    q_order = np.argsort(q_jk, kind="stable")
                    q_sorted = q_jk[q_order]
                lo = np.searchsorted(q_sorted, seg.jk, side="left")
                hi = np.searchsorted(q_sorted, seg.jk, side="right")
                ofs, into_q = _expand_ranges(lo, hi - lo)
                q_idx = q_order[into_q]
            if not len(ofs):
                continue
            if seg.dead is not None:
                alive = ~seg.dead[ofs]
                q_idx = q_idx[alive]
                ofs = ofs[alive]
                if not len(ofs):
                    continue
            q_parts.append(q_idx)
            rk_parts.append(seg.rk[ofs])
            for i in range(self.n_cols):
                col_parts[i].append(seg.cols[i][ofs])
        if not q_parts:
            return self._empty_match()
        return (
            np.concatenate(q_parts),
            np.concatenate(rk_parts),
            [concat_cols(parts) for parts in col_parts],
        )

    def iter_live(self):
        """Yield (jk, rk, cols) arrays of live rows, segment by segment
        (snapshot/introspection use)."""
        for seg in self.segments:
            if not seg.n_live:
                continue
            if seg.dead is None:
                yield seg.jk, seg.rk, seg.cols
            else:
                keep = ~seg.dead
                yield seg.jk[keep], seg.rk[keep], [c[keep] for c in seg.cols]


class SortedCounts:
    """Sorted unique-key → int count, with batch add returning 0↔+ transitions
    (drives outer-join padding flips without per-key dict lookups)."""

    def __init__(self) -> None:
        self.keys = np.empty(0, dtype=np.uint64)
        self.counts = np.empty(0, dtype=np.int64)

    def get(self, q: np.ndarray) -> np.ndarray:
        if not len(self.keys):
            return np.zeros(len(q), dtype=np.int64)
        pos = np.searchsorted(self.keys, q).clip(0, len(self.keys) - 1)
        hit = self.keys[pos] == q
        out = np.zeros(len(q), dtype=np.int64)
        out[hit] = self.counts[pos[hit]]
        return out

    def add(
        self, keys: np.ndarray, deltas: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Apply per-row deltas; returns (unique_keys, prev_count, new_count)."""
        order = np.argsort(keys, kind="stable")
        ks = keys[order]
        starts = group_starts(ks)
        uniq = ks[starts]
        delta_sum = np.add.reduceat(deltas[order], starts)
        prev = self.get(uniq)
        new = prev + delta_sum
        # merge updated counts back into the sorted store
        pos = (
            np.searchsorted(self.keys, uniq).clip(0, max(len(self.keys) - 1, 0))
            if len(self.keys)
            else np.zeros(len(uniq), dtype=np.int64)
        )
        hit = (self.keys[pos] == uniq) if len(self.keys) else np.zeros(len(uniq), dtype=bool)
        self.counts[pos[hit]] = new[hit]
        fresh = ~hit
        if fresh.any():
            add_mask = fresh & (new != 0)
            if add_mask.any():
                merged_keys = np.concatenate([self.keys, uniq[add_mask]])
                merged_counts = np.concatenate([self.counts, new[add_mask]])
                o = np.argsort(merged_keys, kind="stable")
                self.keys = merged_keys[o]
                self.counts = merged_counts[o]
        # drop zeroed entries opportunistically when they accumulate
        if len(self.keys) and (self.counts == 0).sum() > len(self.keys) // 2:
            keep = self.counts != 0
            self.keys = self.keys[keep]
            self.counts = self.counts[keep]
        return uniq, prev, new




class ColumnarKeyedStore:
    """Keyed single-row-per-key columnar map over :class:`ColumnarMultimap`
    (jk == rk == the row key): upserts tombstone the previous row, probes
    return presence masks + key-aligned column arrays."""

    def __init__(self, n_cols: int):
        self.mm = ColumnarMultimap(n_cols)

    def __len__(self) -> int:
        return len(self.mm)

    def delete(self, keys: np.ndarray) -> None:
        self.mm.delete(keys, keys)

    def upsert(self, keys: np.ndarray, cols: list[np.ndarray]) -> None:
        self.mm.delete(keys, keys)
        self.mm.insert(keys, keys, cols)

    def get(self, keys: np.ndarray) -> tuple[np.ndarray, list[np.ndarray]]:
        """(present bool[n], aligned object columns with None where absent)."""
        q_idx, _rk, cols = self.mm.match(keys)
        present = np.zeros(len(keys), dtype=bool)
        present[q_idx] = True
        aligned: list[np.ndarray] = []
        for c in cols:
            out = np.empty(len(keys), dtype=object)
            if len(q_idx):
                # list() keeps datetime64 scalars intact in object storage
                out[q_idx] = list(c) if c.dtype.kind in ("M", "m") else c
            aligned.append(out)
        return present, aligned
