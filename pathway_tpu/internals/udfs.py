"""``pw.UDF`` / ``@pw.udf`` and executors.

Mirrors the reference's ``internals/udfs/`` (``UDF``/``udf`` at
``__init__.py:67,273``; executors ``executors.py:95-226`` — Sync, Async with
capacity/timeout/retry, FullyAsync; caches ``caches.py:23-121``; retries
``retries.py``). Async UDFs are batched per delta block and dispatched through one
event-loop gather — the microbatch replacement for the reference's per-row boxed
futures (``src/engine/dataflow.rs:1924``).
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import os
import pickle
import random
import time as _time
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod


# --------------------------------------------------------------------- retries


class RetryStrategy:
    def sleep_durations(self) -> list[float]:
        return []


class NoRetryStrategy(RetryStrategy):
    pass


class ExponentialBackoffRetryStrategy(RetryStrategy):
    def __init__(
        self,
        max_retries: int = 3,
        initial_delay: int = 1000,
        backoff_factor: float = 2.0,
        jitter_ms: int = 300,
    ):
        self.max_retries = max_retries
        self.initial_delay = initial_delay / 1000.0
        self.backoff_factor = backoff_factor
        self.jitter = jitter_ms / 1000.0

    def sleep_durations(self) -> list[float]:
        out = []
        d = self.initial_delay
        for _ in range(self.max_retries):
            out.append(d + random.random() * self.jitter)
            d *= self.backoff_factor
        return out


class FixedDelayRetryStrategy(RetryStrategy):
    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        self.max_retries = max_retries
        self.delay = delay_ms / 1000.0

    def sleep_durations(self) -> list[float]:
        return [self.delay] * self.max_retries


# ---------------------------------------------------------------------- caches


class CacheStrategy:
    def get(self, key: str) -> tuple[bool, Any]:
        return False, None

    def put(self, key: str, value: Any) -> None:
        pass


class InMemoryCache(CacheStrategy):
    def __init__(self) -> None:
        self._data: dict[str, Any] = {}

    def get(self, key: str) -> tuple[bool, Any]:
        if key in self._data:
            return True, self._data[key]
        return False, None

    def put(self, key: str, value: Any) -> None:
        self._data[key] = value


class DiskCache(CacheStrategy):
    def __init__(self, directory: str | None = None):
        from pathway_tpu.internals.config import get_pathway_config

        self.directory = directory or os.path.join(
            get_pathway_config().persistent_storage or ".pathway_cache", "udf_cache"
        )
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, key)

    def get(self, key: str) -> tuple[bool, Any]:
        p = self._path(key)
        if os.path.exists(p):
            with open(p, "rb") as f:
                return True, pickle.load(f)
        return False, None

    def put(self, key: str, value: Any) -> None:
        with open(self._path(key), "wb") as f:
            pickle.dump(value, f)


DefaultCache = DiskCache


def _cache_key(fn_name: str, args: tuple, kwargs: dict) -> str:
    from pathway_tpu.internals.keys import _canonical_bytes

    payload = _canonical_bytes((fn_name, tuple(args), tuple(sorted(kwargs.items()))))
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


# ------------------------------------------------------------------- executors


class Executor:
    def wrap(self, fn: Callable) -> Callable:
        return fn

    is_async = False


class SyncExecutor(Executor):
    pass


class AsyncExecutor(Executor):
    """Capacity / timeout / retry wrapper around an async fn
    (reference ``executors.py:135``)."""

    is_async = True

    def __init__(
        self,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: RetryStrategy | None = None,
    ):
        self.capacity = capacity
        self.timeout = timeout
        self.retry_strategy = retry_strategy

    def wrap(self, fn: Callable) -> Callable:
        sem: asyncio.Semaphore | None = None
        strategy = self.retry_strategy

        @functools.wraps(fn)
        async def wrapped(*args: Any, **kwargs: Any) -> Any:
            nonlocal sem
            if self.capacity is not None and sem is None:
                sem = asyncio.Semaphore(self.capacity)

            async def attempt() -> Any:
                coro = fn(*args, **kwargs)
                if self.timeout is not None:
                    return await asyncio.wait_for(coro, timeout=self.timeout)
                return await coro

            async def with_retries() -> Any:
                delays = strategy.sleep_durations() if strategy else []
                for d in delays:
                    try:
                        return await attempt()
                    except Exception:
                        await asyncio.sleep(d)
                return await attempt()

            if sem is not None:
                async with sem:
                    return await with_retries()
            return await with_retries()

        return wrapped


class FullyAsyncExecutor(AsyncExecutor):
    """Emits Pending immediately; the real value arrives as a later update
    (reference ``executors.py:226``, ``Future`` dtype)."""


def async_executor(
    *,
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: RetryStrategy | None = None,
) -> AsyncExecutor:
    return AsyncExecutor(capacity=capacity, timeout=timeout, retry_strategy=retry_strategy)


def fully_async_executor(**kwargs: Any) -> FullyAsyncExecutor:
    return FullyAsyncExecutor(**kwargs)


# ------------------------------------------------------------------------- UDF


class UDF:
    """Base class for user-defined functions; subclass with ``__wrapped__`` or use
    the ``@pw.udf`` decorator (reference ``internals/udfs/__init__.py:67``)."""

    #: microbatch knobs honored for ``is_batched`` subclasses (see
    #: ``engine.operators.MicrobatchApplyNode``): device launch chunk
    #: (``None`` = the PATHWAY_MICROBATCH_MAX_BATCH default) and the smallest
    #: padded bucket the jitted callee should ever see
    microbatch_max_batch: int | None = None
    microbatch_min_bucket: int = 8

    def __init__(
        self,
        *,
        return_type: Any = None,
        deterministic: bool = False,
        propagate_none: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
        _fn: Callable | None = None,
    ):
        if _fn is not None:
            self._fn = _fn
        elif hasattr(self, "__wrapped__"):
            self._fn = self.__wrapped__  # type: ignore[attr-defined]
        else:
            self._fn = None  # subclass overrides __wrapped__ later
        self._return_type = return_type
        self.deterministic = deterministic
        self.propagate_none = propagate_none
        self.executor = executor or (
            AsyncExecutor()
            if self._fn is not None and asyncio.iscoroutinefunction(self._fn)
            else SyncExecutor()
        )
        self.cache_strategy = cache_strategy
        self._wrapped_cache: Callable | None = None

    # subclasses may define __wrapped__ as a method
    def _resolve_fn(self) -> Callable:
        if self._fn is not None:
            return self._fn
        if hasattr(self, "__wrapped__"):
            return self.__wrapped__  # type: ignore[attr-defined]
        raise TypeError("UDF subclass must define __wrapped__")

    def _callable(self) -> Callable:
        if self._wrapped_cache is not None:
            return self._wrapped_cache
        fn = self._resolve_fn()
        fn = self.executor.wrap(fn)
        if self.cache_strategy is not None:
            fn = _with_cache(fn, self.cache_strategy, asyncio.iscoroutinefunction(fn))
        self._wrapped_cache = fn
        return fn

    @property
    def func(self) -> Callable:
        return self._resolve_fn()

    def _return_dtype(self) -> Any:
        if self._return_type is not None:
            return self._return_type
        return expr_mod._infer_return_type(self._resolve_fn())

    def __call__(self, *args: Any, **kwargs: Any):
        fn = self._callable()
        rt = self._return_dtype()
        if isinstance(self.executor, FullyAsyncExecutor):
            return expr_mod.FullyAsyncApplyExpression(
                fn, rt, args=args, kwargs=kwargs,
                propagate_none=self.propagate_none,
                deterministic=self.deterministic,
            )
        if asyncio.iscoroutinefunction(self._resolve_fn()):
            return expr_mod.AsyncApplyExpression(
                fn, rt, args=args, kwargs=kwargs,
                propagate_none=self.propagate_none,
                deterministic=self.deterministic,
            )
        if getattr(self, "is_batched", False):
            # fn receives whole columns (lists) — TPU model UDFs; dispatched via
            # the cross-tick microbatcher (engine MicrobatchApplyNode) when the
            # call is a top-level select column and PATHWAY_MICROBATCH allows,
            # one jitted call per delta block otherwise; caching/retry wrappers
            # don't apply per row
            e = expr_mod.BatchApplyExpression(
                self._resolve_fn(), rt, args=args, kwargs=kwargs,
                propagate_none=self.propagate_none,
                deterministic=self.deterministic,
            )
            # the microbatch planner reads per-UDF knobs off the expression
            # (microbatch_max_batch / microbatch_min_bucket class attrs)
            e.udf = self
            return e
        return expr_mod.ApplyExpression(
            fn, rt, args=args, kwargs=kwargs,
            propagate_none=self.propagate_none,
            deterministic=self.deterministic,
        )


def _with_cache(fn: Callable, cache: CacheStrategy, is_async: bool) -> Callable:
    name = getattr(fn, "__name__", "udf")
    if is_async:

        @functools.wraps(fn)
        async def cached_async(*args: Any, **kwargs: Any) -> Any:
            key = _cache_key(name, args, kwargs)
            hit, value = cache.get(key)
            if hit:
                return value
            value = await fn(*args, **kwargs)
            cache.put(key, value)
            return value

        return cached_async

    @functools.wraps(fn)
    def cached(*args: Any, **kwargs: Any) -> Any:
        key = _cache_key(name, args, kwargs)
        hit, value = cache.get(key)
        if hit:
            return value
        value = fn(*args, **kwargs)
        cache.put(key, value)
        return value

    return cached


def udf(
    fn: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    deterministic: bool = False,
    propagate_none: bool = False,
    executor: Executor | None = None,
    cache_strategy: CacheStrategy | None = None,
) -> Any:
    """Decorator: ``@pw.udf`` (reference ``internals/udfs/__init__.py:273``)."""

    def make(f: Callable) -> UDF:
        u = UDF(
            return_type=return_type,
            deterministic=deterministic,
            propagate_none=propagate_none,
            executor=executor,
            cache_strategy=cache_strategy,
            _fn=f,
        )
        functools.update_wrapper(u, f, updated=[])
        return u

    if fn is not None:
        return make(fn)
    return make
