"""``pw.sql`` — SQL → Table API translation (reference: ``internals/sql.py`` via
sqlglot). sqlglot is not available in this environment; a minimal translator covers
the common SELECT/WHERE/GROUP BY shapes used in the reference's tests."""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.table import Table

_AGGS = {
    "count": lambda args: reducers.count(),
    "sum": lambda args: reducers.sum(args[0]),
    "min": lambda args: reducers.min(args[0]),
    "max": lambda args: reducers.max(args[0]),
    "avg": lambda args: reducers.avg(args[0]),
}


def sql(query: str, **tables: Table) -> Table:
    try:
        import sqlglot  # noqa: F401

        raise NotImplementedError("sqlglot backend not wired yet")
    except ImportError:
        pass
    return _mini_sql(query, tables)


def _mini_sql(query: str, tables: dict[str, Table]) -> Table:
    q = re.sub(r"\s+", " ", query.strip().rstrip(";"))
    m = re.match(
        r"(?is)select (?P<sel>.*?) from (?P<tab>\w+)"
        r"(?: where (?P<where>.*?))?(?: group by (?P<gb>.*?))?$",
        q,
    )
    if not m:
        raise ValueError(f"unsupported SQL: {query!r}")
    t = tables[m.group("tab")]
    if m.group("where"):
        t = t.filter(_parse_expr(m.group("where"), t))
    sel_items = _split_commas(m.group("sel"))
    if m.group("gb"):
        gb_cols = [c.strip() for c in _split_commas(m.group("gb"))]
        grouped = t.groupby(*[t[c] for c in gb_cols])
        exprs = {}
        for item in sel_items:
            name, e = _parse_select_item(item, t)
            exprs[name] = e
        return grouped.reduce(**exprs)
    if len(sel_items) == 1 and sel_items[0].strip() == "*":
        return t
    exprs = {}
    for item in sel_items:
        name, e = _parse_select_item(item, t)
        exprs[name] = e
    return t.select(**exprs)


def _split_commas(s: str) -> list[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _parse_select_item(item: str, t: Table):
    item = item.strip()
    m = re.match(r"(?is)^(?P<expr>.+?)\s+as\s+(?P<alias>\w+)$", item)
    alias = None
    if m:
        alias = m.group("alias")
        item = m.group("expr").strip()
    e = _parse_expr(item, t)
    if alias is None:
        alias = item if re.fullmatch(r"\w+", item) else "expr"
    return alias, e


def _parse_expr(s: str, t: Table):
    s = s.strip()
    m = re.match(r"(?is)^(\w+)\((.*)\)$", s)
    if m and m.group(1).lower() in _AGGS:
        inner = m.group(2).strip()
        args = [] if inner in ("", "*") else [_parse_expr(inner, t)]
        return _AGGS[m.group(1).lower()](args)
    # comparison / arithmetic via python-ish eval over column refs
    names = set(re.findall(r"[A-Za-z_]\w*", s))
    env: dict[str, Any] = {}
    for n in names:
        if n in t.column_names():
            env[n] = t[n]
    py = re.sub(r"(?<![<>!=])=(?!=)", "==", s)
    py = re.sub(r"(?i)\bAND\b", "&", py)
    py = re.sub(r"(?i)\bOR\b", "|", py)
    py = re.sub(r"(?i)\bNOT\b", "~", py)
    return eval(py, {"__builtins__": {}}, env)  # noqa: S307 — restricted namespace
