"""``pw.sql`` — SQL → Table API translation.

The reference routes SQL through sqlglot (``internals/sql.py``); sqlglot is not
in this environment, so this module carries its own tokenizer + recursive-
descent parser for the documented subset the reference supports: SELECT
expression lists with aliases and arithmetic/boolean operators, FROM with
INNER/LEFT/RIGHT/FULL JOIN ... ON equality chains, WHERE, GROUP BY + HAVING,
the standard aggregates (COUNT/SUM/MIN/MAX/AVG), UNION [ALL] / INTERSECT, and
WITH common table expressions. Queries lower onto the same Table operators the
reference's translation targets (filter/select/join/groupby/reduce/concat).
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.table import Table

# ------------------------------------------------------------------ tokenizer

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<op><=|>=|<>|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.)
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "join", "inner",
    "left", "right", "full", "outer", "on", "and", "or", "not", "as",
    "union", "all", "intersect", "with", "null", "true", "false", "is",
    "count", "sum", "min", "max", "avg",
}


class _Tok:
    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: Any):
        self.kind = kind  # num | str | op | name | kw | end
        self.value = value

    def __repr__(self) -> str:
        return f"{self.kind}:{self.value!r}"


def _tokenize(q: str) -> list[_Tok]:
    out: list[_Tok] = []
    pos = 0
    q = q.strip().rstrip(";")
    while pos < len(q):
        m = _TOKEN_RE.match(q, pos)
        if m is None:
            raise ValueError(f"pw.sql: cannot tokenize at {q[pos:pos + 20]!r}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        if m.lastgroup == "num":
            text = m.group()
            out.append(_Tok("num", float(text) if "." in text else int(text)))
        elif m.lastgroup == "str":
            out.append(_Tok("str", m.group()[1:-1].replace("''", "'")))
        elif m.lastgroup == "op":
            out.append(_Tok("op", m.group()))
        else:
            name = m.group()
            kind = "kw" if name.lower() in _KEYWORDS else "name"
            out.append(_Tok(kind, name.lower() if kind == "kw" else name))
    out.append(_Tok("end", None))
    return out


# ------------------------------------------------------------------ parser


class _Parser:
    def __init__(self, toks: list[_Tok]):
        self.toks = toks
        self.i = 0

    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Any = None) -> _Tok | None:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Any = None) -> _Tok:
        t = self.accept(kind, value)
        if t is None:
            raise ValueError(f"pw.sql: expected {value or kind}, got {self.peek()!r}")
        return t

    # statement := [WITH name AS (select) [, ...]] select_set
    def statement(self) -> dict:
        ctes: list[tuple[str, dict]] = []
        if self.accept("kw", "with"):
            while True:
                name = self.expect("name").value
                self.expect("kw", "as")
                self.expect("op", "(")
                ctes.append((name, self.select_set()))
                self.expect("op", ")")
                if not self.accept("op", ","):
                    break
        node = self.select_set()
        node["ctes"] = ctes
        self.expect("end")
        return node

    # select_set := select { (UNION [ALL] | INTERSECT) select }
    def select_set(self) -> dict:
        node = self.select()
        while True:
            if self.accept("kw", "union"):
                all_ = bool(self.accept("kw", "all"))
                node = {"op": "union", "all": all_, "left": node, "right": self.select()}
            elif self.accept("kw", "intersect"):
                node = {"op": "intersect", "left": node, "right": self.select()}
            else:
                return node

    def select(self) -> dict:
        self.expect("kw", "select")
        items: list[tuple[str | None, dict]] = []
        if self.accept("op", "*"):
            items.append((None, {"k": "star"}))
        else:
            while True:
                e = self.expr()
                alias = None
                if self.accept("kw", "as"):
                    alias = self.expect("name").value
                elif self.peek().kind == "name":
                    alias = self.next().value
                items.append((alias, e))
                if not self.accept("op", ","):
                    break
        self.expect("kw", "from")
        table = self.expect("name").value
        joins: list[dict] = []
        while True:
            how = None
            if self.accept("kw", "join"):
                how = "inner"
            elif self.accept("kw", "inner"):
                self.expect("kw", "join")
                how = "inner"
            elif self.accept("kw", "left"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                how = "left"
            elif self.accept("kw", "right"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                how = "right"
            elif self.accept("kw", "full"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                how = "outer"
            else:
                break
            jt = self.expect("name").value
            self.expect("kw", "on")
            cond = self.expr()
            joins.append({"table": jt, "how": how, "on": cond})
        where = self.expr() if self.accept("kw", "where") else None
        group: list[dict] | None = None
        having = None
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group = [self.expr()]
            while self.accept("op", ","):
                group.append(self.expr())
            if self.accept("kw", "having"):
                having = self.expr()
        return {
            "op": "select", "items": items, "table": table, "joins": joins,
            "where": where, "group": group, "having": having,
        }

    # expression grammar: or > and > not > comparison > add > mul > unary > atom
    def expr(self) -> dict:
        node = self.and_()
        while self.accept("kw", "or"):
            node = {"k": "bin", "op": "|", "l": node, "r": self.and_()}
        return node

    def and_(self) -> dict:
        node = self.not_()
        while self.accept("kw", "and"):
            node = {"k": "bin", "op": "&", "l": node, "r": self.not_()}
        return node

    def not_(self) -> dict:
        if self.accept("kw", "not"):
            return {"k": "not", "e": self.not_()}
        return self.cmp()

    def cmp(self) -> dict:
        node = self.add()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            op = {"=": "==", "<>": "!=", "!=": "!="}.get(t.value, t.value)
            return {"k": "bin", "op": op, "l": node, "r": self.add()}
        if self.accept("kw", "is"):
            neg = bool(self.accept("kw", "not"))
            self.expect("kw", "null")
            return {"k": "isnull", "e": node, "neg": neg}
        return node

    def add(self) -> dict:
        node = self.mul()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                node = {"k": "bin", "op": t.value, "l": node, "r": self.mul()}
            else:
                return node

    def mul(self) -> dict:
        node = self.unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                node = {"k": "bin", "op": t.value, "l": node, "r": self.unary()}
            else:
                return node

    def unary(self) -> dict:
        if self.accept("op", "-"):
            return {"k": "neg", "e": self.unary()}
        return self.atom()

    def atom(self) -> dict:
        t = self.peek()
        if self.accept("op", "("):
            node = self.expr()
            self.expect("op", ")")
            return node
        if t.kind == "num" or t.kind == "str":
            self.next()
            return {"k": "const", "v": t.value}
        if t.kind == "kw" and t.value in ("null", "true", "false"):
            self.next()
            return {"k": "const", "v": {"null": None, "true": True, "false": False}[t.value]}
        if t.kind == "kw" and t.value in ("count", "sum", "min", "max", "avg"):
            self.next()
            self.expect("op", "(")
            if t.value == "count" and self.accept("op", "*"):
                self.expect("op", ")")
                return {"k": "agg", "fn": "count", "arg": None}
            arg = self.expr()
            self.expect("op", ")")
            return {"k": "agg", "fn": t.value, "arg": arg}
        if t.kind == "name":
            self.next()
            if self.accept("op", "."):
                col = self.expect("name").value
                return {"k": "col", "table": t.value, "name": col}
            return {"k": "col", "table": None, "name": t.value}
        raise ValueError(f"pw.sql: unexpected token {t!r}")


# ------------------------------------------------------------------ translate


class _Scope:
    """Column resolution over the current materialization: ``frames`` maps
    table name → {user column → materialized column}, in FROM/JOIN order, so
    same-named columns of joined tables never shadow each other."""

    def __init__(self, table: Table, frames: dict[str, dict[str, str]]):
        self.table = table
        self.frames = frames

    def resolve(self, tname: str | None, col: str):
        if tname is not None:
            if tname not in self.frames:
                raise ValueError(f"pw.sql: unknown table {tname!r}")
            frame = self.frames[tname]
            if col not in frame:
                raise ValueError(f"pw.sql: no column {col!r} in table {tname!r}")
            return self.table[frame[col]]
        for frame in self.frames.values():
            if col in frame:
                return self.table[frame[col]]
        raise ValueError(f"pw.sql: unknown column {col!r}")


def _build_expr(node: dict, scope: _Scope, in_agg: bool = False):
    import operator as op

    k = node["k"]
    if k == "const":
        return expr_mod.wrap(node["v"])
    if k == "col":
        return scope.resolve(node["table"], node["name"])
    if k == "neg":
        return -_build_expr(node["e"], scope, in_agg)
    if k == "not":
        return ~_build_expr(node["e"], scope, in_agg)
    if k == "isnull":
        e = _build_expr(node["e"], scope, in_agg)
        return e.is_not_none() if node["neg"] else e.is_none()
    if k == "bin":
        l = _build_expr(node["l"], scope, in_agg)
        r = _build_expr(node["r"], scope, in_agg)
        return {
            "+": op.add, "-": op.sub, "*": op.mul, "/": op.truediv, "%": op.mod,
            "==": op.eq, "!=": op.ne, "<": op.lt, "<=": op.le, ">": op.gt,
            ">=": op.ge, "&": op.and_, "|": op.or_,
        }[node["op"]](l, r)
    if k == "agg":
        if not in_agg:
            raise ValueError("pw.sql: aggregate used outside an aggregation context")
        arg = None if node["arg"] is None else _build_expr(node["arg"], scope)
        return {
            "count": lambda a: reducers.count(),
            "sum": lambda a: reducers.sum(a),
            "min": lambda a: reducers.min(a),
            "max": lambda a: reducers.max(a),
            "avg": lambda a: reducers.avg(a),
        }[node["fn"]](arg)
    raise ValueError(f"pw.sql: unhandled expression node {k!r}")


def _has_agg(node: dict) -> bool:
    if not isinstance(node, dict) or "k" not in node:
        return False
    if node["k"] == "agg":
        return True
    return any(_has_agg(v) for v in node.values() if isinstance(v, dict))


def _unique_name(base: str, taken: dict) -> str:
    """SQL result columns never silently collide: later duplicates get _1, _2…"""
    if base not in taken:
        return base
    n = 1
    while f"{base}_{n}" in taken:
        n += 1
    return f"{base}_{n}"


def _default_name(node: dict, i: int) -> str:
    if node["k"] == "col":
        return node["name"]
    if node["k"] == "agg":
        return node["fn"]
    return f"_col_{i}"


def _split_eq_conds(node: dict) -> list[dict]:
    """Flatten ON a.x = b.y AND ... into a list of equality nodes."""
    if node["k"] == "bin" and node["op"] == "&":
        return _split_eq_conds(node["l"]) + _split_eq_conds(node["r"])
    return [node]


def _extract_having_aggs(node: dict, found: list[dict]) -> dict:
    """Replace aggregate nodes with references to hidden reduce columns."""
    if node["k"] == "agg":
        name = f"__having_{len(found)}"
        found.append(node)
        return {"k": "col", "table": None, "name": name}
    out = dict(node)
    for key, v in node.items():
        if isinstance(v, dict) and "k" in v:
            out[key] = _extract_having_aggs(v, found)
    return out


def _translate_select(node: dict, env: dict[str, Table]) -> Table:
    base_name = node["table"]
    if base_name not in env:
        raise ValueError(f"pw.sql: unknown table {base_name!r}")
    current: Table = env[base_name]
    # frames: table name -> {user col -> materialized col in `current`}
    frames: dict[str, dict[str, str]] = {
        base_name: {c: c for c in current.column_names()}
    }

    for j in node["joins"]:
        jt_name = j["table"]
        if jt_name not in env:
            raise ValueError(f"pw.sql: unknown table {jt_name!r}")
        jt = env[jt_name]
        jt_frames = {**frames, jt_name: {c: c for c in jt.column_names()}}

        class _JoinScope:
            def resolve(self, tname, col):
                if tname == jt_name:
                    return jt[col]
                if tname is not None:
                    return _Scope(current, frames).resolve(tname, col)
                for frame in frames.values():
                    if col in frame:
                        return current[frame[col]]
                if col in jt.column_names():
                    return jt[col]
                raise ValueError(f"pw.sql: unknown column {col!r}")

        jscope = _JoinScope()
        conds = []
        for c in _split_eq_conds(j["on"]):
            if not (c["k"] == "bin" and c["op"] == "=="):
                raise ValueError("pw.sql: JOIN ON supports equality conditions")
            conds.append(_build_expr(c["l"], jscope) == _build_expr(c["r"], jscope))
        joined = current.join(jt, *conds, how=j["how"])
        # materialize BOTH sides under unique names: same-named columns of
        # different tables must never shadow each other
        cols: dict[str, Any] = {}
        new_frames: dict[str, dict[str, str]] = {}
        for tn, frame in jt_frames.items():
            new_frames[tn] = {}
            src = jt if tn == jt_name else current
            for cn, mat in frame.items():
                uname = f"__{tn}__{cn}"
                cols[uname] = src[mat if tn != jt_name else cn]
                new_frames[tn][cn] = uname
        current = joined.select(**cols)
        frames = new_frames

    scope = _Scope(current, frames)
    if node["where"] is not None:
        current = current.filter(_build_expr(node["where"], scope))
        scope = _Scope(current, frames)

    items = node["items"]
    if node["group"] is not None:
        key_refs = []
        for g in node["group"]:
            if g["k"] != "col":
                raise ValueError("pw.sql: GROUP BY supports plain columns")
            key_refs.append(scope.resolve(g["table"], g["name"]))
        grouped = current.groupby(*[current[r.name] for r in key_refs])
        out: dict[str, Any] = {}
        for i, (alias, e) in enumerate(items):
            if e["k"] == "star":
                raise ValueError("pw.sql: SELECT * with GROUP BY is not supported")
            out[_unique_name(alias or _default_name(e, i), out)] = _build_expr(
                e, scope, in_agg=True
            )
        having = node["having"]
        hidden: list[dict] = []
        if having is not None:
            having = _extract_having_aggs(having, hidden)
            for i, agg_node in enumerate(hidden):
                out[f"__having_{i}"] = _build_expr(agg_node, scope, in_agg=True)
        result = grouped.reduce(**out)
        if having is not None:
            hv_scope = _Scope(result, {"": {c: c for c in result.column_names()}})
            result = result.filter(_build_expr(having, hv_scope))
            if hidden:
                keep = [c for c in result.column_names() if not c.startswith("__having_")]
                result = result.select(**{c: result[c] for c in keep})
        return result

    if any(_has_agg(e) for (_a, e) in items if e["k"] != "star"):
        out = {}
        for i, (alias, e) in enumerate(items):
            out[_unique_name(alias or _default_name(e, i), out)] = _build_expr(
                e, scope, in_agg=True
            )
        return current.reduce(**out)

    if len(items) == 1 and items[0][1]["k"] == "star":
        if not node["joins"]:
            return current
        out = {}
        for tn, frame in frames.items():
            for cn, mat in frame.items():
                out[_unique_name(cn, out)] = current[mat]
        return current.select(**out)
    out = {}
    for i, (alias, e) in enumerate(items):
        if e["k"] == "star":
            for tn, frame in frames.items():
                for cn, mat in frame.items():
                    out[_unique_name(cn, out)] = current[mat]
            continue
        out[_unique_name(alias or _default_name(e, i), out)] = _build_expr(e, scope)
    return current.select(**out)


def _distinct(t: Table) -> Table:
    cols = t.column_names()
    return t.groupby(*[t[c] for c in cols]).reduce(
        **{c: t[c] for c in cols}
    )


def _translate(node: dict, env: dict[str, Table]) -> Table:
    if node["op"] == "select":
        return _translate_select(node, env)
    left = _translate(node["left"], env)
    right = _translate(node["right"], env)
    if node["op"] == "union":
        merged = Table.concat_reindex(left, right)
        return merged if node.get("all") else _distinct(merged)
    if node["op"] == "intersect":
        return _distinct(left).intersect(_distinct(right))
    raise ValueError(f"pw.sql: unhandled set op {node['op']!r}")


def sql(query: str, **tables: Table) -> Table:
    """Translate a SQL query over the given tables (``pw.sql`` surface; see
    the module docstring for the supported subset)."""
    ast = _Parser(_tokenize(query)).statement()
    env = dict(tables)
    for name, cte in ast.get("ctes", []):
        env[name] = _translate(cte, env)
    return _translate(ast, env)
