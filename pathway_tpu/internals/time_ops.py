"""Temporal behavior primitives: buffer, forget, freeze (+ forget_immediately).

Block-engine counterparts of the reference's custom timely operators in
``src/engine/dataflow/operators/time_column.rs`` (driven from
``internals/table.py:670-754``): each tracks a **watermark** — the max value of the
``current_time`` column over all rows seen — and compares it to each row's
``threshold`` column when the frontier advances:

- **buffer**: rows with ``threshold > watermark`` are held back (consolidated in the
  buffer) and released once the watermark passes their threshold. Rows already past
  threshold flow through immediately.
- **forget**: rows are passed through, then retracted once the watermark passes
  their threshold; late rows (arriving already past threshold) are dropped.
- **freeze**: once the watermark passes a row's threshold the row is immutable —
  subsequent updates/retractions for it are dropped, as are late arrivals.
- **forget_immediately**: every row is retracted at the end of its own tick
  (serves the as-of-now request/response pattern, reference
  ``internals/table.py`` ``_forget_immediately``).

Watermark updates follow the reference's discipline (temporal_behavior.py docstring):
the recorded time advances only after the whole input batch of a tick is processed,
so simultaneous arrivals all see the pre-tick watermark.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch, consolidate
from pathway_tpu.engine.graph import END_OF_STREAM, Node
from pathway_tpu.internals.logical import LogicalNode


class _SharedWatermark:
    """One watermark cell shared by all worker shards of a temporal node.

    The reference broadcasts the frontier to every worker over timely's
    progress channels; here the logical node creates ONE of these at graph
    definition time and every worker's node copy folds its local per-tick max
    into it, so row state can shard by key while the watermark stays global.
    Across PROCESSES the cluster runtime merges each node's per-process tick
    maxima through a barrier before every frontier round
    (``ClusterRuntime._sync_watermarks`` — the watermark-gossip analogue of
    timely's progress broadcast)."""

    __slots__ = ("lock", "watermark", "tick_max")

    def __init__(self):
        self.lock = threading.Lock()
        self.watermark: Any = None
        self.tick_max: Any = None


class _WatermarkNode(Node):
    """Shared machinery: evaluate threshold/current-time per row, keep watermark.

    The watermark starts as ``None`` (no data seen) rather than ``-inf`` so time
    columns of any comparable dtype (ints, floats, datetime64) work.

    Row state (held/live/frozen rows) is keyed by row key and shards across
    workers with the default row-key exchange; only the watermark is global
    (``_SharedWatermark``), which keeps sharded behavior bit-identical to the
    serial node: a row's hold/release/drop decision depends only on (its
    threshold, the global watermark)."""

    #: multi-process runtimes without watermark gossip must run these serial
    global_watermark = True

    def __init__(
        self,
        threshold_fn: Callable[[DeltaBatch], np.ndarray],
        current_time_fn: Callable[[DeltaBatch], np.ndarray],
        shared: _SharedWatermark | None = None,
    ):
        super().__init__(n_inputs=1)
        self.threshold_fn = threshold_fn
        self.current_time_fn = current_time_fn
        self._shared = shared if shared is not None else _SharedWatermark()

    # watermark/_tick_max live in the shared cell; exposed as attributes so
    # snapshot_attrs (plain values) and existing call sites stay unchanged
    @property
    def watermark(self) -> Any:
        return self._shared.watermark

    @watermark.setter
    def watermark(self, value: Any) -> None:
        with self._shared.lock:
            self._shared.watermark = value

    @property
    def _tick_max(self) -> Any:
        return self._shared.tick_max

    @_tick_max.setter
    def _tick_max(self, value: Any) -> None:
        with self._shared.lock:
            self._shared.tick_max = value

    def _observe(self, batch: DeltaBatch) -> np.ndarray:
        """Track the batch's max current-time (applied to the watermark at frontier);
        return per-row thresholds."""
        cur = self.current_time_fn(batch)
        if len(cur):
            m = np.max(cur)
            with self._shared.lock:
                if self._shared.tick_max is None or m > self._shared.tick_max:
                    self._shared.tick_max = m
        return self.threshold_fn(batch)

    def _past(self, threshold: Any) -> bool:
        """Has the watermark passed this threshold?"""
        wm = self._shared.watermark
        return wm is not None and threshold <= wm

    def _advance_watermark(self) -> None:
        with self._shared.lock:
            s = self._shared
            if s.tick_max is not None and (
                s.watermark is None or s.tick_max > s.watermark
            ):
                s.watermark = s.tick_max


class BufferNode(_WatermarkNode):
    name = "buffer"
    snapshot_attrs = ("watermark", "_tick_max", "_held", "_columns")

    def __init__(self, threshold_fn, current_time_fn, shared=None):
        super().__init__(threshold_fn, current_time_fn, shared)
        # key -> [threshold, values, net_diff]
        self._held: dict[int, list] = {}
        # set on first batch; snapshotted so a restored shard can release its
        # held rows even if the post-restart suffix never touches it
        self._columns: list[str] | None = None

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        thresholds = self._observe(batch)
        pass_idx: list[int] = []
        cols = list(batch.data.values())
        for i in range(len(batch)):
            thr = thresholds[i]
            if self._past(thr):
                pass_idx.append(i)
                continue
            key = int(batch.keys[i])
            entry = self._held.get(key)
            row = tuple(c[i] for c in cols)
            if entry is None:
                self._held[key] = [thr, row, int(batch.diffs[i])]
            else:
                entry[0] = thr
                entry[2] += int(batch.diffs[i])
                if batch.diffs[i] > 0:
                    entry[1] = row
                if entry[2] == 0:
                    del self._held[key]
        if not pass_idx:
            return []
        return [batch.take(np.asarray(pass_idx, dtype=np.int64))]

    def _release(self, time: int) -> list[DeltaBatch]:
        if time == END_OF_STREAM:
            due = list(self._held.items())  # close: flush everything (reference
            # flushes buffers when input ends so no data is lost)
        else:
            due = [(k, e) for k, e in self._held.items() if self._past(e[0])]
        if not due:
            return []
        for k, _ in due:
            del self._held[k]
        keys = [k for k, _ in due]
        rows = [e[1] for _, e in due]
        diffs = [e[2] for _, e in due]
        columns = list(self._columns)
        return [
            consolidate(
                DeltaBatch.from_rows(keys, rows, columns, time, diffs=diffs)
            )
        ]

    def on_frontier(self, time):
        self._advance_watermark()
        # column names aren't known until the first batch arrives
        if not self._held or self._columns is None:
            return []
        return self._release(time)

    def accept(self, port, batch):
        if self._columns is None:
            self._columns = list(batch.data.keys())
        super().accept(port, batch)


class ForgetNode(_WatermarkNode):
    name = "forget"
    snapshot_attrs = ("watermark", "_tick_max", "_live", "_columns")

    def __init__(self, threshold_fn, current_time_fn, mark_forgetting_records=False, shared=None):
        super().__init__(threshold_fn, current_time_fn, shared)
        self.mark = mark_forgetting_records
        # key -> [threshold, values, net_diff] of rows currently downstream
        self._live: dict[int, list] = {}
        self._columns: list[str] | None = None

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        if self._columns is None:
            self._columns = list(batch.data.keys())
        thresholds = self._observe(batch)
        keep_idx: list[int] = []
        cols = list(batch.data.values())
        for i in range(len(batch)):
            if self._past(thresholds[i]):
                continue  # late: already forgotten territory
            keep_idx.append(i)
            key = int(batch.keys[i])
            entry = self._live.get(key)
            row = tuple(c[i] for c in cols)
            if entry is None:
                self._live[key] = [thresholds[i], row, int(batch.diffs[i])]
            else:
                entry[0] = thresholds[i]
                entry[2] += int(batch.diffs[i])
                if batch.diffs[i] > 0:
                    entry[1] = row
                if entry[2] == 0:
                    del self._live[key]
        if not keep_idx:
            return []
        return [batch.take(np.asarray(keep_idx, dtype=np.int64))]

    def on_frontier(self, time):
        self._advance_watermark()
        if self._columns is None or time == END_OF_STREAM:
            return []  # closing the stream does NOT forget remaining rows
        due = [(k, e) for k, e in self._live.items() if self._past(e[0])]
        if not due:
            return []
        for k, _ in due:
            del self._live[k]
        keys = [k for k, _ in due]
        rows = [e[1] for _, e in due]
        diffs = [-e[2] for _, e in due]
        return [DeltaBatch.from_rows(keys, rows, self._columns, time, diffs=diffs)]


class FreezeNode(_WatermarkNode):
    name = "freeze"
    snapshot_attrs = ("watermark", "_tick_max", "_frozen", "_pending_freeze")

    def __init__(self, threshold_fn, current_time_fn, shared=None):
        super().__init__(threshold_fn, current_time_fn, shared)
        self._frozen: set[int] = set()
        # key -> threshold of rows passed but not yet frozen
        self._pending_freeze: dict[int, Any] = {}

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        thresholds = self._observe(batch)
        keep_idx: list[int] = []
        for i in range(len(batch)):
            key = int(batch.keys[i])
            if key in self._frozen or self._past(thresholds[i]):
                continue  # frozen row or late arrival: drop the update
            keep_idx.append(i)
            self._pending_freeze[key] = thresholds[i]
        if not keep_idx:
            return []
        return [batch.take(np.asarray(keep_idx, dtype=np.int64))]

    def on_frontier(self, time):
        self._advance_watermark()
        newly = [k for k, thr in self._pending_freeze.items() if self._past(thr)]
        for k in newly:
            self._frozen.add(k)
            del self._pending_freeze[k]
        return []


class ForgetImmediatelyNode(Node):
    name = "forget_immediately"

    def exchange_key(self, port):
        # no cross-row state at all: negate each tick's batches wherever they
        # were produced — fully parallel
        return None

    def __init__(self):
        super().__init__(n_inputs=1)
        self._this_tick: list[DeltaBatch] = []

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        self._this_tick.append(batch)
        return [batch]

    def on_frontier(self, time):
        out = [b.negated() for b in self._this_tick]
        self._this_tick = []
        return out


# ---------------------------------------------------------------- table-level impls


def _impl(table, threshold_column, current_time_column, node_cls, **kw):
    from pathway_tpu.internals.table import Table, _compile_single

    thr_fn = _compile_single(table._bind(threshold_column), table)
    cur_fn = _compile_single(table._bind(current_time_column), table)
    # one shared watermark cell per LOGICAL node: every worker's copy folds
    # into it, so row state shards while the watermark stays global
    shared = _SharedWatermark()

    def make():
        # builds happen before any processing (and before snapshot restore),
        # so resetting here gives every RUN of this logical graph a fresh
        # watermark — the cell outlives runs, its contents must not
        with shared.lock:
            shared.watermark = None
            shared.tick_max = None
        return node_cls(thr_fn, cur_fn, shared=shared, **kw)

    node = LogicalNode(make, [table._node], name=node_cls.name)
    return Table(node, table._schema, table._universe.subset())


def buffer_impl(table, threshold_column, current_time_column):
    return _impl(table, threshold_column, current_time_column, BufferNode)


def forget_impl(table, threshold_column, current_time_column, mark_forgetting_records=False):
    return _impl(
        table,
        threshold_column,
        current_time_column,
        ForgetNode,
        mark_forgetting_records=mark_forgetting_records,
    )


def freeze_impl(table, threshold_column, current_time_column):
    return _impl(table, threshold_column, current_time_column, FreezeNode)


def forget_immediately_impl(table):
    from pathway_tpu.internals.table import Table

    node = LogicalNode(ForgetImmediatelyNode, [table._node], name="forget_immediately")
    return Table(node, table._schema, table._universe.subset())
