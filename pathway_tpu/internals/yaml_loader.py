"""YAML pipeline templates — ``pw.load_yaml`` (reference:
``internals/yaml_loader.py:74-214``).

Semantics matched from the reference:
- ``!module.path.obj`` tags resolve to python objects; a mapping node calls
  the object with the mapping as kwargs, an empty scalar node calls it with no
  arguments (or yields the object itself when it isn't callable).
- ``$name`` scalars are variables; top-level mapping keys of the form
  ``$name`` DEFINE them. References resolve lazily and each definition is
  instantiated at most once (shared instances). Undefined ALL-UPPERCASE
  variables fall back to the environment (their text parsed as YAML).
- Tags shortened to ``!pw.xxx`` resolve inside ``pathway_tpu``.
"""

from __future__ import annotations

import importlib
import os
from typing import Any, IO

import yaml


class Variable:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self) -> str:
        return f"${self.name}"

    def __hash__(self) -> int:
        return hash(("pw-yaml-var", self.name))

    def __eq__(self, other) -> bool:
        return isinstance(other, Variable) and other.name == self.name


class Value:
    """Deferred constructor call (``!tag`` node)."""

    __slots__ = ("constructor", "kwargs", "constructed", "value")

    def __init__(self, constructor=None, kwargs=None, constructed=False, value=None):
        self.constructor = constructor
        self.kwargs = kwargs or {}
        self.constructed = constructed
        self.value = value


def _import_object(tag: str) -> Any:
    path = tag.lstrip("!")
    if path.startswith("pw."):
        path = "pathway_tpu." + path[3:]
    parts = path.split(".")
    for split in range(len(parts), 0, -1):
        module_name = ".".join(parts[:split])
        try:
            obj: Any = importlib.import_module(module_name)
        except ImportError:
            continue
        try:
            for attr in parts[split:]:
                obj = getattr(obj, attr)
        except AttributeError:
            break
        return obj
    raise ValueError(f"pw.load_yaml: cannot import {tag!r}")


class PathwayYamlLoader(yaml.SafeLoader):
    def construct_pathway_variable(self, node: yaml.Node) -> Variable:
        name = self.construct_yaml_str(node)
        if not name.startswith("$") or not name[1:].isidentifier():
            raise yaml.MarkedYAMLError(
                problem=f"invalid variable name {name!r}",
                problem_mark=node.start_mark,
            )
        return Variable(name[1:])

    def construct_pathway_value(self, tag: str, node: yaml.Node) -> Value:
        constructor = _import_object(tag)
        if isinstance(node, yaml.ScalarNode) and node.value == "":
            if callable(constructor):
                return Value(constructor, {})
            return Value(constructed=True, value=constructor)
        if isinstance(node, yaml.MappingNode) and callable(constructor):
            return Value(constructor, self.construct_mapping(node, deep=True))
        raise yaml.MarkedYAMLError(
            problem=f"tag {tag!r} expects a mapping or an empty node"
            + ("" if callable(constructor) else f" ({tag!r} is not callable)"),
            problem_mark=node.start_mark,
        )


PathwayYamlLoader.add_implicit_resolver("!pw-variable", __import__("re").compile(r"^\$"), "$")
PathwayYamlLoader.add_constructor("!pw-variable", PathwayYamlLoader.construct_pathway_variable)
PathwayYamlLoader.add_multi_constructor("!", PathwayYamlLoader.construct_pathway_value)


class _Resolver:
    def __init__(self, definitions: dict[Variable, Any]):
        self.definitions = definitions
        self.cache: dict[Variable, Any] = {}
        self.value_cache: dict[int, Any] = {}
        self.resolving: set[Variable] = set()

    def resolve(self, obj: Any) -> Any:
        if isinstance(obj, Variable):
            return self._resolve_variable(obj)
        if isinstance(obj, Value):
            if id(obj) in self.value_cache:
                return self.value_cache[id(obj)]
            if obj.constructed:
                result = obj.value
            else:
                kwargs = {k: self.resolve(v) for k, v in obj.kwargs.items()}
                result = obj.constructor(**kwargs)
            self.value_cache[id(obj)] = result
            return result
        if isinstance(obj, dict):
            return {k: self.resolve(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self.resolve(v) for v in obj]
        return obj

    def _resolve_variable(self, v: Variable) -> Any:
        if v in self.cache:
            return self.cache[v]
        if v in self.resolving:
            raise ValueError(f"pw.load_yaml: circular definition of ${v.name}")
        if v in self.definitions:
            self.resolving.add(v)
            try:
                result = self.resolve(self.definitions[v])
            finally:
                self.resolving.discard(v)
        elif v.name.isupper() or all(c.isupper() or c == "_" for c in v.name):
            raw = os.environ.get(v.name)
            if raw is None:
                raise KeyError(f"pw.load_yaml: variable ${v.name} is not defined")
            result = yaml.safe_load(raw)
        else:
            raise KeyError(f"pw.load_yaml: variable ${v.name} is not defined")
        self.cache[v] = result
        return result


def load_yaml(stream: str | bytes | IO) -> Any:
    """Load a YAML pipeline template: ``!tags`` construct python objects,
    ``$variables`` declared as top-level keys resolve lazily and are shared."""
    raw = yaml.load(stream, PathwayYamlLoader)  # noqa: S506 — custom SafeLoader subclass
    definitions: dict[Variable, Any] = {}
    if isinstance(raw, dict):
        definitions = {k: v for k, v in raw.items() if isinstance(k, Variable)}
    resolver = _Resolver(definitions)
    if isinstance(raw, dict):
        return {
            k: resolver.resolve(v)
            for k, v in raw.items()
            if not isinstance(k, Variable)
        }
    return resolver.resolve(raw)
