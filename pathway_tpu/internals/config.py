"""Central runtime configuration from ``PATHWAY_*`` environment variables.

Role of the reference's ``PathwayConfig`` (``python/pathway/internals/config.py``,
176 LoC) and the Rust ``Config::from_env`` (``src/engine/dataflow/config.rs:88-127``):
one object owning every env knob, so subsystems stop reading ``os.environ`` ad hoc.
Properties read the environment live — cheap, and subprocess tests that mutate env
see fresh values without cache invalidation.
"""

from __future__ import annotations

import os
from typing import Any


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {os.environ[name]!r}") from None


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        raise ValueError(f"{name} must be a number, got {os.environ[name]!r}") from None


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false", "no", "off")


class PathwayConfig:
    """Live view of the ``PATHWAY_*`` environment."""

    # ---- worker topology ----------------------------------------------------
    @property
    def threads(self) -> int:
        return max(1, _env_int("PATHWAY_THREADS", 1))

    @property
    def processes(self) -> int:
        return max(1, _env_int("PATHWAY_PROCESSES", 1))

    @property
    def process_id(self) -> int:
        return _env_int("PATHWAY_PROCESS_ID", 0)

    @property
    def first_port(self) -> int:
        return _env_int("PATHWAY_FIRST_PORT", 21000)

    @property
    def barrier_timeout(self) -> float:
        return _env_float("PATHWAY_BARRIER_TIMEOUT", 120.0)

    # ---- resilience ---------------------------------------------------------
    @property
    def heartbeat_interval(self) -> float:
        """Seconds between peer→coordinator heartbeats on the cluster control
        plane; <=0 disables failure detection (barriers then fall back to the
        bare ``barrier_timeout``)."""
        return _env_float("PATHWAY_HEARTBEAT_INTERVAL", 0.5)

    @property
    def heartbeat_timeout(self) -> float:
        """Seconds of heartbeat silence before a connected-but-quiet peer is
        declared dead (a peer whose process exits is detected immediately via
        connection EOF). Clamped so detection always lands within
        ``barrier_timeout``."""
        return min(
            _env_float("PATHWAY_HEARTBEAT_TIMEOUT", 10.0), self.barrier_timeout
        )

    @property
    def fault_plan(self) -> str | None:
        """Fault-injection plan (``resilience.FaultPlan`` syntax), e.g.
        ``kill:proc=1,tick=40;drop_poll:proc=0,tick=3,count=2``."""
        return os.environ.get("PATHWAY_FAULT_PLAN") or None

    @property
    def supervisor_max_restarts(self) -> int:
        return _env_int("PATHWAY_SUPERVISOR_MAX_RESTARTS", 3)

    @property
    def supervisor_backoff_s(self) -> float:
        return _env_float("PATHWAY_SUPERVISOR_BACKOFF", 0.5)

    # ---- elasticity (live scale-out / scale-in) -----------------------------
    @property
    def elastic(self) -> str:
        """Elasticity plane master switch: ``off`` (default — the pre-r17
        fixed-worker behavior, byte for byte), ``manual`` (the coordinator
        honors ``pathway_tpu scale --to N`` requests: the pod quiesces to the
        next committed checkpoint epoch, commits a new membership version and
        exits with the rescale status so a Supervisor relaunches it at the new
        shape, state resharding by key range from the committed epoch), or
        ``auto`` (additionally the pressure-driven autoscaler decides joins
        and drains from the r9 pod-pressure signal + sink p99 vs SLO)."""
        raw = os.environ.get("PATHWAY_ELASTIC", "off").strip().lower()
        if raw in ("", "0", "false", "no", "off"):
            return "off"
        if raw not in ("manual", "auto"):
            raise ValueError(
                f"PATHWAY_ELASTIC must be off/manual/auto, got {raw!r}"
            )
        return raw

    @property
    def elastic_min_processes(self) -> int:
        """Autoscaler lower bound: drains never shrink the pod below this."""
        return max(1, _env_int("PATHWAY_ELASTIC_MIN_PROCESSES", 1))

    @property
    def elastic_max_processes(self) -> int:
        """Autoscaler upper bound: joins never grow the pod past this."""
        return max(1, _env_int("PATHWAY_ELASTIC_MAX_PROCESSES", 8))

    @property
    def elastic_high_pressure(self) -> float:
        """Pod-pressure level treated as saturation: sustained readings at or
        above it (see ``PATHWAY_ELASTIC_SUSTAIN_TICKS``) trigger a join."""
        v = _env_float("PATHWAY_ELASTIC_HIGH_PRESSURE", 0.75)
        if not 0.0 < v <= 1.0:
            raise ValueError(
                f"PATHWAY_ELASTIC_HIGH_PRESSURE must be in (0, 1], got {v}"
            )
        return v

    @property
    def elastic_low_pressure(self) -> float:
        """Pod-pressure level treated as idle: sustained readings at or below
        it trigger a drain. Must sit below the high threshold (hysteresis —
        the band between them is the no-decision zone)."""
        v = _env_float("PATHWAY_ELASTIC_LOW_PRESSURE", 0.05)
        if not 0.0 <= v < 1.0:
            raise ValueError(
                f"PATHWAY_ELASTIC_LOW_PRESSURE must be in [0, 1), got {v}"
            )
        return v

    @property
    def elastic_sustain_ticks(self) -> int:
        """Consecutive ticks a pressure reading must hold beyond a threshold
        before the autoscaler acts — one flooded tick is noise, a sustained
        run is a trend."""
        return max(1, _env_int("PATHWAY_ELASTIC_SUSTAIN_TICKS", 50))

    @property
    def elastic_cooldown_s(self) -> float:
        """Seconds after any scale decision during which no further decision
        fires — the relaunched pod needs time to warm before its pressure
        readings mean anything."""
        return max(0.0, _env_float("PATHWAY_ELASTIC_COOLDOWN", 30.0))

    # ---- persistence / replay ----------------------------------------------
    @property
    def persistent_storage(self) -> str | None:
        return os.environ.get("PATHWAY_PERSISTENT_STORAGE")

    @property
    def replay_storage(self) -> str | None:
        return os.environ.get("PATHWAY_REPLAY_STORAGE")

    @property
    def replay_mode(self) -> str:
        return os.environ.get("PATHWAY_REPLAY_MODE", "speedrun")

    @property
    def continue_after_replay(self) -> bool:
        return _env_bool("PATHWAY_CONTINUE_AFTER_REPLAY", True)

    # ---- behavior flags -----------------------------------------------------
    @property
    def terminate_on_error(self) -> bool:
        return _env_bool("PATHWAY_TERMINATE_ON_ERROR", True)

    @property
    def runtime_typechecking(self) -> bool:
        return _env_bool("PATHWAY_RUNTIME_TYPECHECKING", False)

    @property
    def ignore_asserts(self) -> bool:
        return _env_bool("PATHWAY_IGNORE_ASSERTS", False)

    @property
    def device_exchange(self) -> str:
        """On-device all_to_all exchange plane for sharded runtimes:
        ``off`` | ``auto`` (blocks ≥ min_rows ride the mesh) | ``on`` (every
        eligible batch; byte-identity suites run this)."""
        mode = os.environ.get("PATHWAY_DEVICE_EXCHANGE", "auto").strip().lower()
        if mode not in ("off", "auto", "on"):
            raise ValueError(
                f"PATHWAY_DEVICE_EXCHANGE must be off/auto/on, got {mode!r}"
            )
        return mode

    @property
    def device_exchange_min_rows(self) -> int:
        return _env_int("PATHWAY_DEVICE_EXCHANGE_MIN_ROWS", 4096)

    @property
    def device_exchange_fused(self) -> str:
        """Fused consolidate+exchange launch for the device plane: ``off`` =
        consolidate on host, then exchange; ``auto``/``on`` = keyed delta
        blocks are digest-netted (diffs segment-summed, net-zero rows
        invalidated) INSIDE the same shard_map launch that re-shards them —
        one kernel, one interconnect round, no intermediate host block."""
        mode = os.environ.get("PATHWAY_DEVICE_EXCHANGE_FUSED", "auto").strip().lower()
        if mode not in ("off", "auto", "on"):
            raise ValueError(
                f"PATHWAY_DEVICE_EXCHANGE_FUSED must be off/auto/on, got {mode!r}"
            )
        return mode

    @property
    def engine_phases(self) -> bool:
        """Host-side per-phase tick attribution (consolidate / rehash / probe /
        groupby / join / realloc / kernel / exchange / capture wall time):
        read by ``benchmarks/engine_bench.py`` for the BENCH per-phase tick
        breakdown. Off by default — instrumented sites pay one global read."""
        return _env_bool("PATHWAY_ENGINE_PHASES", False)

    @property
    def fuse(self) -> str:
        """Chain fusion (``engine/fusion.py``): lower maximal
        single-consumer operator chains into one sweep step per chain —
        batches hand off member to member in-process instead of paying the
        per-node drain/route/accept dispatch, and runs of expression members
        collapse into one composed block program. ``off`` restores the
        one-node-per-step r14 sweep byte-for-byte. Default ``on``
        (BENCH_r15: the small-tick dispatch win)."""
        mode = os.environ.get("PATHWAY_FUSE", "on").strip().lower()
        if mode in ("on", "1", "true"):
            return "on"
        if mode in ("off", "0", "false"):
            return "off"
        raise ValueError(f"PATHWAY_FUSE must be off/on, got {mode!r}")

    @property
    def fuse_jax(self) -> str:
        """Jitted fused-chain kernels: lower a composed expression segment
        (whitelisted numeric filter/map chain) into ONE buffer-donating XLA
        launch per tick, inputs padded to the shared power-of-two buckets so
        the jit shape set stays closed under row-count churn. ``auto``
        routes only blocks of at least ``PATHWAY_FUSE_JAX_MIN_ROWS`` rows
        (below that, XLA dispatch overhead loses to the composed numpy
        program on CPU — the jax_kernels adoption discipline); ``on``
        forces every eligible block through the kernel; ``off`` keeps chains
        on the composed numpy path. Values are bit-identical either way
        (the whitelist admits only ops with no numpy/XLA divergence)."""
        mode = os.environ.get("PATHWAY_FUSE_JAX", "auto").strip().lower()
        if mode not in ("off", "auto", "on"):
            raise ValueError(f"PATHWAY_FUSE_JAX must be off/auto/on, got {mode!r}")
        return mode

    @property
    def fuse_jax_min_rows(self) -> int:
        """Row threshold for ``PATHWAY_FUSE_JAX=auto`` (default 65536 —
        the measured crossover scale of the other engine kernels on CPU)."""
        return max(1, _env_int("PATHWAY_FUSE_JAX_MIN_ROWS", 65536))

    @property
    def arrange_device_cache(self) -> bool:
        """Persistent device-resident arrangements for the jitted probe
        kernel: sorted state segments are transferred once per compaction
        generation and re-probed from device memory across ticks, instead of
        re-uploading the arrangement every tick. On by default; ``0`` forces
        the per-call transfer (debugging / memory-pressure escape hatch)."""
        return _env_bool("PATHWAY_ARRANGE_CACHE", True)

    @property
    def arrange_donate(self) -> str:
        """Buffer donation on the tick-loop jit entry points (probe queries,
        grouped segment-sum inputs, exchange staging): ``auto`` = donate on
        tpu/gpu backends where XLA reuses the buffer for outputs and skips a
        copy, never on cpu (donation is ignored there and warns); ``on`` /
        ``off`` force it."""
        mode = os.environ.get("PATHWAY_ARRANGE_DONATE", "auto").strip().lower()
        if mode not in ("off", "auto", "on"):
            raise ValueError(
                f"PATHWAY_ARRANGE_DONATE must be off/auto/on, got {mode!r}"
            )
        return mode

    @property
    def microbatch(self) -> str:
        """Cross-tick accumulate-then-launch dispatch for ``is_batched`` UDFs
        (embedders/rerankers): ``off`` = one call per delta block (the r5
        behavior), ``auto``/``on`` = buffer rows across ticks per (UDF, bucket)
        and launch padded power-of-two batches, holding rows until their batch
        completes (flushed on the autocommit deadline, so added latency is
        bounded by ``autocommit_duration_ms``), ``pending`` = same batching but
        rows appear immediately with ``PENDING`` in the UDF columns and settle
        via a retract/insert correction on the completing tick (the
        ``await_futures`` discipline, ``internals/table.py``). Measured default:
        ``auto`` — BENCH_r06 streaming 64-row ticks reach batch-512 device
        throughput instead of a fraction of it."""
        mode = os.environ.get("PATHWAY_MICROBATCH", "auto").strip().lower()
        if mode not in ("off", "auto", "on", "pending"):
            raise ValueError(
                f"PATHWAY_MICROBATCH must be off/auto/on/pending, got {mode!r}"
            )
        return mode

    @property
    def microbatch_max_batch(self) -> int:
        """Device launch chunk for cross-tick microbatching; 512 is the measured
        best batch on v5e (BENCH_r05 ``device_docs_per_s_by_batch``)."""
        n = _env_int("PATHWAY_MICROBATCH_MAX_BATCH", 512)
        if n < 1:
            raise ValueError(
                f"PATHWAY_MICROBATCH_MAX_BATCH must be >= 1, got {n}"
            )
        return n

    @property
    def microbatch_flush_ms(self) -> float | None:
        """Override the buffer-age flush deadline (defaults to the runtime's
        ``autocommit_duration_ms``)."""
        raw = os.environ.get("PATHWAY_MICROBATCH_FLUSH_MS")
        return None if raw in (None, "") else float(raw)

    # ---- flow control (adaptive admission plane) ----------------------------
    @property
    def flow(self) -> str:
        """Adaptive flow-control plane master switch: ``off`` (default — no
        gates installed, ingest queues unbounded, byte-for-byte the pre-r9
        behavior) or ``on`` (bounded credit queues on every connector input,
        priority admission for interactive vs bulk service classes, and the
        AIMD microbatch controller)."""
        raw = os.environ.get("PATHWAY_FLOW", "off").strip().lower()
        if raw in ("", "0", "false", "no", "off"):
            return "off"
        if raw in ("1", "true", "yes", "on"):
            return "on"
        raise ValueError(f"PATHWAY_FLOW must be off/on, got {raw!r}")

    @property
    def input_queue_rows(self) -> int:
        """Per-connector ingest queue bound (rows) when the flow plane is on.
        Credits are consumed by connector pushes and replenished when the tick
        that drained the rows completes downstream."""
        n = _env_int("PATHWAY_INPUT_QUEUE_ROWS", 65536)
        if n < 1:
            raise ValueError(f"PATHWAY_INPUT_QUEUE_ROWS must be >= 1, got {n}")
        return n

    @property
    def flow_policy(self) -> str:
        """Overflow policy of a full ingest queue: ``block`` (default — the
        producer thread waits for credit, classic backpressure) or ``shed``
        (overflow rows are dropped and counted — explicit, telemetry-visible
        load shedding instead of silent memory growth)."""
        raw = os.environ.get("PATHWAY_FLOW_POLICY", "block").strip().lower()
        if raw not in ("block", "shed"):
            raise ValueError(f"PATHWAY_FLOW_POLICY must be block/shed, got {raw!r}")
        return raw

    @property
    def latency_slo_ms(self) -> float:
        """Interactive sink end-to-end latency objective (ms). The AIMD
        controller halves the microbatch target bucket when the recent sink
        p99 exceeds this, and the admission scheduler throttles bulk-class
        inputs as the observed latency approaches it."""
        v = _env_float("PATHWAY_LATENCY_SLO_MS", 250.0)
        if v <= 0:
            raise ValueError(f"PATHWAY_LATENCY_SLO_MS must be > 0, got {v}")
        return v

    @property
    def flow_bulk_min_rows(self) -> int:
        """Guaranteed bulk-class admission per tick under full pressure —
        backfill keeps progressing (never starved) while interactive traffic
        overtakes it."""
        return max(1, _env_int("PATHWAY_FLOW_BULK_MIN_ROWS", 64))

    @property
    def flow_bulk_max_rows(self) -> int:
        """Standing per-tick bulk drain ceiling, applied even at zero
        pressure (0 = unlimited, the r9 behavior). The pressure signal is
        reactive — it engages only after interactive latency degrades — so
        serving tiers whose bulk rows carry real device cost (doc-ingest
        embeds) set this to bound the stall a fresh flood can inflict before
        the controller responds."""
        return max(0, _env_int("PATHWAY_FLOW_BULK_MAX_ROWS", 0))

    # ---- REST serving plane (io/http rest_connector) ------------------------
    @property
    def serve_max_inflight(self) -> int:
        """Bounded in-flight request budget per REST route: requests admitted
        but not yet answered. Past it the route sheds with a fast 429 +
        ``Retry-After`` instead of growing an unbounded futures dict — the
        serving-side mirror of the ingest credit gate."""
        n = _env_int("PATHWAY_SERVE_MAX_INFLIGHT", 1024)
        if n < 1:
            raise ValueError(f"PATHWAY_SERVE_MAX_INFLIGHT must be >= 1, got {n}")
        return n

    @property
    def serve_coalesce_ms(self) -> float:
        """How long a query arrival may wait for concurrent requests to
        coalesce into the same engine tick before a tick is forced. The
        arrival-driven scheduler wakes the tick loop after this delay (or
        immediately once ``PATHWAY_SERVE_COALESCE_ROWS`` requests are
        waiting), so single-request latency is ~this bound plus the tick,
        instead of the autocommit poll interval."""
        v = _env_float("PATHWAY_SERVE_COALESCE_MS", 2.0)
        if v < 0:
            raise ValueError(f"PATHWAY_SERVE_COALESCE_MS must be >= 0, got {v}")
        return v

    @property
    def serve_coalesce_rows(self) -> int:
        """In-flight request count that triggers an IMMEDIATE tick wakeup —
        a full coalesce bucket shouldn't wait out the coalesce window."""
        return max(1, _env_int("PATHWAY_SERVE_COALESCE_ROWS", 64))

    @property
    def serve_rate(self) -> float:
        """Per-route token-bucket refill rate (requests/second) applied at
        EVERY front door — the coordinator's and, with the fabric on, each
        peer's. 0 (default) disables rate limiting. Requests past the bucket
        shed with ``429`` + an exact ``Retry-After`` derived from the refill
        rate, counted per route per process and merged pod-wide over the
        heartbeat telemetry."""
        v = _env_float("PATHWAY_SERVE_RATE", 0.0)
        if v < 0:
            raise ValueError(f"PATHWAY_SERVE_RATE must be >= 0, got {v}")
        return v

    @property
    def serve_burst(self) -> int:
        """Token-bucket capacity (burst) for ``PATHWAY_SERVE_RATE``. 0
        (default) sizes the bucket at ``max(1, ceil(rate))`` — one second of
        refill."""
        n = _env_int("PATHWAY_SERVE_BURST", 0)
        if n < 0:
            raise ValueError(f"PATHWAY_SERVE_BURST must be >= 0, got {n}")
        return n

    @property
    def serve_api_keys(self) -> tuple[str, ...]:
        """Comma-separated API keys accepted at every front door (presented
        as ``X-API-Key`` or ``Authorization: Bearer``). Empty (default)
        disables auth. With keys set, a request without a key answers ``401``
        and a wrong key ``403`` — both shed at the door, before admission,
        with exact per-route counters."""
        raw = os.environ.get("PATHWAY_SERVE_API_KEYS", "")
        return tuple(k.strip() for k in raw.split(",") if k.strip())

    @property
    def serve_tick(self) -> str:
        """REST query tick scheduling: ``arrival`` (default — query arrival
        wakes the tick loop through the coalesce window above) or ``poll``
        (pre-r14 behavior: requests wait for the fixed autocommit poll; the
        serving bench's baseline mode)."""
        raw = os.environ.get("PATHWAY_SERVE_TICK", "arrival").strip().lower()
        if raw not in ("arrival", "poll"):
            raise ValueError(
                f"PATHWAY_SERVE_TICK must be arrival/poll, got {raw!r}"
            )
        return raw

    # ---- distributed serving fabric (pathway_tpu/fabric) --------------------
    @property
    def fabric(self) -> str:
        """Distributed serving fabric master switch: ``off`` (default — REST
        routes live on the coordinator only, the pre-r18 behavior byte for
        byte) or ``on`` (every cluster process starts a front door for every
        registered route; a request landing on a non-owner process is
        forwarded over the fabric transport to the owning process and the
        answer relayed back byte-identical, replica-served table routes
        answer locally from the changelog feed, and ``/_schema`` is served
        from every door). No-op on single-process runs."""
        raw = os.environ.get("PATHWAY_FABRIC", "off").strip().lower()
        if raw in ("", "0", "false", "no", "off"):
            return "off"
        if raw in ("1", "true", "yes", "on"):
            return "on"
        raise ValueError(f"PATHWAY_FABRIC must be off/on, got {raw!r}")

    @property
    def fabric_port_stride(self) -> int:
        """Front-door port offset per process: process ``i``'s door binds the
        route's port + ``i * stride``. The default 1 keeps single-host pods
        (tests, laptops) collision-free; multi-host pods set 0 so every host
        serves the SAME port behind one load balancer."""
        n = _env_int("PATHWAY_FABRIC_PORT_STRIDE", 1)
        if n < 0:
            raise ValueError(f"PATHWAY_FABRIC_PORT_STRIDE must be >= 0, got {n}")
        return n

    @property
    def fabric_max_staleness_ms(self) -> float:
        """Replica freshness bound: a replica-served table route answers
        locally only while its changelog lag is at most this; a staler
        replica falls back to forwarding the lookup to the owner (counted,
        never silently stale past the bound)."""
        v = _env_float("PATHWAY_FABRIC_MAX_STALENESS_MS", 2000.0)
        if v <= 0:
            raise ValueError(
                f"PATHWAY_FABRIC_MAX_STALENESS_MS must be > 0, got {v}"
            )
        return v

    @property
    def fabric_timeout(self) -> float:
        """Seconds an ingress front door waits for a forwarded request's
        answer from the owning process before answering 503."""
        v = _env_float("PATHWAY_FABRIC_TIMEOUT", 30.0)
        if v <= 0:
            raise ValueError(f"PATHWAY_FABRIC_TIMEOUT must be > 0, got {v}")
        return v

    # ---- replica-served retrieval (pathway_tpu/fabric/index_replica) --------
    @property
    def replica(self) -> str:
        """Replica-served retrieval master switch: ``on`` (default — with the
        fabric live on a cluster run, every process replays the index
        changelog into a local replica index and its front door answers
        ``/v1/retrieve`` locally within ``PATHWAY_REPLICA_MAX_STALENESS_MS``,
        falling back to owner-forwarding when stale or resyncing) or ``off``
        (every retrieval pays the r18 owner hop; the pre-r20 behavior byte
        for byte). No-op without ``PATHWAY_FABRIC=on`` or on single-process
        runs."""
        raw = os.environ.get("PATHWAY_REPLICA", "on").strip().lower()
        if raw in ("1", "true", "yes", "on", ""):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        raise ValueError(f"PATHWAY_REPLICA must be on/off, got {raw!r}")

    @property
    def replica_max_staleness_ms(self) -> float:
        """Replica-index freshness bound: a door answers ``/v1/retrieve``
        from its local replica index only while every peer slice's changelog
        lag is at most this; a staler (or never-synced, or resyncing) replica
        forwards to the owner instead — counted, never silently stale past
        the bound."""
        v = _env_float("PATHWAY_REPLICA_MAX_STALENESS_MS", 2000.0)
        if v <= 0:
            raise ValueError(
                f"PATHWAY_REPLICA_MAX_STALENESS_MS must be > 0, got {v}"
            )
        return v

    @property
    def replica_memo_share(self) -> str:
        """Pod-wide query-embedding memo sharing: ``on`` (default — each
        process piggybacks its freshly-encoded memo entries on the replica
        cast so a pod-wide hot query set embeds once; peers insert them into
        their own embedder memos) or ``off`` (the r14 memo stays strictly
        per-process). No-op without a fabric or with unmemoized embedders."""
        raw = os.environ.get("PATHWAY_REPLICA_MEMO_SHARE", "on").strip().lower()
        if raw in ("1", "true", "yes", "on", ""):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        raise ValueError(
            f"PATHWAY_REPLICA_MEMO_SHARE must be on/off, got {raw!r}"
        )

    # ---- shard-map plane (internals/shardmap) ------------------------------
    @property
    def shardmap(self) -> str:
        """Versioned shard-map plane master switch: ``off`` (default — key
        ownership stays the derived ``(key & SHARD_MASK) % n_workers`` modulo
        rule, pre-r19 behavior byte for byte) or ``on`` (cluster placement,
        fabric door routing, and elastic rescale all consult one committed
        ``internals/shardmap.ShardMap`` of contiguous residue ranges: fabric
        doors route requests directly to the key's owning process instead of
        worker 0, and a rescale moves only the re-mapped ranges)."""
        raw = os.environ.get("PATHWAY_SHARDMAP", "off").strip().lower()
        if raw in ("", "0", "false", "no", "off"):
            return "off"
        if raw in ("1", "true", "yes", "on"):
            return "on"
        raise ValueError(f"PATHWAY_SHARDMAP must be off/on, got {raw!r}")

    @property
    def shardmap_migration(self) -> str:
        """Live state migration under the shard-map plane: ``on`` (default —
        a rescale diffs shard map V→V+1 and MOVES only the re-mapped key
        ranges' operator shards, restoring everything else positionally, and
        input-log trim stays enabled) or ``off`` (fall back to the r17
        wipe-positional-shards + replay-full-input-logs path; trim stays
        suspended). Ignored while ``PATHWAY_SHARDMAP`` is off."""
        raw = os.environ.get("PATHWAY_SHARDMAP_MIGRATION", "on").strip().lower()
        if raw in ("1", "true", "yes", "on", ""):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        raise ValueError(
            f"PATHWAY_SHARDMAP_MIGRATION must be on/off, got {raw!r}"
        )

    @property
    def monitoring_server(self) -> str | None:
        return os.environ.get("PATHWAY_MONITORING_SERVER")

    @property
    def monitoring_http_host(self) -> str:
        """Bind host for the monitoring HTTP server. Default stays loopback;
        multi-host TPU-VM pods set ``0.0.0.0`` (or the NIC address) so peers'
        ``/metrics`` are scrapable across the pod."""
        return os.environ.get("PATHWAY_MONITORING_HTTP_HOST", "127.0.0.1")

    # ---- live tracing (observability plane) ---------------------------------
    @property
    def trace_mode(self) -> str:
        """Live span pipeline master switch: ``off`` (default — no tracer is
        installed, hot loops pay one ``is None`` test) or ``on``."""
        raw = os.environ.get("PATHWAY_TRACE", "off").strip().lower()
        if raw in ("", "0", "false", "no", "off"):
            return "off"
        if raw in ("1", "true", "yes", "on", "full", "live"):
            return "on"
        raise ValueError(f"PATHWAY_TRACE must be off/on, got {raw!r}")

    @property
    def trace_sample(self) -> float:
        """Head-sampling rate in (0, 1]: the fraction of TICKS traced (a
        sampled tick records all its child spans; an unsampled one records
        none). The tick hash is deterministic, so every cluster process
        samples the same ticks."""
        rate = _env_float("PATHWAY_TRACE_SAMPLE", 1.0)
        if not 0.0 < rate <= 1.0:
            raise ValueError(
                f"PATHWAY_TRACE_SAMPLE must be in (0, 1], got {rate}"
            )
        return rate

    @property
    def trace_live_file(self) -> str | None:
        """Rotating OTLP-JSON live sink (one ExportTraceServiceRequest per
        line); cluster processes suffix ``.p<id>``. Unset = ring buffer only
        (served by ``/trace?since=``)."""
        return os.environ.get("PATHWAY_TRACE_LIVE_FILE") or None

    @property
    def trace_buffer_spans(self) -> int:
        return max(64, _env_int("PATHWAY_TRACE_BUFFER", 8192))

    @property
    def trace_rotate_mb(self) -> int:
        return max(1, _env_int("PATHWAY_TRACE_ROTATE_MB", 64))

    @property
    def run_id(self) -> str:
        return os.environ.get("PATHWAY_RUN_ID", "")

    # ---- request-scoped tracing (observability plane, serving side) ---------
    @property
    def request_trace(self) -> str:
        """Request-scoped tracing plane (``observability/requests.py``):
        ``on`` (default) mints a ``request_id`` per admitted REST request,
        buffers its per-stage flight path in a bounded ring and keeps the
        trace **tail-based** — on completion, iff it was slow
        (``PATHWAY_REQUEST_TRACE_SLOW_MS``), errored/timed out, or falls in
        the deterministic always-keep hash slice
        (``PATHWAY_REQUEST_TRACE_KEEP``). ``off`` installs no plane at all —
        engine hot loops pay one ``is None`` test and zero rings exist."""
        raw = os.environ.get("PATHWAY_REQUEST_TRACE", "on").strip().lower()
        if raw in ("", "1", "true", "yes", "on"):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        raise ValueError(f"PATHWAY_REQUEST_TRACE must be off/on, got {raw!r}")

    @property
    def request_trace_slow_ms(self) -> float:
        """Tail-sampling latency threshold: a completed request whose
        arrival-to-response latency is at least this keeps its trace (0 keeps
        every trace — investigation mode)."""
        v = _env_float("PATHWAY_REQUEST_TRACE_SLOW_MS", 250.0)
        if v < 0:
            raise ValueError(
                f"PATHWAY_REQUEST_TRACE_SLOW_MS must be >= 0, got {v}"
            )
        return v

    @property
    def request_trace_keep(self) -> float:
        """Deterministic always-keep slice in [0, 1]: the fraction of
        request ids (by hash) whose traces are kept even when fast and
        successful — the healthy-baseline exemplars slow traces are compared
        against."""
        v = _env_float("PATHWAY_REQUEST_TRACE_KEEP", 0.01)
        if not 0.0 <= v <= 1.0:
            raise ValueError(
                f"PATHWAY_REQUEST_TRACE_KEEP must be in [0, 1], got {v}"
            )
        return v

    @property
    def request_trace_kept(self) -> int:
        """Bounded ring of kept traces queryable via ``/request?id=`` and the
        ``pathway_tpu trace`` CLI (oldest evicted first)."""
        return max(8, _env_int("PATHWAY_REQUEST_TRACE_KEPT", 256))

    # ---- device profiling (observability plane, device side) ----------------
    @property
    def profile(self) -> str:
        """Device profiling plane: ``on`` (default — compile/shape counters,
        padding-waste accounting, device-memory gauges and the flight-recorder
        ring, all at negligible cost), ``full`` (additionally measures the
        host/device time split by blocking on every traced dispatch — use for
        investigation, not steady state), or ``off``."""
        raw = os.environ.get("PATHWAY_PROFILE", "on").strip().lower()
        if raw in ("", "1", "true", "yes", "on"):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        if raw == "full":
            return "full"
        raise ValueError(f"PATHWAY_PROFILE must be off/on/full, got {raw!r}")

    @property
    def profile_dir(self) -> str | None:
        """When set, capture a ``jax.profiler`` trace of the run's first
        ``PATHWAY_PROFILE_TICKS`` ticks into this directory (viewable in
        TensorBoard/XProf). Further windows can be triggered live via the
        monitoring server's ``/profile?ticks=N`` endpoint or the
        ``pathway_tpu profile`` CLI."""
        return os.environ.get("PATHWAY_PROFILE_DIR") or None

    @property
    def profile_ticks(self) -> int:
        """Length (ticks) of a ``jax.profiler`` capture window."""
        return max(1, _env_int("PATHWAY_PROFILE_TICKS", 16))

    @property
    def profile_shape_warn(self) -> int:
        """Per-callable compile-cache shape-set size past which the
        recompile-storm detector flags the callable on ``/status`` — a
        healthy bucketed pipeline keeps a small closed shape set."""
        return max(2, _env_int("PATHWAY_PROFILE_SHAPE_WARN", 12))

    @property
    def profile_peak_tflops(self) -> float:
        """Per-chip peak TFLOP/s used to turn the rough per-launch FLOP
        estimates into a live MFU gauge (e.g. 197 for v5e bf16). 0 (default)
        reports achieved FLOP/s without an MFU ratio."""
        return max(0.0, _env_float("PATHWAY_PROFILE_PEAK_TFLOPS", 0.0))

    # ---- index plane (serving-scale KNN) ------------------------------------
    @property
    def index_snapshot(self) -> str:
        """Operator-snapshot discipline for external-index nodes: ``delta``
        (default — persist an add/remove delta log per snapshot tick plus a
        periodic compacted base, so a live 1M×384 index pays O(churn) per
        interval instead of re-pickling ~1.5 GB) or ``whole`` (the pre-r13
        whole-backend pickle, kept as an escape hatch)."""
        raw = os.environ.get("PATHWAY_INDEX_SNAPSHOT", "delta").strip().lower()
        if raw not in ("delta", "whole"):
            raise ValueError(
                f"PATHWAY_INDEX_SNAPSHOT must be delta/whole, got {raw!r}"
            )
        return raw

    @property
    def index_compact_frac(self) -> float:
        """Delta-log compaction threshold: when the accumulated delta chunks
        exceed this fraction of the base pickle's bytes, the next snapshot
        tick writes a fresh compacted base and the covered delta chunks are
        deleted after the manifest commit (the input-log trim discipline)."""
        v = _env_float("PATHWAY_INDEX_COMPACT_FRAC", 0.5)
        if v <= 0:
            raise ValueError(f"PATHWAY_INDEX_COMPACT_FRAC must be > 0, got {v}")
        return v

    @property
    def index_hot_rows(self) -> int:
        """HBM-resident row bound of the tiered KNN index's hot shard
        (``TieredKnnBackend``). The hot brute-force matrix is allocated at
        this bound and never grows past it — fixed HBM regardless of corpus
        size; everything else lives in the host IVF cold tier."""
        n = _env_int("PATHWAY_INDEX_HOT_ROWS", 65536)
        if n < 1:
            raise ValueError(f"PATHWAY_INDEX_HOT_ROWS must be >= 1, got {n}")
        return n

    @property
    def index_promote_hits(self) -> int:
        """Cold-tier hit count (within one maintenance window) at which a row
        becomes a promotion candidate for the hot shard."""
        return max(1, _env_int("PATHWAY_INDEX_PROMOTE_HITS", 2))

    @property
    def index_maintain_batch(self) -> int:
        """Max promotions (and matching LRU demotions) applied per between-tick
        maintenance pass — bounds the off-query-path scatter work per tick."""
        return max(1, _env_int("PATHWAY_INDEX_MAINTAIN_BATCH", 4096))

    # ---- data-plane audit (observability plane, correctness side) -----------
    @property
    def audit(self) -> str:
        """Data-plane correctness observability: ``on`` (default — invariant
        monitors at operator edges, per-edge cardinality/selectivity gauges,
        sampled shadow audits and the row-lineage rings, gated ≤5% overhead
        like the device plane), ``full`` (additionally verifies every
        consolidated batch is canonical/net-free and shadow-audits every
        tick — investigation mode, ≤10%), or ``off``."""
        raw = os.environ.get("PATHWAY_AUDIT", "on").strip().lower()
        if raw in ("", "1", "true", "yes", "on"):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        if raw == "full":
            return "full"
        raise ValueError(f"PATHWAY_AUDIT must be off/on/full, got {raw!r}")

    @property
    def audit_sample(self) -> float:
        """Fraction of TICKS shadow-audited in ``on`` mode (``full`` audits
        every tick). Deterministic tick-hash sampling — the same hash the r8
        trace sampler uses — so every cluster process audits the SAME ticks
        and a divergence is attributable pod-wide."""
        rate = _env_float("PATHWAY_AUDIT_SAMPLE", 0.0625)
        if not 0.0 < rate <= 1.0:
            raise ValueError(
                f"PATHWAY_AUDIT_SAMPLE must be in (0, 1], got {rate}"
            )
        return rate

    @property
    def audit_keys(self) -> int:
        """Per-edge key-multiplicity map bound for the invariant monitors.
        A monitor whose map outgrows this stops folding (one structural
        ``monitor_degraded`` event, never a crash) — the tripwire plane must
        not become the memory leak it guards against."""
        return max(1024, _env_int("PATHWAY_AUDIT_KEYS", 262144))

    @property
    def lineage_keys(self) -> int:
        """Row-lineage provenance ring capacity per operator edge (output
        keys remembered for ``/explain``; each keeps at most 8 contributing
        input keys). 0 disables lineage recording while the audit monitors
        stay live."""
        return max(0, _env_int("PATHWAY_LINEAGE_KEYS", 4096))

    @property
    def flight_dir(self) -> str | None:
        """Post-mortem flight-recorder dump directory: on
        ``terminate_on_error`` aborts, ``OtherWorkerError`` and supervised
        restarts, the bounded ring of recent ticks/device events is written
        there as one JSON file per failure. Unset = no dumps (the ring still
        records)."""
        return os.environ.get("PATHWAY_FLIGHT_DIR") or None

    @property
    def flight_events(self) -> int:
        """Flight-recorder ring capacity (device events; ticks keep a
        quarter-sized ring of their own)."""
        return max(64, _env_int("PATHWAY_FLIGHT_EVENTS", 1024))

    # ---- pod health & SLO plane (observability) -----------------------------
    @property
    def health(self) -> str:
        """Pod health & SLO plane (``observability/health.py``): ``on``
        (default) runs the per-door readiness state machine
        (``/healthz``/``/readyz`` on every door), synthetic canary probes,
        declared-SLO burn-rate evaluation, rule-based detectors and the alert
        registry with incident bundles. ``off`` installs nothing — the
        serving path is byte-identical to the plane never existing."""
        raw = os.environ.get("PATHWAY_HEALTH", "on").strip().lower()
        if raw in ("", "1", "true", "yes", "on"):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        raise ValueError(f"PATHWAY_HEALTH must be off/on, got {raw!r}")

    @property
    def health_eval_ms(self) -> int:
        """Interval between SLO/detector evaluator sweeps (burn-rate windows,
        watermark-stall/replica-lag/error-rate/backlog/thrash rules)."""
        return max(50, _env_int("PATHWAY_HEALTH_EVAL_MS", 500))

    @property
    def slo_availability(self) -> float:
        """Pod-wide availability objective in (0, 1): the success-rate target
        the burn-rate rule guards (successes = served responses + passing
        canaries; failures = timeouts + failing canaries). Overridable live
        via ``pw.set_slo(availability=…)``."""
        v = _env_float("PATHWAY_SLO_AVAILABILITY", 0.999)
        if not 0.0 < v < 1.0:
            raise ValueError(
                f"PATHWAY_SLO_AVAILABILITY must be in (0, 1), got {v}"
            )
        return v

    @property
    def slo_p99_ms(self) -> float:
        """Default per-route latency objective: 99% of requests under this
        many milliseconds. 0 (default) declares no latency SLO unless
        ``pw.set_slo(route=…, p99_ms=…)`` does."""
        v = _env_float("PATHWAY_SLO_P99_MS", 0.0)
        if v < 0:
            raise ValueError(f"PATHWAY_SLO_P99_MS must be >= 0, got {v}")
        return v

    @property
    def slo_fast_window_s(self) -> float:
        """Fast burn-rate window (seconds) — catches sudden total breaches."""
        return max(1.0, _env_float("PATHWAY_SLO_FAST_WINDOW_S", 60.0))

    @property
    def slo_slow_window_s(self) -> float:
        """Slow burn-rate window (seconds) — confirms the breach is sustained
        (multi-window rule: an alert needs BOTH windows burning)."""
        return max(1.0, _env_float("PATHWAY_SLO_SLOW_WINDOW_S", 600.0))

    @property
    def slo_burn_fast(self) -> float:
        """Burn-rate threshold for the fast window (1.0 = exactly spending
        the error budget; 14 ≈ the SRE Workbook's page-severity rate)."""
        return max(0.0, _env_float("PATHWAY_SLO_BURN_FAST", 14.0))

    @property
    def slo_burn_slow(self) -> float:
        """Burn-rate threshold for the slow window."""
        return max(0.0, _env_float("PATHWAY_SLO_BURN_SLOW", 2.0))

    @property
    def slo_burn_ticket_fast(self) -> float:
        """Ticket-severity rung of the burn-rate ladder (fast window): a
        breach burning past this but under ``PATHWAY_SLO_BURN_FAST`` files a
        ``ticket`` alert instead of a ``page`` (SRE-workbook multi-window
        multi-burn ladder; 6 ≈ budget gone in ~5 days)."""
        return max(0.0, _env_float("PATHWAY_SLO_BURN_TICKET_FAST", 6.0))

    @property
    def slo_burn_ticket_slow(self) -> float:
        """Ticket-severity rung of the burn-rate ladder (slow window)."""
        return max(0.0, _env_float("PATHWAY_SLO_BURN_TICKET_SLOW", 1.0))

    @property
    def canary_interval_ms(self) -> int:
        """Synthetic canary probe interval per door route (0 disables
        canaries; readiness and detectors stay live)."""
        return max(0, _env_int("PATHWAY_CANARY_INTERVAL_MS", 1000))

    @property
    def canary_timeout_ms(self) -> int:
        """Timeout for one canary probe; a slower door counts as a failed
        canary in the availability SLO."""
        return max(50, _env_int("PATHWAY_CANARY_TIMEOUT_MS", 2000))

    @property
    def incident_dir(self) -> str | None:
        """Incident-bundle directory: each alert activation captures one
        correlated post-mortem JSON (alert, probable-cause stage, per-stage
        p99 decomposition, slowest kept request traces, flight-recorder
        rings, shard-map/membership versions, replica health). Unset = no
        bundles (alerts still fire)."""
        return os.environ.get("PATHWAY_INCIDENT_DIR") or None

    @property
    def alert_webhook(self) -> str | None:
        """Generic webhook notification target: fired alerts POST one JSON
        document each, deduped on (alert, fingerprint) with bounded
        retry/backoff."""
        return os.environ.get("PATHWAY_ALERT_WEBHOOK") or None

    @property
    def alert_slack_channel(self) -> str | None:
        """Slack channel id for alert notifications (needs
        ``PATHWAY_ALERT_SLACK_TOKEN``); same delivery discipline as the
        webhook sink, posting through ``pw.io.slack``'s chat.postMessage."""
        return os.environ.get("PATHWAY_ALERT_SLACK_CHANNEL") or None

    @property
    def alert_slack_token(self) -> str | None:
        """Slack bot token for the alert notification sink."""
        return os.environ.get("PATHWAY_ALERT_SLACK_TOKEN") or None

    @property
    def alert_watermark_stall_s(self) -> float:
        """Watermark-stall detector: an input whose watermark lags this many
        seconds (after ingesting rows) raises ``watermark_stall``."""
        return max(1.0, _env_float("PATHWAY_ALERT_WATERMARK_STALL_S", 120.0))

    @property
    def alert_error_rate(self) -> float:
        """Error-rate-spike detector: fraction of a route's requests failing
        (4xx/timeouts) over the fast window that raises
        ``error_rate_spike``."""
        v = _env_float("PATHWAY_ALERT_ERROR_RATE", 0.10)
        if not 0.0 < v <= 1.0:
            raise ValueError(
                f"PATHWAY_ALERT_ERROR_RATE must be in (0, 1], got {v}"
            )
        return v

    @property
    def alert_backlog_rows(self) -> int:
        """Backlog-growth detector: queued rows past this bound AND rising
        raise ``backlog_growth``."""
        return max(1, _env_int("PATHWAY_ALERT_BACKLOG_ROWS", 100000))

    @property
    def alert_thrash_decisions(self) -> int:
        """Autoscaler-thrash detector: membership version changes within the
        slow window that raise ``autoscaler_thrash``."""
        return max(1, _env_int("PATHWAY_ALERT_THRASH_DECISIONS", 3))

    @property
    def alert_heartbeat_flaps(self) -> int:
        """Heartbeat-flap detector: heartbeat misses accumulating within the
        fast window that raise ``heartbeat_flap``."""
        return max(1, _env_int("PATHWAY_ALERT_HEARTBEAT_FLAPS", 3))

    @property
    def alert_sink_stall_s(self) -> float:
        """Sink-commit-stall detector: a staged-but-unpublished delivery epoch
        older than this many seconds raises ``sink_commit_stall`` (the sink's
        transport keeps failing and output is piling up in the ledger)."""
        return max(1.0, _env_float("PATHWAY_ALERT_SINK_STALL_S", 120.0))

    # ---- pod timeline & bottleneck plane (observability) --------------------
    @property
    def timeline(self) -> str:
        """Pod timeline plane (``observability/timeline.py``): ``on``
        (default) samples every registered gauge/counter delta and histogram
        positional delta on a fixed cadence into bounded in-memory rings,
        piggybacks compressed series summaries on heartbeats so the
        coordinator holds a merged pod timeline, and feeds the bottleneck
        attributor. ``off`` constructs no plane — one flag read on the hot
        path, history and /timeline simply absent."""
        raw = os.environ.get("PATHWAY_TIMELINE", "on").strip().lower()
        if raw in ("", "1", "true", "yes", "on"):
            return "on"
        if raw in ("0", "false", "no", "off"):
            return "off"
        raise ValueError(f"PATHWAY_TIMELINE must be off/on, got {raw!r}")

    @property
    def timeline_window_s(self) -> float:
        """In-memory timeline history retained per process (seconds); older
        points fall off the ring (spilled segment files keep going until
        rotation)."""
        return max(10.0, _env_float("PATHWAY_TIMELINE_WINDOW_S", 600.0))

    @property
    def timeline_step_ms(self) -> int:
        """Timeline sampling cadence (milliseconds between ticks of the
        recorder — each tick captures one delta sample of every probe)."""
        return max(100, _env_int("PATHWAY_TIMELINE_STEP_MS", 1000))

    @property
    def timeline_dir(self) -> str | None:
        """Timeline segment spill directory: each process appends its sampled
        points as rotating OTLP-metrics-JSON lines (r8 file-sink discipline)
        so the history survives a crash alongside the flight recorder. Unset
        = in-memory rings only."""
        return os.environ.get("PATHWAY_TIMELINE_DIR") or None

    @property
    def timeline_rotate_mb(self) -> float:
        """Timeline segment rotation bound (MiB): past this size the live
        segment is renamed to ``.1`` (one rotation generation kept, matching
        the trace file sink)."""
        return max(0.05, _env_float("PATHWAY_TIMELINE_ROTATE_MB", 32.0))

    # ---- exactly-once delivery (r22) ----------------------------------------
    @property
    def delivery(self) -> str:
        """Default delivery mode for sink writers that don't pass an explicit
        ``delivery=``: ``off`` (direct at-least-once writes) or
        ``exactly_once`` (epoch-transactional through the delivery ledger)."""
        v = os.environ.get("PATHWAY_DELIVERY", "off")
        if v not in ("off", "exactly_once"):
            raise ValueError(
                f"PATHWAY_DELIVERY must be 'off' or 'exactly_once', got {v!r}"
            )
        return v

    @property
    def delivery_stage_rows(self) -> int:
        """Rows per staged ledger chunk (the r13 chunk-store discipline:
        bounded put sizes however large one epoch's output gets)."""
        return max(1, _env_int("PATHWAY_DELIVERY_STAGE_ROWS", 65536))

    @property
    def delivery_max_staged_epochs(self) -> int:
        """Backpressure bound on staged-but-unpublished epochs per sink: past
        this depth the run fails rather than staging unbounded output against
        a sink that never accepts it."""
        return max(1, _env_int("PATHWAY_DELIVERY_MAX_STAGED_EPOCHS", 512))

    # ---- helpers ------------------------------------------------------------
    @property
    def total_workers(self) -> int:
        return self.threads * self.processes

    def spawn_env(self, process_id: int) -> dict[str, str]:
        """Env block for a child process of ``pathway_tpu spawn``."""
        env = dict(os.environ)
        env["PATHWAY_THREADS"] = str(self.threads)
        env["PATHWAY_PROCESSES"] = str(self.processes)
        env["PATHWAY_PROCESS_ID"] = str(process_id)
        env["PATHWAY_FIRST_PORT"] = str(self.first_port)
        return env

    def to_dict(self) -> dict[str, Any]:
        return {
            name: getattr(self, name)
            for name in (
                "threads",
                "processes",
                "process_id",
                "first_port",
                "barrier_timeout",
                "heartbeat_interval",
                "heartbeat_timeout",
                "fault_plan",
                "elastic",
                "elastic_min_processes",
                "elastic_max_processes",
                "elastic_high_pressure",
                "elastic_low_pressure",
                "elastic_sustain_ticks",
                "elastic_cooldown_s",
                "persistent_storage",
                "replay_storage",
                "replay_mode",
                "continue_after_replay",
                "terminate_on_error",
                "runtime_typechecking",
                "flow",
                "flow_policy",
                "flow_bulk_min_rows",
                "flow_bulk_max_rows",
                "input_queue_rows",
                "latency_slo_ms",
                "serve_max_inflight",
                "serve_coalesce_ms",
                "serve_coalesce_rows",
                "serve_tick",
                "serve_rate",
                "serve_burst",
                "serve_api_keys",
                "fabric",
                "fabric_port_stride",
                "fabric_max_staleness_ms",
                "fabric_timeout",
                "replica",
                "replica_max_staleness_ms",
                "replica_memo_share",
                "shardmap",
                "shardmap_migration",
                "monitoring_server",
                "profile",
                "index_snapshot",
                "index_hot_rows",
                "audit",
                "audit_sample",
                "lineage_keys",
                "request_trace",
                "request_trace_slow_ms",
                "request_trace_keep",
                "request_trace_kept",
                "flight_dir",
                "health",
                "health_eval_ms",
                "slo_availability",
                "slo_p99_ms",
                "slo_fast_window_s",
                "slo_slow_window_s",
                "slo_burn_fast",
                "slo_burn_slow",
                "slo_burn_ticket_fast",
                "slo_burn_ticket_slow",
                "canary_interval_ms",
                "canary_timeout_ms",
                "incident_dir",
                "alert_webhook",
                "alert_slack_channel",
                "alert_slack_token",
                "alert_watermark_stall_s",
                "alert_error_rate",
                "alert_backlog_rows",
                "alert_thrash_decisions",
                "alert_heartbeat_flaps",
                "alert_sink_stall_s",
                "timeline",
                "timeline_window_s",
                "timeline_step_ms",
                "timeline_dir",
                "timeline_rotate_mb",
                "delivery",
                "delivery_stage_rows",
                "delivery_max_staged_epochs",
                "run_id",
                "engine_phases",
                "device_exchange_fused",
                "arrange_device_cache",
                "arrange_donate",
                "fuse",
                "fuse_jax",
                "fuse_jax_min_rows",
            )
        }


pathway_config = PathwayConfig()


def get_pathway_config() -> PathwayConfig:
    return pathway_config


def set_license_key(key: str | None) -> None:
    """Reference API parity (``pw.set_license_key``) — licensing is not
    replicated (BUSL gating has no TPU-build equivalent); accepted and ignored."""
