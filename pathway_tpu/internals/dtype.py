"""Static dtype lattice for the declarative layer.

Plays the role of the reference's dtype system (``python/pathway/internals/dtype.py``:
INT/FLOAT/BOOL/STR/BYTES/NONE/ANY/Array/Pointer/Optional/Tuple/List/Json/Callable/
Duration/DateTimeNaive/DateTimeUtc/Future/PyObjectWrapper with ``is_subtype``-driven
unification), re-targeted at a columnar engine: every dtype maps onto a numpy storage
class so delta blocks stay vectorizable and, where numeric, JAX-ingestible.
"""

from __future__ import annotations

import datetime
import typing
from abc import ABC
from typing import Any, Callable, Iterable

import numpy as np


class DType(ABC):
    """Base of the static type lattice."""

    _name: str = "DType"

    def __repr__(self) -> str:
        return self._name

    @property
    def typehint(self) -> Any:
        return Any

    # numpy storage dtype for engine columns
    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(object)

    def is_optional(self) -> bool:
        return False

    @property
    def wrapped(self) -> DType:
        return self

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other) and self._key() == other._key()  # type: ignore[attr-defined]

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._key()))

    def _key(self) -> tuple:
        return ()

    def equivalent_to(self, other: DType) -> bool:
        return dtype_equivalence(self, other)


class _SimpleDType(DType):
    def __init__(self, name: str, np_dtype: np.dtype, typehint: Any):
        self._name = name
        self._np = np_dtype
        self._hint = typehint

    def _key(self) -> tuple:
        return (self._name,)

    @property
    def np_dtype(self) -> np.dtype:
        return self._np

    @property
    def typehint(self) -> Any:
        return self._hint


INT = _SimpleDType("INT", np.dtype(np.int64), int)
FLOAT = _SimpleDType("FLOAT", np.dtype(np.float64), float)
BOOL = _SimpleDType("BOOL", np.dtype(np.bool_), bool)
STR = _SimpleDType("STR", np.dtype(object), str)
BYTES = _SimpleDType("BYTES", np.dtype(object), bytes)
NONE = _SimpleDType("NONE", np.dtype(object), type(None))
ANY = _SimpleDType("ANY", np.dtype(object), Any)
DURATION = _SimpleDType("DURATION", np.dtype("timedelta64[ns]"), datetime.timedelta)
DATE_TIME_NAIVE = _SimpleDType("DATE_TIME_NAIVE", np.dtype("datetime64[ns]"), datetime.datetime)
DATE_TIME_UTC = _SimpleDType("DATE_TIME_UTC", np.dtype("datetime64[ns]"), datetime.datetime)
JSON = _SimpleDType("JSON", np.dtype(object), Any)
PY_OBJECT_WRAPPER = _SimpleDType("PY_OBJECT_WRAPPER", np.dtype(object), Any)


class Pointer(DType):
    """Row-reference dtype; stored as uint64 key columns (engine keys are 64-bit
    splitmix/blake2 hashes — the TPU-side analogue of the reference's
    ``Key(u128)`` at ``src/engine/value.rs:41``)."""

    def __init__(self, *args: Any):
        self.args = args
        self._name = "Pointer"

    def __repr__(self) -> str:
        return "Pointer"

    def _key(self) -> tuple:
        return ()  # all pointers unify

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.uint64)

    @property
    def typehint(self) -> Any:
        return Pointer


POINTER = Pointer()


class Optional(DType):
    def __new__(cls, wrapped: DType):
        wrapped = wrap(wrapped)
        if isinstance(wrapped, Optional) or wrapped in (NONE, ANY):
            return wrapped
        self = object.__new__(cls)
        self._wrapped = wrapped
        return self

    def __repr__(self) -> str:
        return f"Optional({self._wrapped!r})"

    def _key(self) -> tuple:
        return (self._wrapped,)

    def is_optional(self) -> bool:
        return True

    @property
    def wrapped(self) -> DType:
        return self._wrapped

    @property
    def np_dtype(self) -> np.dtype:
        # optionality forces object storage for value types that can't hold NaN/NaT
        if self._wrapped in (FLOAT, DATE_TIME_NAIVE, DATE_TIME_UTC, DURATION):
            return self._wrapped.np_dtype
        return np.dtype(object)

    @property
    def typehint(self) -> Any:
        return typing.Optional[self._wrapped.typehint]


class Tuple(DType):
    """Fixed-arity heterogeneous tuple."""

    def __init__(self, *args: Any):
        self.args = tuple(wrap(a) for a in args)
        self._name = f"Tuple{self.args}"

    def _key(self) -> tuple:
        return self.args

    def __repr__(self) -> str:
        return f"Tuple[{', '.join(map(repr, self.args))}]"


ANY_TUPLE = Tuple()  # sentinel for unknown-arity tuples


class List(DType):
    def __init__(self, arg: Any):
        self.wrapped_ = wrap(arg)

    def _key(self) -> tuple:
        return (self.wrapped_,)

    def __repr__(self) -> str:
        return f"List[{self.wrapped_!r}]"


class Array(DType):
    """N-dim numeric array dtype (ndarray columns; the TPU-native payload)."""

    def __init__(self, n_dim: int | None = None, wrapped: DType = ANY):
        self.n_dim = n_dim
        self.wrapped_ = wrapped if isinstance(wrapped, DType) else wrap(wrapped)

    def _key(self) -> tuple:
        return (self.n_dim, self.wrapped_)

    def __repr__(self) -> str:
        return f"Array({self.n_dim}, {self.wrapped_!r})"


ANY_ARRAY = Array()


class Callable_(DType):
    _name = "Callable"


CALLABLE = Callable_()


class Future(DType):
    """Value may still be Pending — result of fully-async UDFs (reference:
    ``internals/dtype.py`` Future + ``table.await_futures``)."""

    def __init__(self, wrapped: DType):
        self.wrapped_ = wrap(wrapped)

    def _key(self) -> tuple:
        return (self.wrapped_,)

    def __repr__(self) -> str:
        return f"Future({self.wrapped_!r})"


class DateTimeNaive(datetime.datetime):
    """Annotation alias (reference exposes ``pw.DateTimeNaive`` the same way)."""


class DateTimeUtc(datetime.datetime):
    pass


class Duration(datetime.timedelta):
    pass


_SIMPLE_FROM_HINT: dict[Any, DType] = {
    DateTimeNaive: DATE_TIME_NAIVE,
    DateTimeUtc: DATE_TIME_UTC,
    Duration: DURATION,
    int: INT,
    float: FLOAT,
    bool: BOOL,
    str: STR,
    bytes: BYTES,
    type(None): NONE,
    Any: ANY,
    datetime.timedelta: DURATION,
    datetime.datetime: DATE_TIME_NAIVE,
    np.int64: INT,
    np.int32: INT,
    np.float64: FLOAT,
    np.float32: FLOAT,
    np.bool_: BOOL,
    np.ndarray: ANY_ARRAY,
    dict: JSON,
}


def wrap(hint: Any) -> DType:
    """Coerce a python typehint / DType into a DType."""
    if isinstance(hint, DType):
        return hint
    if hint is None:
        return NONE
    from pathway_tpu.internals import json as pw_json

    if hint is pw_json.Json:
        return JSON
    if hint in _SIMPLE_FROM_HINT:
        return _SIMPLE_FROM_HINT[hint]
    if hint is Pointer:
        return POINTER
    origin = typing.get_origin(hint)
    if origin is not None:
        targs = typing.get_args(hint)
        import types as _types

        if origin is typing.Union or origin is _types.UnionType:
            non_none = [a for a in targs if a is not type(None)]
            if len(non_none) < len(targs):
                if len(non_none) == 1:
                    return Optional(wrap(non_none[0]))
                return ANY
            return ANY
        if origin in (tuple,):
            if len(targs) == 2 and targs[1] is Ellipsis:
                return List(wrap(targs[0]))
            return Tuple(*[wrap(a) for a in targs])
        if origin in (list,):
            return List(wrap(targs[0])) if targs else List(ANY)
        if origin is np.ndarray:
            return ANY_ARRAY
        if origin is Callable:
            return CALLABLE
        if origin is dict:
            return JSON
    if isinstance(hint, type) and issubclass(hint, np.ndarray):
        return ANY_ARRAY
    if callable(hint) and not isinstance(hint, type):
        return CALLABLE
    return ANY


def dtype_of_value(value: Any) -> DType:
    from pathway_tpu.internals import json as pw_json

    if value is None:
        return NONE
    if isinstance(value, bool) or isinstance(value, np.bool_):
        return BOOL
    if isinstance(value, (int, np.integer)):
        return INT
    if isinstance(value, (float, np.floating)):
        return FLOAT
    if isinstance(value, str):
        return STR
    if isinstance(value, bytes):
        return BYTES
    if isinstance(value, datetime.timedelta) or isinstance(value, np.timedelta64):
        return DURATION
    if isinstance(value, np.datetime64):
        return DATE_TIME_NAIVE
    if isinstance(value, datetime.datetime):
        return DATE_TIME_UTC if value.tzinfo is not None else DATE_TIME_NAIVE
    if isinstance(value, np.ndarray):
        return Array(value.ndim, wrap(value.dtype.type) if value.dtype.kind in "ifb" else ANY)
    if isinstance(value, pw_json.Json):
        return JSON
    if isinstance(value, tuple):
        return Tuple(*[dtype_of_value(v) for v in value])
    if isinstance(value, list):
        return List(ANY)
    if isinstance(value, dict):
        return JSON
    return ANY


def is_subtype(sub: DType, sup: DType) -> bool:
    """Subtype check driving schema compatibility (mirrors the reference's
    ``dtype.is_subtype`` role in unification)."""
    if sup == ANY or sub == sup:
        return True
    if sub == ANY:
        return False
    if isinstance(sup, Optional):
        if sub == NONE:
            return True
        return is_subtype(sub.wrapped if isinstance(sub, Optional) else sub, sup.wrapped)
    if isinstance(sub, Optional):
        return False
    if sub == INT and sup == FLOAT:
        return True
    if isinstance(sub, Pointer) and isinstance(sup, Pointer):
        return True
    if isinstance(sub, Tuple) and sup == ANY_TUPLE:
        return True
    if isinstance(sub, Tuple) and isinstance(sup, Tuple):
        return len(sub.args) == len(sup.args) and all(
            is_subtype(a, b) for a, b in zip(sub.args, sup.args)
        )
    if isinstance(sub, List) and isinstance(sup, List):
        return is_subtype(sub.wrapped_, sup.wrapped_)
    if isinstance(sub, Tuple) and isinstance(sup, List):
        return all(is_subtype(a, sup.wrapped_) for a in sub.args)
    if isinstance(sub, Array) and isinstance(sup, Array):
        if sup.n_dim is not None and sub.n_dim != sup.n_dim:
            return False
        return is_subtype(sub.wrapped_, sup.wrapped_) or sup.wrapped_ == ANY
    return False


def types_lca(a: DType, b: DType, raising: bool = False) -> DType:
    """Least common ancestor — unification for if_else/coalesce/concat."""
    if a == b:
        return a
    if is_subtype(a, b):
        return b
    if is_subtype(b, a):
        return a
    if a == NONE:
        return Optional(b)
    if b == NONE:
        return Optional(a)
    if isinstance(a, Optional) or isinstance(b, Optional):
        inner = types_lca(a.wrapped, b.wrapped, raising=False)
        return Optional(inner)
    if {a, b} == {INT, FLOAT}:
        return FLOAT
    if isinstance(a, Tuple) and isinstance(b, Tuple):
        if len(a.args) == len(b.args):
            return Tuple(*[types_lca(x, y) for x, y in zip(a.args, b.args)])
        return ANY_TUPLE
    if isinstance(a, Array) and isinstance(b, Array):
        return Array(a.n_dim if a.n_dim == b.n_dim else None, types_lca(a.wrapped_, b.wrapped_))
    if raising:
        raise TypeError(f"cannot unify dtypes {a!r} and {b!r}")
    return ANY


def unoptionalize(d: DType) -> DType:
    return d.wrapped if isinstance(d, Optional) else d


def normalize_pointers(dtypes: Iterable[DType]) -> list[DType]:
    return [POINTER if isinstance(d, Pointer) else d for d in dtypes]


def coerce_scalar_to(value: Any, d: DType) -> Any:
    """Best-effort scalar coercion used when building columns of a known dtype."""
    if value is None:
        return None
    if d == INT:
        return int(value)
    if d == FLOAT:
        return float(value)
    if d == BOOL:
        return bool(value)
    if d == STR:
        return str(value) if not isinstance(value, str) else value
    return value
