"""OTLP-shaped trace + metrics export (span-per-run + span-per-operator,
gauge-per-probe).

Offline counterpart of the reference's OpenTelemetry pipeline
(``src/engine/telemetry.rs:42-47`` builds OTLP trace+metrics exporters over
tonic/gRPC; ``graph_runner/telemetry.py`` opens ``graph_runner.run`` spans with
graph-statistics attributes). This image has zero egress, so instead of a
collector endpoint the run writes one OTLP/JSON document
(``ExportTraceServiceRequest`` shape — the same JSON an OTLP file exporter or
``otlp-json`` collector receiver consumes) to a file:

- root span ``pathway.run`` carrying run-level attributes (workers, operator
  count, row totals),
- one child span per operator with its rows/busy-time/latency/lag probes
  (the ``OperatorStats`` analogue, ``src/engine/graph.rs:497-527``).

Metrics export alongside (r5, VERDICT r4 #9 — the reference ships OTLP traces
AND metrics, ``telemetry.rs:42-47``): an ``ExportMetricsServiceRequest``-shaped
JSON document with per-operator rows/busy/latency/lag gauges plus run totals,
the same data the Prometheus endpoint renders as text.

Enable with ``pw.set_monitoring_config(trace_file=..., metrics_file=...)`` or
``PATHWAY_TRACE_FILE=...`` / ``PATHWAY_METRICS_FILE=...``.
"""

from __future__ import annotations

import json
import os
import secrets
from typing import Any

_UNSET = object()
_DISABLED = object()

_trace_file_override: Any = _UNSET
_metrics_file_override: Any = _UNSET

# -- resilience event log ------------------------------------------------------
# Cross-cutting recovery events (heartbeat-miss, checkpoint-epoch-committed,
# replay, fault injection, supervised restart) recorded by whichever subsystem
# observes them and exported through the SAME OTLP trace/metrics documents as
# the operator stats — so a recovery is visible in the run's own telemetry
# (ISSUE 2 satellite; reference: telemetry.rs exports trace AND metrics).

import threading as _threading
import time as _time_mod

#: bound on the retained raw events — long streaming runs commit an epoch per
#: tick with moving offsets (~50/s at the default autocommit), so the raw log
#: keeps only the most recent window while the counters below stay exact
_EVENTS_MAX = 4096

_events: list[dict] = []
_events_lock = _threading.Lock()
_counters: dict[str, int] = {}
_last_epoch: int | None = None
_replayed_total = 0


def record_event(kind: str, **attrs: Any) -> dict:
    """Record one resilience/lifecycle event. ``kind`` is a dotted name like
    ``resilience.heartbeat_miss``; attrs must be OTLP-attribute-friendly
    scalars. The raw log is bounded (oldest dropped past ``_EVENTS_MAX``);
    per-kind counters and the epoch/replay aggregates are exact regardless."""
    global _last_epoch, _replayed_total
    ev = {"kind": kind, "ts_ns": _time_mod.time_ns(), "attrs": dict(attrs)}
    with _events_lock:
        _events.append(ev)
        if len(_events) > _EVENTS_MAX:
            del _events[: len(_events) - _EVENTS_MAX]
        _counters[kind] = _counters.get(kind, 0) + 1
        if kind == "resilience.epoch_committed":
            _last_epoch = attrs.get("epoch", _last_epoch)
        elif kind == "resilience.replay":
            _replayed_total += int(attrs.get("events", 0))
    return ev


def events(kind: str | None = None) -> list[dict]:
    with _events_lock:
        snap = list(_events)
    if kind is None:
        return snap
    return [e for e in snap if e["kind"] == kind]


def clear_events() -> None:
    """Reset the event log and aggregates — called at the start of every
    ``pw.run`` so /status and the exported documents describe THIS run."""
    global _last_epoch, _replayed_total
    with _events_lock:
        _events.clear()
        _counters.clear()
        _last_epoch = None
        _replayed_total = 0


def resilience_summary() -> dict[str, Any]:
    """Aggregate view of the recorded events (monitoring /status + metrics)."""
    with _events_lock:
        counters = dict(_counters)
        last_epoch = _last_epoch
        replayed = _replayed_total
    return {
        "heartbeat_misses": counters.get("resilience.heartbeat_miss", 0),
        "last_committed_epoch": last_epoch,
        "replayed_events": replayed,
        "restarts": counters.get("resilience.restart", 0),
        "faults_injected": sum(
            v for k, v in counters.items() if k.startswith("resilience.fault")
        ),
        "events": sum(counters.values()),
    }


def set_monitoring_config(*, trace_file: Any = _UNSET, metrics_file: Any = _UNSET) -> None:
    """Runtime override of the trace/metrics destinations (reference:
    ``pw.set_monitoring_config(monitoring_server=...)``). Only explicitly
    passed knobs change their setting — calls configuring other knobs leave
    the rest untouched. An explicit ``None`` DISABLES that export even when
    the corresponding ``PATHWAY_*_FILE`` env var is set."""
    global _trace_file_override, _metrics_file_override
    if trace_file is not _UNSET:
        _trace_file_override = _DISABLED if trace_file is None else trace_file
    if metrics_file is not _UNSET:
        _metrics_file_override = _DISABLED if metrics_file is None else metrics_file


def trace_file() -> str | None:
    if _trace_file_override is _DISABLED:
        return None
    if _trace_file_override is not _UNSET:
        return _trace_file_override
    return os.environ.get("PATHWAY_TRACE_FILE") or None


def metrics_file() -> str | None:
    if _metrics_file_override is _DISABLED:
        return None
    if _metrics_file_override is not _UNSET:
        return _metrics_file_override
    return os.environ.get("PATHWAY_METRICS_FILE") or None


def maybe_export_run_trace(runtime, start_ns: int) -> None:
    """Shared run-end hook (both the batch and interactive pw.run paths):
    write the OTLP trace/metrics documents if destinations are configured,
    never raise."""
    import time as _time

    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()

    def ranked(path: str) -> str:
        # multi-process cluster runs share one env: suffix by process id so
        # ranks don't clobber one file (same rule as the monitoring HTTP port)
        return f"{path}.p{cfg.process_id}" if cfg.processes > 1 else path

    path = trace_file()
    if path:
        try:
            export_run_trace(runtime, ranked(path), start_ns, _time.time_ns())
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "trace export to %s failed", path, exc_info=True
            )
    mpath = metrics_file()
    if mpath:
        try:
            export_run_metrics(runtime, ranked(mpath), _time.time_ns())
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "metrics export to %s failed", mpath, exc_info=True
            )


def _attr(key: str, value: Any) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def export_run_trace(
    runtime, path: str, start_ns: int, end_ns: int
) -> dict:
    """Write one OTLP/JSON trace document for a finished (or stopping) run;
    returns the document (tests introspect it)."""
    from pathway_tpu import observability as _obs
    from pathway_tpu.internals.config import get_pathway_config
    from pathway_tpu.internals.monitoring import run_stats

    stats = run_stats(runtime)
    # trace id derives from PATHWAY_RUN_ID when set (spawn exports one per
    # cluster launch), so every process's offline doc — and the live span
    # plane — stitch under ONE trace; the deterministic root-span id lets
    # peers parent their subtree to process 0's root without coordination
    cfg = get_pathway_config()
    trace_id = _obs.run_trace_id()
    shared_root = _obs.spans.derive_root_span_id(trace_id)
    if cfg.processes > 1 and cfg.process_id != 0 and cfg.run_id:
        # only with a shared run id does process 0 emit the span this parent
        # id names — without one, trace ids are per-process random and a
        # parent link would dangle (orphaned subtree in Perfetto)
        root_id = secrets.token_hex(8)
        root_span = {
            "traceId": trace_id,
            "spanId": root_id,
            "parentSpanId": shared_root,
            "name": f"pathway.run.p{cfg.process_id}",
        }
    else:
        root_id = shared_root
        root_span = {"traceId": trace_id, "spanId": root_id, "name": "pathway.run"}
    root_span.update(
        {
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [
                _attr("pathway.n_operators", len(stats["operators"])),
                _attr("pathway.rows_in_total", stats["rows_in_total"]),
                _attr("pathway.rows_out_total", stats["rows_out_total"]),
                _attr("pathway.process_id", cfg.process_id),
                _attr(
                    "pathway.n_workers",
                    len(getattr(runtime, "workers", None) or []) or 1,
                ),
            ],
        }
    )
    spans = [root_span]
    for op in stats["operators"]:
        attrs = [
            _attr("pathway.operator.id", op["id"]),
            _attr("pathway.operator.rows_in", op["rows_in"]),
            _attr("pathway.operator.rows_out", op["rows_out"]),
            _attr("pathway.operator.busy_ms", op["time_ms"]),
            _attr("pathway.operator.latency_ms", op["latency_ms"]),
        ]
        if op.get("lag") is not None:
            attrs.append(_attr("pathway.operator.lag", op["lag"]))
        spans.append(
            {
                "traceId": trace_id,
                "spanId": secrets.token_hex(8),
                "parentSpanId": root_id,
                "name": f"operator/{op['operator']}",
                "kind": 1,
                "startTimeUnixNano": str(start_ns),
                "endTimeUnixNano": str(end_ns),
                "attributes": attrs,
            }
        )
    # resilience events ride the same trace as zero-duration child spans so a
    # recovery (replay, heartbeat miss, epoch commit) is visible inline with
    # the operators it affected
    for ev in events():
        spans.append(
            {
                "traceId": trace_id,
                "spanId": secrets.token_hex(8),
                "parentSpanId": root_id,
                "name": f"event/{ev['kind']}",
                "kind": 1,
                "startTimeUnixNano": str(ev["ts_ns"]),
                "endTimeUnixNano": str(ev["ts_ns"]),
                "attributes": [_attr(k, v) for k, v in ev["attrs"].items()],
            }
        )
    doc = {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        _attr("service.name", "pathway_tpu"),
                        _attr("process.pid", os.getpid()),
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "pathway_tpu.run", "version": "1"},
                        "spans": spans,
                    }
                ],
            }
        ]
    }
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return doc


def export_spans(
    path: str,
    spans_in: list[tuple[str, int, int, dict]],
    *,
    scope: str = "pathway_tpu.resilience",
    root_name: str | None = None,
) -> dict:
    """Write a standalone OTLP/JSON trace document from (name, start_ns,
    end_ns, attrs) tuples — used by processes that have no engine runtime
    (e.g. the ``resilience.Supervisor`` parent recording restart spans).
    Returns the document."""
    trace_id = secrets.token_hex(16)
    root_id = None
    spans: list[dict] = []
    if root_name is not None and spans_in:
        root_id = secrets.token_hex(8)
        spans.append(
            {
                "traceId": trace_id,
                "spanId": root_id,
                "name": root_name,
                "kind": 1,
                "startTimeUnixNano": str(min(s[1] for s in spans_in)),
                "endTimeUnixNano": str(max(s[2] for s in spans_in)),
                "attributes": [],
            }
        )
    for name, start_ns, end_ns, attrs in spans_in:
        span = {
            "traceId": trace_id,
            "spanId": secrets.token_hex(8),
            "name": name,
            "kind": 1,
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [_attr(k, v) for k, v in attrs.items()],
        }
        if root_id is not None:
            span["parentSpanId"] = root_id
        spans.append(span)
    doc = {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        _attr("service.name", "pathway_tpu"),
                        _attr("process.pid", os.getpid()),
                    ]
                },
                "scopeSpans": [{"scope": {"name": scope, "version": "1"}, "spans": spans}],
            }
        ]
    }
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return doc


def export_run_metrics(runtime, path: str, ts_ns: int) -> dict:
    """Write one OTLP/JSON metrics document (``ExportMetricsServiceRequest``
    shape — the file/collector form of the reference's OTLP metrics pipeline,
    ``src/engine/telemetry.rs:42-47``): per-operator rows/busy/latency/lag
    gauges + run totals. Returns the document (tests introspect it)."""
    from pathway_tpu.internals.monitoring import run_stats

    stats = run_stats(runtime)
    t = str(ts_ns)

    def point(value: Any, attrs: list[dict]) -> dict:
        key = "asInt" if isinstance(value, int) else "asDouble"
        v: Any = str(value) if isinstance(value, int) else float(value)
        return {"timeUnixNano": t, key: v, "attributes": attrs}

    def gauge(name: str, unit: str, points: list[dict]) -> dict:
        return {"name": name, "unit": unit, "gauge": {"dataPoints": points}}

    per_op: dict[str, list[dict]] = {
        "pathway.operator.rows_in": [],
        "pathway.operator.rows_out": [],
        "pathway.operator.busy_ms": [],
        "pathway.operator.latency_ms": [],
        "pathway.operator.lag": [],
    }
    for op in stats["operators"]:
        attrs = [
            _attr("pathway.operator", op["operator"]),
            _attr("pathway.operator.id", op["id"]),
        ]
        per_op["pathway.operator.rows_in"].append(point(int(op["rows_in"]), attrs))
        per_op["pathway.operator.rows_out"].append(point(int(op["rows_out"]), attrs))
        per_op["pathway.operator.busy_ms"].append(point(float(op["time_ms"]), attrs))
        per_op["pathway.operator.latency_ms"].append(
            point(float(op["latency_ms"]), attrs)
        )
        if op.get("lag") is not None:
            per_op["pathway.operator.lag"].append(point(int(op["lag"]), attrs))
    metrics = [
        gauge("pathway.rows_in_total", "{rows}", [point(int(stats["rows_in_total"]), [])]),
        gauge("pathway.rows_out_total", "{rows}", [point(int(stats["rows_out_total"]), [])]),
        gauge("pathway.operator.rows_in", "{rows}", per_op["pathway.operator.rows_in"]),
        gauge("pathway.operator.rows_out", "{rows}", per_op["pathway.operator.rows_out"]),
        gauge("pathway.operator.busy_ms", "ms", per_op["pathway.operator.busy_ms"]),
        gauge(
            "pathway.operator.latency_ms", "ms", per_op["pathway.operator.latency_ms"]
        ),
    ]
    if per_op["pathway.operator.lag"]:
        metrics.append(gauge("pathway.operator.lag", "1", per_op["pathway.operator.lag"]))
    res = resilience_summary()
    if res["events"]:
        metrics.append(
            gauge(
                "pathway.resilience.heartbeat_misses",
                "1",
                [point(int(res["heartbeat_misses"]), [])],
            )
        )
        metrics.append(
            gauge(
                "pathway.resilience.replayed_events",
                "{rows}",
                [point(int(res["replayed_events"]), [])],
            )
        )
        metrics.append(
            gauge(
                "pathway.resilience.restarts", "1", [point(int(res["restarts"]), [])]
            )
        )
        if res["last_committed_epoch"] is not None:
            metrics.append(
                gauge(
                    "pathway.resilience.last_committed_epoch",
                    "1",
                    [point(int(res["last_committed_epoch"]), [])],
                )
            )
    doc = {
        "resourceMetrics": [
            {
                "resource": {
                    "attributes": [
                        _attr("service.name", "pathway_tpu"),
                        _attr("process.pid", os.getpid()),
                    ]
                },
                "scopeMetrics": [
                    {
                        "scope": {"name": "pathway_tpu.run", "version": "1"},
                        "metrics": metrics,
                    }
                ],
            }
        ]
    }
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return doc
