"""Interactive (notebook) mode — ``pw.enable_interactive_mode()`` +
``pw.live(table)`` (reference: ``internals/interactive.py`` LiveTables).

With interactive mode on, ``pw.run()`` starts the runtime on a daemon thread
and returns immediately with a handle; ``LiveTable`` objects subscribe to
their table and keep a pandas snapshot that notebooks re-render as updates
stream in."""

from __future__ import annotations

import threading
from typing import Any

_interactive = False


def enable_interactive_mode() -> None:
    global _interactive
    _interactive = True


def is_interactive_mode_enabled() -> bool:
    return _interactive


class InteractiveRunHandle:
    """Returned by ``pw.run()`` in interactive mode."""

    def __init__(self, runtime, thread: threading.Thread, on_finish=None):
        self._runtime = runtime
        self._thread = thread
        self._on_finish = on_finish

    def _finish(self) -> None:
        if self._on_finish is not None and not self._thread.is_alive():
            cb, self._on_finish = self._on_finish, None
            cb()

    def stop(self, timeout: float = 10.0) -> None:
        self._runtime.request_stop()
        self._thread.join(timeout)
        self._finish()

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)
        self._finish()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()


class LiveTable:
    """A live, auto-updating snapshot of a table (create BEFORE ``pw.run``)."""

    def __init__(self, table: Any):
        from pathway_tpu.io._subscribe import subscribe

        self._columns = table.column_names()
        self._rows: dict[int, tuple] = {}
        self._lock = threading.Lock()
        self.version = 0

        def on_change(key, row, time, is_addition):
            with self._lock:
                if is_addition:
                    self._rows[int(key)] = tuple(row[c] for c in self._columns)
                else:
                    self._rows.pop(int(key), None)
                self.version += 1

        subscribe(table, on_change)

    def to_pandas(self):
        import pandas as pd

        with self._lock:
            rows = dict(self._rows)
        return pd.DataFrame.from_dict(
            rows, orient="index", columns=self._columns
        ).sort_index()

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def _repr_html_(self) -> str:
        return self.to_pandas()._repr_html_()

    def __repr__(self) -> str:
        return repr(self.to_pandas())


def live(table: Any) -> LiveTable:
    return LiveTable(table)
