"""Monitoring: per-operator stats, console dashboard, HTTP/Prometheus endpoint.

Role of the reference's monitoring stack (``internals/monitoring.py:22-271``
dashboard + ``src/engine/http_server.rs:25-77`` metrics server): engine nodes
already count rows in/out and processing time; this module aggregates them into

- a console summary (``monitoring_level`` AUTO/IN_OUT/ALL — AUTO prints only on
  a TTY, NONE is silent),
- ``/status`` (JSON) and ``/metrics`` (Prometheus text exposition) served by a
  daemon-thread HTTP server while the run is live (``with_http_server=True``;
  port from ``PATHWAY_MONITORING_HTTP_PORT``, default 20000).
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any


def scheduler_stats(scheduler) -> list[dict[str, Any]]:
    """Per-operator counters from a live or finished scheduler. Sharded and
    cluster runtimes expose per-worker graphs; their counters aggregate by
    node position."""
    from pathway_tpu.observability.metrics import iter_graphs

    graphs = iter_graphs(scheduler)
    agg: dict[int, dict[str, Any]] = {}
    for g in graphs:
        for node in g.nodes:
            o = agg.get(node.node_index)
            if o is None:
                agg[node.node_index] = o = {
                    "id": node.node_index,
                    "operator": node.name,
                    "rows_in": 0,
                    "rows_out": 0,
                    "time_ms": 0.0,
                    "latency_ms": 0.0,
                    "last_time": -1,
                }
            o["rows_in"] += node.stats_rows_in
            o["rows_out"] += node.stats_rows_out
            o["time_ms"] = round(o["time_ms"] + node.stats_time_ns / 1e6, 3)
            # worker shards: worst (max) queue latency, most advanced tick
            o["latency_ms"] = round(
                max(o["latency_ms"], node.stats_latency_ewma_ms), 3
            )
            o["last_time"] = max(o["last_time"], node.stats_last_time)
    ops = [agg[i] for i in sorted(agg)]
    # lag (reference OperatorStats.lag): logical ticks behind the
    # most-advanced operator; operators that never saw data report no lag
    frontier = max((o["last_time"] for o in ops), default=-1)
    for o in ops:
        o["lag"] = (frontier - o["last_time"]) if o["last_time"] >= 0 else None
    return ops


#: operators shown at the in_out/auto levels: sources, sinks, and writers
_EDGE_OPERATORS = {"stream_input", "static_input", "subscribe", "capture", "output"}


def _visible_operators(ops: list[dict], level: str) -> list[dict]:
    """The operator rows a given monitoring level displays — shared by the
    live dashboard and the end-of-run summary so the two can never drift."""
    if level in ("in_out", "auto"):
        shown = [
            o
            for o in ops
            if o["operator"] in _EDGE_OPERATORS
            or o["operator"].split(":")[0].endswith("_write")
        ]
        return shown or ops
    return ops


def run_stats(runtime) -> dict[str, Any]:
    from pathway_tpu import observability as _obs
    from pathway_tpu.internals.telemetry import resilience_summary
    from pathway_tpu.observability.metrics import Histogram

    scheduler = getattr(runtime, "scheduler", None)
    ops = scheduler_stats(scheduler)
    def _q(snap, q):
        v = Histogram.quantile(snap, q)
        # the +Inf overflow bucket has no finite upper bound — keep /status
        # strict JSON (no Infinity literal)
        return None if v is None or v == float("inf") else v

    sink_lat = {}
    for label, snap in _obs.run_metrics().sink_snapshots().items():
        sink_lat[label] = {
            "count": snap["count"],
            "sum_s": round(snap["sum_s"], 6),
            "p50_s": _q(snap, 0.5),
            "p99_s": _q(snap, 0.99),
        }
    stats = {
        "alive": True,
        "current_time": getattr(scheduler, "current_time", None),
        "operators": ops,
        "rows_in_total": sum(o["rows_in"] for o in ops),
        "rows_out_total": sum(o["rows_out"] for o in ops),
        # live observability plane: per-input watermarks, queue/microbatch
        # backlogs, per-sink end-to-end latency summaries
        "watermarks": _obs.input_watermarks(scheduler),
        "backlogs": _obs.backlog_gauges(scheduler),
        "sink_latency": sink_lat,
        # recovery observability: heartbeat misses, committed checkpoint
        # epochs, replayed events and supervised restarts, from the same
        # event log the OTLP exports consume (``internals/telemetry.py``)
        "resilience": resilience_summary(),
    }
    # exactly-once delivery plane (r22): per-sink staged/published frontiers,
    # uncommitted-epoch depth and publish failures (present only when a sink
    # opted into delivery="exactly_once")
    from pathway_tpu import delivery as _delivery

    delivery_summary = _delivery.run_summary(runtime)
    if delivery_summary is not None:
        stats["delivery"] = delivery_summary
    # flow-control plane (PATHWAY_FLOW=on): per-input credit/occupancy/shed
    # counters and the AIMD controller's recent decisions — shedding is only
    # acceptable because every drop is visible here
    from pathway_tpu import flow as _flow

    flow_status = _flow.status(runtime)
    if flow_status is not None:
        stats["flow"] = flow_status
    # device profiling plane: per-callable compile/shape telemetry, pad-waste
    # ratios, memory attribution, host/device time split, recompile-storm
    # warnings (PATHWAY_PROFILE, on by default)
    stats["device"] = _obs.device.status_summary(runtime)
    # data-plane audit (PATHWAY_AUDIT, on by default): invariant violations,
    # shadow-audit divergences, per-operator-edge cardinality/selectivity,
    # lineage ring occupancy
    aud = _obs.audit.current()
    stats["audit"] = (
        aud.status_summary(runtime)
        if aud is not None
        else {"enabled": False, "mode": "off"}
    )
    # tiered-index plane: hot/cold residency, exact hot-hit ratio and
    # promotion/demotion counters (present only while a tiered index lives)
    ts = _obs.device.index_tier_stats()
    if ts is not None:
        stats["index"] = ts
    # REST serving plane: per-route request/response/shed counters, in-flight
    # occupancy vs budget, coalesced batch sizes and arrival-to-response
    # latency quantiles (present only while rest_connector routes are live)
    from pathway_tpu.io.http import _server as _rest_serve

    serving = _rest_serve.serving_status(runtime)
    if serving is not None:
        stats["serving"] = serving
    # request-scoped tracing plane: tail-sampling counters + the slowest-
    # request exemplars (id + per-stage latency decomposition) — the serving
    # section's "which queries are slow and where" answer
    rp = _obs.requests.current()
    if rp is not None:
        stats["request_trace"] = rp.status_summary()
        if serving is not None:
            stats["serving"]["slowest"] = rp.slowest_exemplars()
    # live error log: per-operator row-level failure counts (UDF raises under
    # terminate_on_error=False — previously only visible via pw.global_error_log())
    from pathway_tpu.internals import error_log as _error_log

    stats["errors"] = _error_log.summary()
    tracer = _obs.current()
    if tracer is not None:
        stats["trace"] = {
            "trace_id": tracer.trace_id,
            "sample": tracer.sample,
            "spans": tracer.buffer._seq,
        }
    server = getattr(runtime, "monitoring_server", None)
    if server is not None:
        stats["monitoring"] = {"host": server.host, "port": server.port}
    # coordinator of a cluster run: every process's summary, from the
    # telemetry piggybacked on heartbeats (observability.aggregate)
    cluster = _obs.aggregate.cluster_status(runtime)
    if cluster is not None:
        stats["cluster"] = cluster
    # elasticity plane (PATHWAY_ELASTIC): membership version/shape, pending
    # scale decisions, autoscaler streaks and the last reshard's movement
    from pathway_tpu import elastic as _elastic

    elastic = _elastic.status(runtime)
    if elastic is not None:
        stats["elastic"] = elastic
    # serving fabric (PATHWAY_FABRIC): this process's doors, forward health
    # and per-route replica rows/lag (also present — replica-only — when
    # serve_table runs without a cluster)
    from pathway_tpu import fabric as _fabric

    fabric = _fabric.status(runtime)
    if fabric is not None:
        stats["fabric"] = fabric
    # pod health & SLO plane (PATHWAY_HEALTH): door state machine, canary
    # probes, burn rates and the active-alert set
    from pathway_tpu.observability import health as _health

    health = _health.status(runtime)
    if health is not None:
        stats["health"] = health
    # pod timeline plane (PATHWAY_TIMELINE): ring occupancy + the ranked
    # bottleneck verdict (the series themselves are served by /timeline)
    from pathway_tpu.observability import bottleneck as _bottleneck
    from pathway_tpu.observability import timeline as _timeline

    tplane = _timeline.current()
    if tplane is not None:
        stats["timeline"] = tplane.status_summary()
        verdict = _bottleneck.status(runtime)
        if verdict is not None:
            stats["bottleneck"] = verdict
    # embedding memo counters (exact hits/misses/evictions + the pod-wide
    # shared tier) — sys.modules gate: no xpacks import unless the pipeline
    # already made one
    import sys as _sys

    _emb = _sys.modules.get("pathway_tpu.xpacks.llm.embedders")
    if _emb is not None:
        memo = _emb.memo_stats()
        if memo:
            stats["embedder_memo"] = memo
    return stats


def escape_label_value(value: Any) -> str:
    r"""Prometheus exposition label-value escaping: ``\`` → ``\\``, ``"`` →
    ``\"``, newline → ``\n`` (the spec's exhaustive list). Operator names come
    from user pipelines (UDF/table names ride along), so they can contain any
    of the three."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_label(**labels: Any) -> str:
    return ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels.items())


def prometheus_text(runtime) -> str:
    """Prometheus exposition format (``http_server.rs`` metric names adapted),
    extended with the live plane: per-input watermarks, backlog gauges and
    per-sink end-to-end latency histograms (fixed log-2 buckets)."""
    from pathway_tpu import observability as _obs
    from pathway_tpu.observability.metrics import BUCKET_BOUNDS_S

    stats = run_stats(runtime)
    metrics = [
        ("pathway_operator_rows_in_total", "Rows consumed by an operator", "rows_in", "counter"),
        ("pathway_operator_rows_out_total", "Rows emitted by an operator", "rows_out", "counter"),
        ("pathway_operator_time_ms", "Time spent inside an operator", "time_ms", "counter"),
        ("pathway_operator_latency_ms", "Input queue latency (EWMA) of an operator", "latency_ms", "gauge"),
        ("pathway_operator_lag", "Logical ticks behind the most-advanced operator", "lag", "gauge"),
    ]
    labels = [
        _fmt_label(operator=o["operator"], id=o["id"]) for o in stats["operators"]
    ]
    lines = []
    for name, help_text, field, mtype in metrics:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for o, label in zip(stats["operators"], labels):
            if o[field] is None:
                continue
            lines.append(f"{name}{{{label}}} {o[field]}")
    # ---- watermarks + ingest counters per input connector -------------------
    wms = stats["watermarks"]
    if wms:
        lines.append("# HELP pathway_input_watermark_unix_seconds Event-time (or ingest-time) watermark of an input connector")
        lines.append("# TYPE pathway_input_watermark_unix_seconds gauge")
        for w in wms:
            if w["watermark"] is not None:
                lines.append(
                    f'pathway_input_watermark_unix_seconds{{{_fmt_label(input=w["input"])}}} {w["watermark"]}'
                )
        lines.append("# HELP pathway_input_watermark_lag_seconds Now minus the input watermark")
        lines.append("# TYPE pathway_input_watermark_lag_seconds gauge")
        for w in wms:
            if w["lag_s"] is not None:
                lines.append(
                    f'pathway_input_watermark_lag_seconds{{{_fmt_label(input=w["input"])}}} {w["lag_s"]}'
                )
        lines.append("# HELP pathway_input_rows_ingested_total Rows ingested by an input connector")
        lines.append("# TYPE pathway_input_rows_ingested_total counter")
        for w in wms:
            lines.append(
                f'pathway_input_rows_ingested_total{{{_fmt_label(input=w["input"])}}} {w["rows_ingested"]}'
            )
    # ---- backlog gauges (connector queues + cross-tick microbatch buffers) --
    backlogs = stats["backlogs"]
    if backlogs:
        lines.append("# HELP pathway_backlog_rows Rows buffered in a connector queue or microbatch buffer")
        lines.append("# TYPE pathway_backlog_rows gauge")
        for b in backlogs:
            lines.append(
                f'pathway_backlog_rows{{{_fmt_label(queue=b["queue"])}}} {b["rows"]}'
            )
    # ---- flow-control plane (credits, sheds, controller) --------------------
    flow = stats.get("flow")
    if flow:
        lines.append("# HELP pathway_flow_queued_rows Rows holding credit in a connector ingest queue")
        lines.append("# TYPE pathway_flow_queued_rows gauge")
        for g in flow["inputs"]:
            lines.append(
                f'pathway_flow_queued_rows{{{_fmt_label(input=g["input"], service_class=g["service_class"])}}} {g["queued"] + g["in_flight"]}'
            )
        lines.append("# HELP pathway_flow_credits_available Remaining ingest credits of a connector queue")
        lines.append("# TYPE pathway_flow_credits_available gauge")
        for g in flow["inputs"]:
            avail = max(0, g["effective_bound"] - g["queued"] - g["in_flight"])
            lines.append(
                f'pathway_flow_credits_available{{{_fmt_label(input=g["input"])}}} {avail}'
            )
        lines.append("# HELP pathway_flow_shed_rows_total Rows dropped by the shed overflow policy")
        lines.append("# TYPE pathway_flow_shed_rows_total counter")
        for g in flow["inputs"]:
            lines.append(
                f'pathway_flow_shed_rows_total{{{_fmt_label(input=g["input"])}}} {g["shed_rows"]}'
            )
        lines.append("# HELP pathway_flow_target_batch Microbatch launch bucket chosen by the AIMD controller")
        lines.append("# TYPE pathway_flow_target_batch gauge")
        lines.append(f'pathway_flow_target_batch {flow["controller"]["target_batch"]}')
        lines.append("# HELP pathway_flow_pressure Flow-control pressure in [0,1] (latency-vs-SLO blended with queue occupancy)")
        lines.append("# TYPE pathway_flow_pressure gauge")
        lines.append(f'pathway_flow_pressure {flow["pressure"]}')
    # ---- per-sink end-to-end latency histograms -----------------------------
    snaps = _obs.run_metrics().sink_snapshots()
    if snaps:
        lines.append("# HELP pathway_sink_latency_seconds End-to-end ingest-to-emit latency per sink")
        lines.append("# TYPE pathway_sink_latency_seconds histogram")
        for label, snap in snaps.items():
            cum = 0
            for bound, c in zip(BUCKET_BOUNDS_S, snap["counts"]):
                cum += c
                lines.append(
                    f'pathway_sink_latency_seconds_bucket{{{_fmt_label(sink=label, le=repr(bound))}}} {cum}'
                )
            cum += snap["counts"][-1]
            lines.append(
                f'pathway_sink_latency_seconds_bucket{{{_fmt_label(sink=label)},le="+Inf"}} {cum}'
            )
            lines.append(
                f'pathway_sink_latency_seconds_sum{{{_fmt_label(sink=label)}}} {snap["sum_s"]}'
            )
            lines.append(
                f'pathway_sink_latency_seconds_count{{{_fmt_label(sink=label)}}} {snap["count"]}'
            )
    # ---- REST serving plane (per-route requests/sheds/latency) --------------
    from pathway_tpu.io.http import _server as _rest_serve

    lines.extend(_rest_serve.serving_prometheus_lines(runtime))
    # ---- request-scoped tracing (per-stage latency decomposition) -----------
    rp = _obs.requests.current()
    if rp is not None:
        lines.extend(rp.prometheus_lines())
    # ---- device profiling plane (compiles, pad waste, memory, FLOPs) --------
    lines.extend(_obs.device.prometheus_lines(runtime))
    # ---- data-plane audit (edge cardinality, violations, divergences) -------
    aud = _obs.audit.current()
    if aud is not None:
        lines.extend(aud.prometheus_lines(runtime))
    # ---- elasticity plane (membership + reshard movement) -------------------
    from pathway_tpu import elastic as _elastic

    lines.extend(_elastic.prometheus_lines(runtime))
    # ---- serving fabric (replica lag/rows, forward health) ------------------
    from pathway_tpu import fabric as _fabric

    lines.extend(_fabric.prometheus_lines(runtime))
    # ---- pod health & SLO plane (door state, canaries, burn rates, alerts) --
    from pathway_tpu.observability import health as _health

    lines.extend(_health.prometheus_lines(runtime))
    # ---- pod timeline plane (recorder counters + bottleneck verdict) --------
    from pathway_tpu.observability import timeline as _timeline

    tplane = _timeline.current()
    if tplane is not None:
        lines.append("# HELP pathway_timeline_samples_total Timeline recorder steps taken")
        lines.append("# TYPE pathway_timeline_samples_total counter")
        lines.append(f"pathway_timeline_samples_total {tplane.samples_total}")
        top = (tplane.bottleneck or {}).get("top")
        if top is not None:
            lines.append("# HELP pathway_bottleneck_score Score of the current top throughput-bound-by verdict")
            lines.append("# TYPE pathway_bottleneck_score gauge")
            lines.append(
                f'pathway_bottleneck_score{{{_fmt_label(cause=top["cause"])}}} {top["score"]}'
            )
    # ---- exactly-once delivery plane (staged/published/uncommitted) ---------
    from pathway_tpu import delivery as _delivery_mod

    lines.extend(_delivery_mod.prometheus_lines(runtime))
    # ---- embedding memo (hit ratio + shared tier) ---------------------------
    import sys as _sys

    _emb = _sys.modules.get("pathway_tpu.xpacks.llm.embedders")
    if _emb is not None:
        lines.extend(_emb.memo_prometheus_lines())
    # ---- per-operator row-level error counters ------------------------------
    from pathway_tpu.internals import error_log as _error_log

    err_counts = _error_log.operator_error_counts()
    lines.append("# HELP pathway_operator_errors_total Row-level failures logged per operator")
    lines.append("# TYPE pathway_operator_errors_total counter")
    for op in sorted(err_counts):
        lines.append(
            f'pathway_operator_errors_total{{{_fmt_label(op=op)}}} {err_counts[op]}'
        )
    return "\n".join(lines) + "\n"


def _profile_payload(query: str) -> bytes:
    """``/profile?ticks=N[&dir=...]``: arm a live ``jax.profiler`` capture
    window on the running pipeline (dir defaults to ``PATHWAY_PROFILE_DIR``).
    With no query arguments, reports the current window state instead."""
    from urllib.parse import parse_qs, unquote

    from pathway_tpu.observability import device as _device

    qs = parse_qs(query)
    if not qs:
        return json.dumps(
            {"ok": True, "window": _device._profile_state()}
        ).encode()
    ticks = None
    try:
        ticks = int(qs["ticks"][0])
    except (KeyError, ValueError, IndexError):
        pass
    path = unquote(qs["dir"][0]) if qs.get("dir") else None
    return json.dumps(_device.request_profile(ticks, path)).encode()


def _explain_payload(runtime, query: str) -> bytes:
    """``/explain?sink=<label>&key=<output key>``: walk the operator graph
    backward from a sink row through the lineage rings — contributing input
    rows, operator path, originating trace span ids. Requires the audit
    plane's lineage store (``PATHWAY_AUDIT=on`` + ``PATHWAY_LINEAGE_KEYS>0``)."""
    from urllib.parse import parse_qs, unquote

    from pathway_tpu.observability import lineage as _lineage

    qs = parse_qs(query)
    store = _lineage.current()
    if store is None:
        return json.dumps(
            {
                "ok": False,
                "error": "lineage is off (PATHWAY_AUDIT=off or PATHWAY_LINEAGE_KEYS=0)",
            }
        ).encode()
    sink = unquote(qs["sink"][0]) if qs.get("sink") else None
    if not sink:
        return json.dumps(
            {"ok": False, "error": "missing sink=", "sinks": store.sink_labels()}
        ).encode()
    try:
        key = int(qs["key"][0], 0)
    except (KeyError, ValueError, IndexError):
        return json.dumps({"ok": False, "error": "missing or non-integer key="}).encode()
    doc = store.explain(getattr(runtime, "scheduler", None), sink, key)
    return json.dumps(doc, default=str).encode()


def _trace_payload(query: str) -> bytes:
    """``/trace?since=<cursor>`` body: live spans recorded after the cursor
    (OTLP span dicts) + the next cursor, so a poller tails the span stream
    incrementally. Empty when tracing is off (``PATHWAY_TRACE=off``)."""
    from urllib.parse import parse_qs

    from pathway_tpu import observability as _obs

    since = 0
    try:
        since = int(parse_qs(query).get("since", ["0"])[0])
    except (ValueError, TypeError):
        pass
    tracer = _obs.current()
    if tracer is None:
        doc = {"enabled": False, "spans": [], "next": since}
    else:
        spans, next_seq = tracer.buffer.since(since)
        doc = {
            "enabled": True,
            "traceId": tracer.trace_id,
            "sample": tracer.sample,
            "spans": spans,
            "next": next_seq,
        }
    return json.dumps(doc).encode()


def _timeline_payload(query: str) -> bytes:
    """``/timeline?metric=&since=&step=&proc=`` body: the timeline plane's
    cursor response (``proc=pod`` = merged pod rollup on the coordinator,
    ``proc=<pid>`` = that process's heartbeat-shipped ring, default = this
    process). ``{"enabled": false}`` with the plane off."""
    from urllib.parse import parse_qs

    from pathway_tpu.observability import timeline as _timeline

    plane = _timeline.current()
    if plane is None:
        return json.dumps({"enabled": False, "points": [], "next": None}).encode()
    return json.dumps(plane.payload(parse_qs(query))).encode()


def _scale_payload(runtime, query: str) -> bytes:
    """``/scale?to=N``: hand a manual rescale request to the live elasticity
    plane (the HTTP twin of ``pathway_tpu scale`` writing to the backend).
    Without ``to=``, reports the plane's current status instead."""
    from urllib.parse import parse_qs

    from pathway_tpu import elastic as _elastic

    plane = _elastic.current()
    if plane is None:
        from pathway_tpu.internals.config import get_pathway_config

        if get_pathway_config().elastic == "off":
            err = "elasticity is off (PATHWAY_ELASTIC=off)"
        else:
            # configured on, but no plane installed: scale decisions ride the
            # cluster continuation barrier — single/thread-sharded runtimes
            # don't run one
            err = (
                "the elasticity plane is not active on this runtime "
                "(decisions ride the cluster continuation barrier; run with "
                "PATHWAY_PROCESSES > 1 under a Supervisor)"
            )
        return json.dumps({"ok": False, "error": err}).encode()
    qs = parse_qs(query)
    if not qs.get("to"):
        return json.dumps({"ok": True, "elastic": plane.status()}).encode()
    try:
        target = int(qs["to"][0])
        doc = plane.request_scale(target, source="http")
    except ValueError as e:
        return json.dumps({"ok": False, "error": str(e)}).encode()
    return json.dumps(doc).encode()


def _alerts_payload() -> tuple[int, dict, dict[str, str]]:
    """``/alerts``: the structured active-alert set, recent resolutions,
    per-alert fired counters and sink delivery counters."""
    from pathway_tpu.observability import alerts as _alerts

    registry = _alerts.current()
    if registry is None:
        return (
            200,
            {"ok": False, "error": "health plane is off (PATHWAY_HEALTH=off)"},
            {},
        )
    doc = {"ok": True, **registry.status_summary()}
    return 200, doc, {}


def _request_payload(query: str) -> bytes:
    """``/request?id=<request_id>``: one request's kept flight-path trace
    (OTLP spans + per-stage latency decomposition), or its in-flight status.
    With no ``id``, lists the kept trace ids and the in-flight table."""
    from urllib.parse import parse_qs, unquote

    from pathway_tpu.observability import requests as _requests

    plane = _requests.current()
    if plane is None:
        return json.dumps(
            {"ok": False, "error": "request tracing is off (PATHWAY_REQUEST_TRACE=off)"}
        ).encode()
    qs = parse_qs(query)
    rid = unquote(qs["id"][0]) if qs.get("id") else None
    if not rid:
        return json.dumps(
            {
                "ok": True,
                "kept_ids": plane.kept_ids(),
                "in_flight": plane.inflight_table(),
                "summary": plane.status_summary(),
            }
        ).encode()
    return json.dumps(plane.get_trace(rid), default=str).encode()


class MonitoringHttpServer:
    """``/status`` + ``/metrics`` + ``/trace`` over a daemon thread for the
    run's lifetime. Binds ``PATHWAY_MONITORING_HTTP_HOST`` (default loopback;
    multi-host TPU-VM pods set an external address so peers are scrapable)."""

    def __init__(self, runtime, port: int | None = None, host: str | None = None):
        import os

        from pathway_tpu.internals.config import get_pathway_config

        self.runtime = runtime
        if port is None:
            base = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
            # multi-process runs inherit one env: offset by process id so
            # workers don't collide on the bind (reference http_server.rs)
            port = 0 if base == 0 else base + int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        self.port = port
        self.host = host if host is not None else get_pathway_config().monitoring_http_host
        self._stopped = False
        rt = runtime

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                from pathway_tpu.observability import health as _health

                path, _, query = self.path.partition("?")
                if path.rstrip("/") in ("/healthz", "/readyz", "/alerts"):
                    # door endpoints: served even while draining — liveness
                    # and the active-alert set are exactly what an operator
                    # needs when the pod is quiescing
                    if path.rstrip("/") == "/healthz":
                        status, doc = _health.healthz_payload()
                        hdrs = {}
                    elif path.rstrip("/") == "/readyz":
                        status, doc, hdrs = _health.readyz_payload()
                    else:
                        status, doc, hdrs = _alerts_payload()
                    body = json.dumps(doc, default=str).encode()
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in hdrs.items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path.rstrip("/") in ("/metrics", "/status") and _health.quiescing():
                    # monitoring consistent with readiness: while the pod
                    # quiesces to a rescale epoch, half-merged numbers would
                    # mislead a scraper — answer 503 like the doors do
                    plane = _health.current()
                    body = json.dumps(
                        {
                            "ok": False,
                            "state": "draining",
                            "reason": plane.drain_reason() if plane else None,
                        }
                    ).encode()
                    self.send_response(503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header("Retry-After", "5")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path.rstrip("/") == "/metrics":
                    body = prometheus_text(rt).encode()
                    ctype = "text/plain; version=0.0.4"
                elif path.rstrip("/") == "/status":
                    body = json.dumps(run_stats(rt)).encode()
                    ctype = "application/json"
                elif path.rstrip("/") == "/trace":
                    body = _trace_payload(query)
                    ctype = "application/json"
                elif path.rstrip("/") == "/profile":
                    body = _profile_payload(query)
                    ctype = "application/json"
                elif path.rstrip("/") == "/explain":
                    body = _explain_payload(rt, query)
                    ctype = "application/json"
                elif path.rstrip("/") == "/request":
                    body = _request_payload(query)
                    ctype = "application/json"
                elif path.rstrip("/") == "/scale":
                    body = _scale_payload(rt, query)
                    ctype = "application/json"
                elif path.rstrip("/") == "/timeline":
                    body = _timeline_payload(query)
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)

    def start(self) -> "MonitoringHttpServer":
        self.thread.start()
        return self

    def stop(self) -> None:
        # idempotent + exception-safe: runs in ``finally`` blocks after failed
        # runs, possibly twice (interactive handle + run teardown)
        if self._stopped:
            return
        self._stopped = True
        try:
            self.server.shutdown()
        finally:
            self.server.server_close()
        self.thread.join(timeout=5.0)


class LiveDashboard:
    """Live console dashboard during a streaming run (reference:
    ``internals/monitoring.py:22-271`` — the rich-based table of per-connector
    message counts and per-operator latency, refreshed while the run lives).

    Renders the same per-operator stats table as :func:`print_summary` plus
    latency/lag probes, redrawing in place with ANSI cursor control every
    ``refresh_s``. Starts only when the output stream is a TTY (or
    ``force=True`` for tests) — exactly when a human is watching."""

    def __init__(self, runtime, level: str, file=None, refresh_s: float = 1.0, force: bool = False):
        self.runtime = runtime
        self.level = level
        self.file = file or sys.stderr
        self.refresh_s = refresh_s
        self.force = force
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_lines = 0
        self.failed = False

    def should_run(self) -> bool:
        if self.level in (None, "none"):
            return False
        return self.force or getattr(self.file, "isatty", lambda: False)()

    def _render(self) -> str:
        stats = run_stats(self.runtime)
        shown = _visible_operators(stats["operators"], self.level)
        width = max([len(o["operator"]) for o in shown] + [8])
        head = (
            f"{'operator':<{width}}  {'rows_in':>10}  {'rows_out':>10}  "
            f"{'latency_ms':>10}  {'lag':>5}"
        )
        lines = [
            f"tick {stats['current_time']}  rows_in {stats['rows_in_total']}  "
            f"rows_out {stats['rows_out_total']}",
            head,
        ]
        for o in shown:
            lag = "-" if o.get("lag") is None else str(o["lag"])
            lines.append(
                f"{o['operator']:<{width}}  {o['rows_in']:>10}  {o['rows_out']:>10}  "
                f"{o['latency_ms']:>10.2f}  {lag:>5}"
            )
        return "\n".join(lines)

    def _draw(self) -> None:
        text = self._render()
        lines = text.count("\n") + 1
        out = ""
        if self._last_lines:
            out += f"\x1b[{self._last_lines}F\x1b[J"  # up N lines, clear below
        out += text + "\n"
        self.file.write(out)
        getattr(self.file, "flush", lambda: None)()
        self._last_lines = lines

    def start(self) -> "LiveDashboard":
        if not self.should_run():
            return self

        def loop() -> None:
            try:
                while not self._stop.wait(self.refresh_s):
                    self._draw()
                self._draw()  # final state
            except Exception:
                # never let the dashboard kill a run; the run-end summary
                # still prints because `failed` records the dead display
                self.failed = True

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def print_summary(runtime, level: str, file=None) -> str | None:
    """Console dashboard at run end (reference's monitoring table, condensed).

    AUTO prints only when attached to a TTY; IN_OUT shows connector/sink rows;
    ALL shows every operator.
    """
    file = file or sys.stderr
    if level in (None, "none"):
        return None
    if level == "auto" and not getattr(file, "isatty", lambda: False)():
        return None
    stats = run_stats(runtime)
    # summary semantics: auto shows everything (one final table); the LIVE
    # dashboard narrows auto to the edge operators instead
    ops = _visible_operators(stats["operators"], "all" if level == "auto" else level)
    width = max([len(o["operator"]) for o in ops] + [8])
    lines = [f"{'operator':<{width}}  {'rows_in':>10}  {'rows_out':>10}  {'time_ms':>10}"]
    for o in ops:
        lines.append(
            f"{o['operator']:<{width}}  {o['rows_in']:>10}  {o['rows_out']:>10}  {o['time_ms']:>10.1f}"
        )
    text = "\n".join(lines)
    print(text, file=file)
    return text
