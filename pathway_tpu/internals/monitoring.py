"""Monitoring: per-operator stats, console dashboard, HTTP/Prometheus endpoint.

Role of the reference's monitoring stack (``internals/monitoring.py:22-271``
dashboard + ``src/engine/http_server.rs:25-77`` metrics server): engine nodes
already count rows in/out and processing time; this module aggregates them into

- a console summary (``monitoring_level`` AUTO/IN_OUT/ALL — AUTO prints only on
  a TTY, NONE is silent),
- ``/status`` (JSON) and ``/metrics`` (Prometheus text exposition) served by a
  daemon-thread HTTP server while the run is live (``with_http_server=True``;
  port from ``PATHWAY_MONITORING_HTTP_PORT``, default 20000).
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any


def scheduler_stats(scheduler) -> list[dict[str, Any]]:
    """Per-operator counters from a live or finished scheduler."""
    if scheduler is None:
        return []
    out = []
    for node in scheduler.graph.nodes:
        out.append(
            {
                "id": node.node_index,
                "operator": node.name,
                "rows_in": node.stats_rows_in,
                "rows_out": node.stats_rows_out,
                "time_ms": round(node.stats_time_ns / 1e6, 3),
            }
        )
    return out


def run_stats(runtime) -> dict[str, Any]:
    scheduler = getattr(runtime, "scheduler", None)
    ops = scheduler_stats(scheduler)
    return {
        "alive": True,
        "current_time": getattr(scheduler, "current_time", None),
        "operators": ops,
        "rows_in_total": sum(o["rows_in"] for o in ops),
        "rows_out_total": sum(o["rows_out"] for o in ops),
    }


def prometheus_text(runtime) -> str:
    """Prometheus exposition format (``http_server.rs`` metric names adapted)."""
    stats = run_stats(runtime)
    lines = [
        "# HELP pathway_operator_rows_in_total Rows consumed by an operator",
        "# TYPE pathway_operator_rows_in_total counter",
    ]
    for o in stats["operators"]:
        label = f'operator="{o["operator"]}",id="{o["id"]}"'
        lines.append(f'pathway_operator_rows_in_total{{{label}}} {o["rows_in"]}')
    lines += [
        "# HELP pathway_operator_rows_out_total Rows emitted by an operator",
        "# TYPE pathway_operator_rows_out_total counter",
    ]
    for o in stats["operators"]:
        label = f'operator="{o["operator"]}",id="{o["id"]}"'
        lines.append(f'pathway_operator_rows_out_total{{{label}}} {o["rows_out"]}')
    lines += [
        "# HELP pathway_operator_time_ms Time spent inside an operator",
        "# TYPE pathway_operator_time_ms counter",
    ]
    for o in stats["operators"]:
        label = f'operator="{o["operator"]}",id="{o["id"]}"'
        lines.append(f'pathway_operator_time_ms{{{label}}} {o["time_ms"]}')
    return "\n".join(lines) + "\n"


class MonitoringHttpServer:
    """``/status`` + ``/metrics`` over a daemon thread for the run's lifetime."""

    def __init__(self, runtime, port: int | None = None):
        import os

        self.runtime = runtime
        self.port = port if port is not None else int(
            os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000")
        )
        rt = runtime

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                if self.path.startswith("/metrics"):
                    body = prometheus_text(rt).encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/status"):
                    body = json.dumps(run_stats(rt)).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)

    def start(self) -> "MonitoringHttpServer":
        self.thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()


def print_summary(runtime, level: str, file=None) -> str | None:
    """Console dashboard at run end (reference's monitoring table, condensed).

    AUTO prints only when attached to a TTY; IN_OUT shows connector/sink rows;
    ALL shows every operator.
    """
    file = file or sys.stderr
    if level in (None, "none"):
        return None
    if level == "auto" and not getattr(file, "isatty", lambda: False)():
        return None
    stats = run_stats(runtime)
    ops = stats["operators"]
    if level == "in_out":
        edge = {"stream_input", "static_input", "subscribe", "capture", "output"}
        ops = [o for o in ops if o["operator"] in edge]
    width = max([len(o["operator"]) for o in ops] + [8])
    lines = [f"{'operator':<{width}}  {'rows_in':>10}  {'rows_out':>10}  {'time_ms':>10}"]
    for o in ops:
        lines.append(
            f"{o['operator']:<{width}}  {o['rows_in']:>10}  {o['rows_out']:>10}  {o['time_ms']:>10.1f}"
        )
    text = "\n".join(lines)
    print(text, file=file)
    return text
