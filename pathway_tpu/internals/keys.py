"""Stable 64-bit row keys and vectorized hashing.

The reference keys every row with a 128-bit xxh3 ``Key`` whose low 16 bits pick the
worker shard (``src/engine/value.rs:41,38``, ``src/engine/dataflow/shard.rs:15-20``).
Here keys are uint64 (numpy-native, JAX-native) produced by a splitmix64-style mixer
for numeric columns — fully vectorized over column blocks — and a blake2b(8) digest
for object columns. The low ``SHARD_BITS`` bits still select the shard so device
placement over a mesh axis is a bitmask, exactly as in the reference.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Any, Iterable

import numpy as np

SHARD_BITS = 16
SHARD_MASK = np.uint64((1 << SHARD_BITS) - 1)

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 arrays."""
    with np.errstate(over="ignore"):
        x = (x + _GOLDEN).astype(np.uint64)
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        x = x ^ (x >> np.uint64(31))
    return x


def _mix2(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return splitmix64(a * np.uint64(0x100000001B3) ^ b)


def _canonical_bytes(v: Any) -> bytes:
    """Canonical encoding for stable cross-run hashing of scalar values."""
    if v is None:
        return b"\x00N"
    if isinstance(v, bool) or isinstance(v, np.bool_):
        return b"\x01" + (b"1" if v else b"0")
    if isinstance(v, (int, np.integer)):
        iv = int(v)
        if -(2**63) <= iv < 2**63:
            return b"\x02" + struct.pack("<q", iv)
        return b"\x02" + struct.pack("<Q", iv & 0xFFFFFFFFFFFFFFFF)
    if isinstance(v, (float, np.floating)):
        f = float(v)
        if f == 0.0:
            f = 0.0  # normalize -0.0
        return b"\x03" + struct.pack("<d", f)
    if isinstance(v, str):
        return b"\x04" + v.encode("utf-8")
    if isinstance(v, bytes):
        return b"\x05" + v
    if isinstance(v, np.datetime64):
        return b"\x07" + struct.pack("<q", v.astype("datetime64[ns]").astype(np.int64))
    if isinstance(v, np.timedelta64):
        return b"\x08" + struct.pack("<q", v.astype("timedelta64[ns]").astype(np.int64))
    if isinstance(v, np.ndarray):
        return b"\x09" + v.tobytes() + str(v.shape).encode()
    if isinstance(v, (tuple, list)):
        out = [b"\x06", struct.pack("<i", len(v))]
        for item in v:
            b = _canonical_bytes(item)
            out.append(struct.pack("<i", len(b)))
            out.append(b)
        return b"".join(out)
    # Json / arbitrary objects
    return b"\x0A" + repr(v).encode("utf-8")


_U64_MASK = 0xFFFFFFFFFFFFFFFF


def _splitmix64_int(x: int) -> int:
    """Scalar splitmix64 over native Python ints — bit-identical to
    :func:`splitmix64` but without numpy array/errstate overhead."""
    x = (x + 0x9E3779B97F4A7C15) & _U64_MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _U64_MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _U64_MASK
    return x ^ (x >> 31)


_NONE_SEED = 0xA5C9

# Deployment-stable salt for key hashing. pwhash64 is a fast NON-
# CRYPTOGRAPHIC hash (like the reference engine's key hashing): with the
# default salt an adversary who fully controls input keys can engineer
# collisions. Deployments ingesting untrusted keys can set PATHWAY_HASH_SALT
# to make the chain unpredictable; it must be identical on every process of a
# cluster and across restarts of a persisted pipeline. The salt covers every
# value-derived path — str/bytes (seed), int/float/bool/datetime (pre-mix
# xor, scalar AND vectorized), None, and the blake2b fallback (keyed) — and
# is a no-op when unset, so default-salt hashes are unchanged.
import os as _os

_HASH_SALT = (
    _splitmix64_int(int(_os.environ["PATHWAY_HASH_SALT"]) & _U64_MASK)
    if "PATHWAY_HASH_SALT" in _os.environ
    else 0
)
_SALT_U64 = np.uint64(_HASH_SALT)
_SALT_KEY = _HASH_SALT.to_bytes(8, "little") if _HASH_SALT else b""


def _salted(bits: np.ndarray) -> np.ndarray:
    """XOR the salt into a uint64 array — identity (no extra array pass on the
    hot per-tick hashing path) when no salt is configured."""
    return bits ^ _SALT_U64 if _HASH_SALT else bits


def _pwhash_bytes(b: bytes, tag: int) -> int:
    """splitmix64 over zero-padded little-endian 8-byte chunks, seeded with a
    type tag and the length — the pure-Python mirror of
    ``native/pwhash.c::pwhash_bytes`` (the two MUST stay bit-identical)."""
    n = len(b)
    h = _splitmix64_int(tag ^ _HASH_SALT ^ n)
    full = n - (n % 8)
    for i in range(0, full, 8):
        h = _splitmix64_int(h ^ int.from_bytes(b[i : i + 8], "little"))
    if full < n:
        h = _splitmix64_int(h ^ int.from_bytes(b[full:], "little"))
    return h


def stable_hash_obj(v: Any) -> np.uint64:
    # Scalars that can also live in typed numpy columns MUST hash identically to
    # hash_column's vectorized paths — join/group keys may see the same value in
    # either storage (e.g. int64 column on one side, object column on the other).
    if v is None:
        # double-mixed so the colliding integer pre-image is a pseudo-random
        # 64-bit value, not the small literal 0xA5C9
        return np.uint64(_splitmix64_int(_splitmix64_int(_NONE_SEED ^ _HASH_SALT)))
    # datetime64/timedelta64 must precede the integer branch: timedelta64
    # subclasses np.signedinteger, and int() of a non-ns timedelta64 raises
    if isinstance(v, np.datetime64):
        ns = int(v.astype("datetime64[ns]").astype(np.int64))
        return np.uint64(_splitmix64_int((ns ^ _HASH_SALT) & _U64_MASK))
    if isinstance(v, np.timedelta64):
        ns = int(v.astype("timedelta64[ns]").astype(np.int64))
        return np.uint64(_splitmix64_int((ns ^ _HASH_SALT) & _U64_MASK))
    if isinstance(v, (bool, np.bool_, int, np.integer)):
        return np.uint64(_splitmix64_int((int(v) ^ _HASH_SALT) & _U64_MASK))
    if isinstance(v, (float, np.floating)):
        f = np.float64(v) + 0.0  # normalize -0.0
        return np.uint64(_splitmix64_int(int(f.view(np.uint64)) ^ _HASH_SALT))
    if isinstance(v, str):
        return np.uint64(_pwhash_bytes(v.encode("utf-8"), 0x04))
    if isinstance(v, bytes):
        return np.uint64(_pwhash_bytes(v, 0x05))
    digest = hashlib.blake2b(
        _canonical_bytes(v), digest_size=8, key=_SALT_KEY
    ).digest()
    return np.uint64(int.from_bytes(digest, "little"))


_hash_obj_ufunc = np.frompyfunc(stable_hash_obj, 1, 1)

# C kernel for the object-column loop (lazily built; None -> pure Python)
from pathway_tpu.native import try_load as _try_load_native  # noqa: E402

_pwhash_native = _try_load_native("pwhash")

_INT_TYPES = (bool, np.bool_, int, np.int64, np.int32, np.intp)
_FLOAT_TYPES = (float, np.float64, np.float32)


def hash_column(col: np.ndarray) -> np.ndarray:
    """Vectorized stable hash of one column → uint64 array."""
    kind = col.dtype.kind
    if kind in ("i", "u", "b"):
        return splitmix64(_salted(col.astype(np.uint64, copy=False)))
    if kind == "f":
        # normalize -0.0 → 0.0 so equal floats hash equal
        c = col + 0.0
        bits = c.view(np.uint64) if c.dtype == np.float64 else c.astype(np.float64).view(np.uint64)
        return splitmix64(_salted(bits))
    if kind == "M":
        # normalize to ns so equal instants in different units hash equal (and
        # match stable_hash_obj / _canonical_bytes)
        return splitmix64(_salted(col.astype("datetime64[ns]").astype(np.int64).astype(np.uint64)))
    if kind == "m":
        return splitmix64(_salted(col.astype("timedelta64[ns]").astype(np.int64).astype(np.uint64)))
    if kind == "O" and len(col) > 16:
        # homogeneous-scalar fast path: coerce to a typed array and take the
        # vectorized branch (they hash identically by construction)
        types = {type(v) for v in col}
        try:
            if types and all(issubclass(t, _INT_TYPES) for t in types):
                return splitmix64(_salted(col.astype(np.int64).astype(np.uint64)))
            if types and all(issubclass(t, _FLOAT_TYPES) for t in types):
                c = col.astype(np.float64) + 0.0
                return splitmix64(_salted(c.view(np.uint64)))
        except (TypeError, ValueError, OverflowError):
            pass
    if _pwhash_native is not None:
        return _pwhash_native.hash_obj_array(col, stable_hash_obj, _HASH_SALT)
    return _hash_obj_ufunc(col).astype(np.uint64)


def row_keys(columns: Iterable[np.ndarray], n: int | None = None, salt: int = 0) -> np.ndarray:
    """Combine per-column hashes into row keys (order-sensitive)."""
    cols = list(columns)
    if not cols:
        assert n is not None
        return splitmix64(np.arange(n, dtype=np.uint64) + np.uint64(salt))
    h = np.full(len(cols[0]), np.uint64(salt) ^ np.uint64(0xA076_1D64_78BD_642F), dtype=np.uint64)
    for c in cols:
        h = _mix2(h, hash_column(np.asarray(c)))
    return h


def ref_scalar(*values: Any, salt: int = 0) -> np.uint64:
    """Key for a single row from its id-column values (``pw.Table.pointer_from``)."""
    if not values:
        return splitmix64(np.asarray([salt], dtype=np.uint64))[0]
    cols = [np.asarray([v]) if not isinstance(v, str) else np.asarray([v], dtype=object) for v in values]
    return row_keys(cols, salt=salt)[0]


def tie_order(key: Any) -> int:
    """Canonical total order on doc keys for score-tie breaking, shared by the
    KNN kernels, host-side decode, BM25/hybrid ranking, and the sharded-index
    reply merge. Hash order (not numeric order): uniform high bits for EVERY
    key type, so the device kernels' 30-bit composite tie-break is a true
    prefix of this order even for small integer keys (numeric order has empty
    top bits there and would degrade to slot order on device)."""
    return int(stable_hash_obj(key))


def tie_order_u64(keys: np.ndarray) -> np.ndarray:
    """Vectorized :func:`tie_order` for uint64/int key arrays (bit-identical
    to ``stable_hash_obj`` on python ints)."""
    return splitmix64(_salted(keys.astype(np.uint64)))


def combine_keys(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Key for pairs of rows (join outputs: key(left,right) — analogous to the
    reference deriving join ids from both side ids)."""
    return _mix2(a.astype(np.uint64), b.astype(np.uint64))


def shard_of(keys: np.ndarray) -> np.ndarray:
    return (keys & SHARD_MASK).astype(np.int32)


def shard_of_keys(
    keys: np.ndarray, n_shards: int, shard_map=None
) -> np.ndarray:
    """THE worker-placement formula — every layer (host exchange in
    ``parallel/cluster``/``parallel/sharded``, the device exchange dest in
    ``parallel/device_plane``, elastic rebucketing in ``elastic/reshard``, and
    fabric door routing in ``fabric/routing``) routes keys through this one
    helper so the ownership rule cannot drift between layers.

    Default rule (reference ``shard.rs:15-20`` parity): low ``SHARD_BITS`` of
    the key modulo the worker count. When a versioned shard map is passed
    (``internals/shardmap.ShardMap``, the ``PATHWAY_SHARDMAP`` plane), ownership
    is its segment table instead — contiguous residue ranges per worker, so a
    rescale moves only re-mapped ranges instead of re-dealing every residue.
    """
    if shard_map is not None:
        return shard_map.owner_of_keys(keys)
    return ((keys.astype(np.uint64, copy=False) & SHARD_MASK) % np.uint64(n_shards)).astype(
        np.int32
    )


def sequential_keys(start: int, n: int, salt: int = 0) -> np.ndarray:
    return splitmix64(np.arange(start, start + n, dtype=np.uint64) ^ np.uint64(salt))
