"""``pw.iterate`` — fixed-point iteration (reference: ``internals/common.py:39`` /
``IterateOperator`` ``operator.py:316`` / engine ``src/engine/dataflow.rs:4275``).

Full implementation lands with the graphs stdlib milestone; the engine node loops the
body subgraph inside a tick until collections stop changing.
"""

from __future__ import annotations

from typing import Any, Callable


def iterate(body: Callable, iteration_limit: int | None = None, **tables: Any):
    from pathway_tpu.internals.iterate_impl import iterate_impl

    return iterate_impl(body, iteration_limit, **tables)


def iterate_universe(body: Callable, **tables: Any):
    return iterate(body, **tables)
