"""``pw.iterate`` — fixed-point iteration of a dataflow subgraph.

Reference behavior matched: the ``iterate`` API (``internals/common.py:39``), the
argument plumbing of ``IterateOperator`` (``internals/operator.py:316-430`` —
iterated vs. iterated-with-universe vs. extra tables, result-shape preservation),
and the engine fixed-point scope (``src/engine/dataflow.rs:4275-4710``).

TPU-native design (not a translation of differential's ``Variable``): the loop body
is captured once as a *logical* subgraph fed by placeholder feed nodes. The outer
``IterateRunnerNode`` accumulates full input state; whenever it changes at a tick
boundary, a **fresh incremental engine subgraph** is instantiated from the logical
body and driven to quiescence by repeatedly diffing body output against fed input
and pushing only the delta back in — so *within* a tick each fixed-point round
costs O(changed rows). Across outer ticks the fixed point restarts from full input
state (O(state) per changed tick): the conservative-correct choice for
non-monotone input changes (e.g. edge deletions), where an incremental iterate
would need differential's 2-D timestamps to re-derive the interior anyway. Only
the net output-vs-previous delta crosses back into the outer dataflow, so
downstream sees clean retraction semantics no matter how many inner rounds ran.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np

from pathway_tpu.engine import operators as ops
from pathway_tpu.engine.blocks import DeltaBatch, apply_diffs_to_state
from pathway_tpu.engine.graph import SOLO, Node, Scheduler
from pathway_tpu.internals.logical import BuildContext, LogicalNode


class iterate_universe:  # noqa: N801 — matches the reference's lowercase API
    """Marks an iterate argument whose key set may change between iterations
    (reference ``internals/operator.py:359``)."""

    def __init__(self, table: Any):
        self.table = table


class _PortBatch(DeltaBatch):
    """A delta batch tagged with the iterate output it belongs to (the engine
    routes every emission to every consumer; demux nodes filter by tag)."""

    __slots__ = ("port",)


class IterateFeedNode(Node):
    """Placeholder source inside the body subgraph; the runner pushes full-state
    and feedback-delta batches into it between inner rounds."""

    name = "iterate_feed"

    def exchange_key(self, port: int):
        return SOLO

    def __init__(self, columns: list[str], np_dtypes: dict | None = None):
        super().__init__(n_inputs=0)
        self.columns = columns
        self.np_dtypes = np_dtypes or {}
        self._pending: list[DeltaBatch] = []

    def feed(self, batch: DeltaBatch) -> None:
        self._pending.append(batch)

    def poll(self, time: int) -> list[DeltaBatch]:
        pending, self._pending = self._pending, []
        return pending


def _state_delta(
    old: Mapping[int, tuple],
    new: Mapping[int, tuple],
    columns: list[str],
    np_dtypes: dict,
    time: int,
) -> DeltaBatch | None:
    """Retract rows of ``old`` not present (or changed) in ``new``; insert the
    new/changed rows. Returns None when states are identical."""
    keys: list[int] = []
    diffs: list[int] = []
    rows: list[tuple] = []
    for k, row in old.items():
        nrow = new.get(k)
        if nrow is None or _row_differs(row, nrow):
            keys.append(k)
            diffs.append(-1)
            rows.append(row)
    for k, row in new.items():
        orow = old.get(k)
        if orow is None or _row_differs(orow, row):
            keys.append(k)
            diffs.append(1)
            rows.append(row)
    if not keys:
        return None
    return DeltaBatch.from_rows(keys, rows, columns, time, diffs=diffs, np_dtypes=np_dtypes)


def _row_differs(a: tuple, b: tuple) -> bool:
    if len(a) != len(b):
        return True
    for x, y in zip(a, b):
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            if not np.array_equal(x, y):
                return True
        elif x != y:
            return True
    return False


class IterateRunnerNode(Node):
    """Outer engine node driving the fixed point.

    Inputs arrive as deltas on the outer dataflow; the runner folds them into full
    per-table state, and at frontier time reruns the body to quiescence, emitting
    tagged per-output delta batches consumed by :class:`IterateOutputNode`.
    """

    name = "iterate"

    snapshot_attrs = ("input_state", "emitted")

    def exchange_key(self, port: int):
        return SOLO  # the fixed-point driver is a serial operator

    def __init__(
        self,
        in_names: list[str],
        in_columns: dict[str, list[str]],
        in_np_dtypes: dict[str, dict],
        feed_lnodes: dict[str, LogicalNode],
        output_lnodes: dict[str, LogicalNode],
        out_columns: dict[str, list[str]],
        iteration_limit: int | None,
    ):
        super().__init__(n_inputs=len(in_names))
        self.in_names = in_names
        self.in_columns = in_columns
        self.in_np_dtypes = in_np_dtypes
        self.feed_lnodes = feed_lnodes
        self.output_lnodes = output_lnodes
        self.out_columns = out_columns
        self.iteration_limit = iteration_limit
        self.input_state: dict[str, dict[int, tuple]] = {n: {} for n in in_names}
        self.emitted: dict[str, dict[int, tuple]] = {n: {} for n in output_lnodes}
        self._dirty = False

    def process(self, inputs, time):
        for port, batch in enumerate(inputs):
            if batch is None or batch.is_empty:
                continue
            name = self.in_names[port]
            apply_diffs_to_state(
                self.input_state[name], batch.select_columns(self.in_columns[name])
            )
            self._dirty = True
        return []

    def on_frontier(self, time):
        if not self._dirty:
            return []
        self._dirty = False
        final = self._run_fixed_point()
        out: list[DeltaBatch] = []
        for name, new_state in final.items():
            delta = _state_delta(
                self.emitted[name],
                new_state,
                self.out_columns[name],
                self.in_np_dtypes.get(name, {}),
                time,
            )
            self.emitted[name] = new_state
            if delta is not None:
                tagged = _PortBatch(delta.keys, delta.diffs, delta.data, delta.time)
                tagged.port = name
                out.append(tagged)
        return out

    def _run_fixed_point(self) -> dict[str, dict[int, tuple]]:
        ctx = BuildContext()
        feeds = {n: ctx.resolve(ln) for n, ln in self.feed_lnodes.items()}
        caps: dict[str, ops.CaptureNode] = {}
        for name, lnode in self.output_lnodes.items():
            body_out = ctx.resolve(lnode)
            # normalize column order to the input table's order so captured row
            # tuples align with the feedback/emission column lists
            reorder = ops.SelectColumnsNode(self.out_columns[name])
            ctx.graph.add_node(reorder, [body_out])
            cap = ops.CaptureNode(self.out_columns[name])
            ctx.graph.add_node(cap, [reorder])
            caps[name] = cap
        ctx.finish()
        # transient: this inner graph is rebuilt per fixed-point run, so the
        # fused segments must not take the jitted tier (per-rebuild re-trace)
        sched = Scheduler(ctx.graph, transient=True)

        fed = {n: dict(self.input_state[n]) for n in self.in_names}
        for n in self.in_names:
            if fed[n]:
                batch = DeltaBatch.from_rows(
                    list(fed[n].keys()),
                    list(fed[n].values()),
                    self.in_columns[n],
                    0,
                    np_dtypes=self.in_np_dtypes.get(n, {}),
                )
                feeds[n].feed(batch)

        round_no = 0
        while True:
            sched.run_tick(round_no)
            round_no += 1  # body has now been applied round_no times
            deltas: dict[str, DeltaBatch] = {}
            for name in self.output_lnodes:
                new_state = dict(caps[name].current)
                delta = _state_delta(
                    fed[name], new_state, self.in_columns[name],
                    self.in_np_dtypes.get(name, {}), round_no,
                )
                if delta is not None:
                    deltas[name] = delta
                    fed[name] = new_state
            if not deltas:
                break  # fixed point
            if self.iteration_limit is not None and round_no >= self.iteration_limit:
                break  # limit reached: do not feed back further
            for name, delta in deltas.items():
                feeds[name].feed(delta)
        return {name: dict(caps[name].current) for name in self.output_lnodes}


class IterateOutputNode(Node):
    """Demux: forwards only the runner's batches tagged with this output name."""

    name = "iterate_out"

    def exchange_key(self, port: int):
        return None

    def __init__(self, port_name: str):
        super().__init__(n_inputs=1)
        self.port_name = port_name

    def accept(self, port: int, batch: DeltaBatch) -> None:
        if getattr(batch, "port", None) == self.port_name:
            super().accept(port, batch)

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        return [DeltaBatch(batch.keys, batch.diffs, batch.data, batch.time)]


def iterate(body: Callable, iteration_limit: int | None = None, **tables: Any):
    """Iterate ``body`` to fixed point. ``body`` takes Tables (one per kwarg) and
    returns a single Table, a tuple of Tables, or a dict of Tables; returned tables
    are matched to same-named (or positionally first) kwargs and fed back; kwargs
    absent from the result are loop constants. Returns the same shape as ``body``'s
    result, holding the converged tables."""
    from pathway_tpu.internals.table import Table

    if iteration_limit is not None and iteration_limit < 1:
        raise ValueError("wrong iteration limit")
    if not tables:
        raise ValueError("iterate needs at least one table argument")

    in_tables: dict[str, Table] = {}
    for name, arg in tables.items():
        t = arg.table if isinstance(arg, iterate_universe) else arg
        if not isinstance(t, Table):
            raise TypeError(f"iterate argument {name!r} must be a Table, got {type(t)}")
        in_tables[name] = t

    in_names = list(in_tables)
    in_columns = {n: t.column_names() for n, t in in_tables.items()}
    in_np_dtypes = {n: t.schema.np_dtypes() for n, t in in_tables.items()}

    feed_lnodes: dict[str, LogicalNode] = {}
    body_args: dict[str, Table] = {}
    for name, t in in_tables.items():
        cols = in_columns[name]
        npd = in_np_dtypes[name]
        lnode = LogicalNode(
            lambda cols=cols, npd=npd: IterateFeedNode(cols, npd),
            [],
            name=f"iterate_feed[{name}]",
        )
        feed_lnodes[name] = lnode
        body_args[name] = Table(lnode, in_tables[name].schema)

    raw_result = body(**body_args)

    shape: str
    if isinstance(raw_result, Table):
        shape = "single"
        result_dict = {in_names[0]: raw_result}
    elif isinstance(raw_result, tuple):
        shape = "tuple"
        if len(raw_result) > len(in_names):
            raise ValueError(
                f"iterate body returned {len(raw_result)} tables for "
                f"{len(in_names)} input(s); tuple results match inputs positionally"
            )
        result_dict = {in_names[i]: t for i, t in enumerate(raw_result)}
    elif isinstance(raw_result, dict):
        shape = "dict"
        result_dict = dict(raw_result)
    else:
        raise TypeError(f"iterate body must return Table/tuple/dict, got {type(raw_result)}")

    for name, t in result_dict.items():
        if name not in in_tables:
            raise ValueError(f"iterate body returned unknown table {name!r}")
        if set(t.column_names()) != set(in_columns[name]):
            raise ValueError(
                f"iterate output {name!r} columns {t.column_names()} do not match "
                f"input columns {in_columns[name]}"
            )

    out_columns = {n: in_columns[n] for n in result_dict}
    output_lnodes = {n: t._node for n, t in result_dict.items()}

    runner_lnode = LogicalNode(
        lambda: IterateRunnerNode(
            in_names,
            in_columns,
            in_np_dtypes,
            feed_lnodes,
            output_lnodes,
            out_columns,
            iteration_limit,
        ),
        [in_tables[n]._node for n in in_names],
        name="iterate",
    )

    out_tables: dict[str, Table] = {}
    for name, rt in result_dict.items():
        out_lnode = LogicalNode(
            lambda name=name: IterateOutputNode(name),
            [runner_lnode],
            name=f"iterate_out[{name}]",
        )
        # output columns follow the *input* table order (reference's
        # ``_sort_columns_by_other``); schema comes from the input table
        out_tables[name] = Table(out_lnode, in_tables[name].schema)

    if shape == "single":
        return out_tables[in_names[0]]
    if shape == "tuple":
        return tuple(out_tables[n] for n in result_dict)
    return IterateResult(out_tables)


class IterateResult(dict):
    """Dict of converged tables with attribute access (``result.clustering``),
    matching the reference's ArgTuple result shape."""

    def __getattr__(self, name: str):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None
