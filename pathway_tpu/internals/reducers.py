"""User-facing reducer registry (``pw.reducers``).

Mirrors the reference's ``internals/reducers.py`` + ``custom_reducers.py``
(sum/min/max/argmin/argmax/count/tuple/sorted_tuple/unique/any/earliest/latest/avg/
ndarray/stateful_single/stateful_many, udf_reducer via BaseCustomAccumulator). Each
descriptor knows how to build its engine accumulator for the argument dtypes and the
result dtype; ``avg`` desugars to sum/count like the reference's Python layer.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import reducers_impl as impl
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ColumnExpression, ReducerExpression


class Reducer:
    def __init__(
        self,
        name: str,
        make_impl: Callable[[list[dt.DType]], impl.ReducerImpl],
        result_dtype_fn: Callable[[list[dt.DType]], dt.DType],
        append_id: bool = False,
        append_sort_key: bool = False,
    ):
        self.name = name
        self._make_impl = make_impl
        self._result_dtype_fn = result_dtype_fn
        self.append_id = append_id  # engine needs (value, id) pairs (argmin/argmax)
        self.append_sort_key = append_sort_key

    def make_impl(self, arg_dtypes: list[dt.DType]) -> impl.ReducerImpl:
        return self._make_impl(arg_dtypes)

    def result_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return self._result_dtype_fn(arg_dtypes)

    def __repr__(self) -> str:
        return f"reducers.{self.name}"


def _first(dts: list[dt.DType]) -> dt.DType:
    return dts[0] if dts else dt.ANY


def _sum_impl(dts: list[dt.DType]) -> impl.ReducerImpl:
    d = dt.unoptionalize(_first(dts))
    if isinstance(d, dt.Array):
        return impl.ArraySumReducer()
    return impl.SumReducer("float" if d == dt.FLOAT else "int")


_count_reducer = Reducer("count", lambda dts: impl.CountReducer(), lambda dts: dt.INT)
_sum_reducer = Reducer("sum", _sum_impl, _first)
_min_reducer = Reducer("min", lambda dts: impl.MinReducer(), _first)
_max_reducer = Reducer("max", lambda dts: impl.MaxReducer(), _first)
_argmin_reducer = Reducer(
    "argmin", lambda dts: impl.ArgMinReducer(), lambda dts: dt.POINTER, append_id=True
)
_argmax_reducer = Reducer(
    "argmax", lambda dts: impl.ArgMaxReducer(), lambda dts: dt.POINTER, append_id=True
)
_unique_reducer = Reducer("unique", lambda dts: impl.UniqueReducer(), _first)
_any_reducer = Reducer("any", lambda dts: impl.AnyReducer(), _first)
_earliest_reducer = Reducer("earliest", lambda dts: impl.EarliestReducer(), _first)
_latest_reducer = Reducer("latest", lambda dts: impl.LatestReducer(), _first)


def count(*args: Any) -> ReducerExpression:
    return ReducerExpression(_count_reducer)


def sum(expr: ColumnExpression) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(_sum_reducer, expr)


def min(expr: ColumnExpression) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(_min_reducer, expr)


def max(expr: ColumnExpression) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(_max_reducer, expr)


def argmin(expr: ColumnExpression) -> ReducerExpression:
    return ReducerExpression(_argmin_reducer, expr)


def argmax(expr: ColumnExpression) -> ReducerExpression:
    return ReducerExpression(_argmax_reducer, expr)


def unique(expr: ColumnExpression) -> ReducerExpression:
    return ReducerExpression(_unique_reducer, expr)


def any(expr: ColumnExpression) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(_any_reducer, expr)


def earliest(expr: ColumnExpression) -> ReducerExpression:
    return ReducerExpression(_earliest_reducer, expr)


def latest(expr: ColumnExpression) -> ReducerExpression:
    return ReducerExpression(_latest_reducer, expr)


def avg(expr: ColumnExpression) -> ColumnExpression:
    """Desugars to sum/count (matching the reference's Python-level avg)."""
    return expr_mod.BinOpExpression(
        "/", ReducerExpression(_sum_reducer, expr), ReducerExpression(_count_reducer)
    )


def tuple(expr: ColumnExpression, *, skip_nones: bool = False) -> ReducerExpression:  # noqa: A001
    r = Reducer(
        "tuple",
        lambda dts: impl.TupleReducer(skip_nones=skip_nones, with_sort_key=True),
        lambda dts: dt.List(_first(dts)),
        append_sort_key=True,  # honors groupby(sort_by=...); defaults to row id
    )
    return ReducerExpression(r, expr)


def sorted_tuple(expr: ColumnExpression, *, skip_nones: bool = False) -> ReducerExpression:
    r = Reducer(
        "sorted_tuple",
        lambda dts: impl.SortedTupleReducer(skip_nones=skip_nones),
        lambda dts: dt.List(_first(dts)),
    )
    return ReducerExpression(r, expr)


def ndarray(expr: ColumnExpression, *, skip_nones: bool = False) -> ReducerExpression:
    r = Reducer(
        "ndarray",
        lambda dts: impl.NdarrayReducer(),
        lambda dts: dt.ANY_ARRAY,
        append_sort_key=True,
    )
    return ReducerExpression(r, expr)


def stateful_single(combine_fn: Callable) -> Callable[..., ReducerExpression]:
    def make(*exprs: ColumnExpression) -> ReducerExpression:
        r = Reducer(
            "stateful_single",
            lambda dts: impl.StatefulReducer(combine_fn, many=False),
            lambda dts: dt.ANY,
        )
        return ReducerExpression(r, *exprs)

    return make


def stateful_many(combine_fn: Callable) -> Callable[..., ReducerExpression]:
    def make(*exprs: ColumnExpression) -> ReducerExpression:
        r = Reducer(
            "stateful_many",
            lambda dts: impl.StatefulReducer(combine_fn, many=True),
            lambda dts: dt.ANY,
        )
        return ReducerExpression(r, *exprs)

    return make


class BaseCustomAccumulator:
    """Base for ``udf_reducer`` accumulators (reference:
    ``internals/custom_reducers.py`` BaseCustomAccumulator: from_row/update/
    retract/compute_result)."""

    @classmethod
    def from_row(cls, row: list):
        raise NotImplementedError

    def update(self, other) -> None:
        raise NotImplementedError

    def compute_result(self):
        raise NotImplementedError


def udf_reducer(acc_cls: type[BaseCustomAccumulator]) -> Callable[..., ReducerExpression]:
    def make(*exprs: ColumnExpression) -> ReducerExpression:
        r = Reducer(
            "udf_reducer",
            lambda dts: impl.CustomAccumulatorReducer(acc_cls),
            lambda dts: dt.ANY,
        )
        return ReducerExpression(r, *exprs)

    return make
