"""``pw.run`` / ``pw.run_all``.

Mirrors the reference's ``internals/run.py`` → GraphRunner flow
(``internals/graph_runner/__init__.py:111-246``): collect requested outputs from the
global graph, tree-shake, instantiate the engine dataflow, and drive it to completion
(streaming sources run until exhausted or ``persistence``/monitoring shutdown).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.runtime import Runtime
from pathway_tpu.internals.config import get_pathway_config
from pathway_tpu.internals.parse_graph import G


class MonitoringLevel:
    AUTO = "auto"
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"


_last_runtime: Runtime | None = None


def resolved_n_workers(n_workers: int | None = None) -> int:
    """kwarg beats env ``PATHWAY_THREADS`` beats 1 (reference: ``PathwayConfig``
    threads resolution, ``internals/config.py``)."""
    if n_workers is not None:
        return max(1, int(n_workers))
    return get_pathway_config().threads


def make_runtime(
    *,
    n_workers: int | None = None,
    monitoring_level: Any = None,
    autocommit_duration_ms: int | None = 20,
):
    """Runtime factory honoring the worker count (single-worker ``Runtime`` or
    thread-sharded ``ShardedRuntime``)."""
    if get_pathway_config().processes > 1:
        from pathway_tpu.parallel.cluster import ClusterRuntime

        return ClusterRuntime(
            monitoring_level=monitoring_level,
            autocommit_duration_ms=autocommit_duration_ms,
        )
    w = resolved_n_workers(n_workers)
    if w > 1:
        from pathway_tpu.parallel.sharded import ShardedRuntime

        return ShardedRuntime(
            n_workers=w,
            monitoring_level=monitoring_level,
            autocommit_duration_ms=autocommit_duration_ms,
        )
    return Runtime(
        monitoring_level=monitoring_level,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def run(
    *,
    monitoring_level: Any = MonitoringLevel.AUTO,
    with_http_server: bool = False,
    autocommit_duration_ms: int | None = 20,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    terminate_on_error: bool | None = None,
    n_workers: int | None = None,
    **kwargs: Any,
) -> "InteractiveRunHandle | None":
    """Execute every output (sink/subscribe/debug) registered so far.

    Returns ``None``, except in interactive mode where the run continues on a
    daemon thread and an ``InteractiveRunHandle`` is returned."""
    global _last_runtime
    if not G.outputs:
        import warnings

        warnings.warn("pw.run(): no outputs registered; nothing to do")
        return
    # per-run telemetry: the resilience event log (and its exports/status
    # views) describes THIS run, not every run this process ever did
    from pathway_tpu.internals import telemetry as _telemetry_reset

    _telemetry_reset.clear_events()
    runtime = make_runtime(
        n_workers=n_workers,
        monitoring_level=monitoring_level,
        autocommit_duration_ms=autocommit_duration_ms,
    )
    if persistence_config is None:
        # CLI contract: `spawn --record` / `replay` point PATHWAY_PERSISTENT_STORAGE /
        # PATHWAY_REPLAY_STORAGE at a recording root (reference: cli.py:253 + config.py)
        import os as _os

        cfg = get_pathway_config()
        auto_root = cfg.replay_storage or (
            cfg.persistent_storage if _os.environ.get("PATHWAY_RECORD") else None
        )
        if auto_root is not None:
            from pathway_tpu import persistence as _p

            persistence_config = _p.Config(
                backend=_p.Backend.filesystem(auto_root),
                continue_after_replay=cfg.continue_after_replay,
            )
    if persistence_config is not None:
        from pathway_tpu.persistence import attach_persistence

        attach_persistence(runtime, persistence_config)
    _last_runtime = runtime
    from pathway_tpu.internals import errors as _errors

    http_server = None
    if with_http_server:
        from pathway_tpu.internals.monitoring import MonitoringHttpServer

        http_server = MonitoringHttpServer(runtime).start()
        # run_stats reports the bound host:port (cluster peers offset the
        # port by process id — this is where a scraper learns the real one)
        runtime.monitoring_server = http_server
    if terminate_on_error is None:
        # kwarg beats PATHWAY_TERMINATE_ON_ERROR beats True
        terminate_on_error = get_pathway_config().terminate_on_error
    prev_policy = _errors.get_error_policy()
    _errors.set_error_policy(terminate_on_error)

    from pathway_tpu.internals import interactive as _interactive

    if _interactive.is_interactive_mode_enabled():
        # notebook mode: the runtime loops on a daemon thread; LiveTables
        # update as ticks land and the handle stops the run
        import threading as _threading

        outputs = list(G.outputs)
        from pathway_tpu.internals import telemetry as _telemetry

        import time as _time

        t_start_ns = _time.time_ns()

        def _bg():
            ok = False
            try:
                runtime.run(outputs)
                ok = True
            finally:
                if not ok:
                    from pathway_tpu.internals.exported import fail_close_exports

                    fail_close_exports(runtime)
                # the error policy is NOT restored here (restoring a
                # process-global from a daemon thread would race a later
                # pw.run on the main thread) — the handle restores it from
                # stop()/join(), i.e. on the thread that owns the policy
                if http_server is not None:
                    http_server.stop()
                _telemetry.maybe_export_run_trace(runtime, t_start_ns)

        th = _threading.Thread(target=_bg, daemon=True)
        th.start()

        def _restore():
            # restore only if the policy is still the one THIS run set —
            # a later pw.run (or another handle) may own the global by now
            if _errors.get_error_policy() == terminate_on_error:
                _errors.set_error_policy(prev_policy)

        return _interactive.InteractiveRunHandle(runtime, th, on_finish=_restore)

    import time as _time

    from pathway_tpu.internals import telemetry as _telemetry

    t_start_ns = _time.time_ns()
    level = monitoring_level if isinstance(monitoring_level, str) else "auto"
    from pathway_tpu.internals.monitoring import LiveDashboard, print_summary

    dashboard = LiveDashboard(runtime, level).start()
    ok = False
    try:
        runtime.run(list(G.outputs))
        ok = True
    finally:
        if not ok:
            from pathway_tpu.internals.exported import fail_close_exports

            fail_close_exports(runtime)
        _errors.set_error_policy(prev_policy)
        if http_server is not None:
            http_server.stop()
        dashboard.stop()
        _telemetry.maybe_export_run_trace(runtime, t_start_ns)
        if dashboard._thread is None or dashboard.failed:
            # no dashboard ran (no TTY) or its display died: print the summary
            print_summary(runtime, level)
    return None


run_all = run


def current_runtime() -> Runtime | None:
    return _last_runtime
