"""Schema metaclass & column definitions.

Mirrors the reference's ``python/pathway/internals/schema.py`` (``pw.Schema``
metaclass with column defs, primary keys, ``schema_from_types/dict``, schema algebra)
— schemas here additionally know their numpy storage layout so the engine can allocate
columnar delta blocks without inspection at runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping

import numpy as np

from pathway_tpu.internals import dtype as dt


@dataclass(frozen=True)
class ColumnDefinition:
    dtype: dt.DType = dt.ANY
    primary_key: bool = False
    default_value: Any = None
    has_default: bool = False
    name: str | None = None
    append_only: bool | None = None


_MISSING = object()


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = _MISSING,
    dtype: Any = None,
    name: str | None = None,
    append_only: bool | None = None,
) -> Any:
    has_default = default_value is not _MISSING
    return ColumnDefinition(
        dtype=dt.wrap(dtype) if dtype is not None else dt.ANY,
        primary_key=primary_key,
        default_value=None if not has_default else default_value,
        has_default=has_default,
        name=name,
        append_only=append_only,
    )


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnDefinition]
    __append_only__: bool

    def __new__(mcls, name, bases, namespace, append_only: bool = False, **kwargs):
        cls = super().__new__(mcls, name, bases, namespace)
        columns: dict[str, ColumnDefinition] = {}
        for base in reversed(bases):
            columns.update(getattr(base, "__columns__", {}))
        annotations = namespace.get("__annotations__", {})
        for col_name, hint in annotations.items():
            if col_name in ("__module__", "__qualname__", "__doc__", "__slots__"):
                continue
            if isinstance(hint, str):
                hint = _resolve_string_annotation(hint, namespace.get("__module__"))
            given = namespace.get(col_name)
            cdef = given if isinstance(given, ColumnDefinition) else ColumnDefinition()
            cdtype = cdef.dtype if cdef.dtype != dt.ANY or hint is Any else dt.wrap(hint)
            if cdef.dtype == dt.ANY and hint is not Any:
                cdtype = dt.wrap(hint)
            columns[cdef.name or col_name] = ColumnDefinition(
                dtype=cdtype,
                primary_key=cdef.primary_key,
                default_value=cdef.default_value,
                has_default=cdef.has_default,
                name=cdef.name or col_name,
                append_only=cdef.append_only,
            )
        cls.__columns__ = columns
        cls.__append_only__ = append_only or any(getattr(b, "__append_only__", False) for b in bases)
        return cls

    def columns(cls) -> dict[str, ColumnDefinition]:
        return dict(cls.__columns__)

    def column_names(cls) -> list[str]:
        return list(cls.__columns__)

    def keys(cls) -> list[str]:
        return list(cls.__columns__)

    def __getitem__(cls, name: str) -> ColumnDefinition:
        return cls.__columns__[name]

    def typehints(cls) -> dict[str, Any]:
        return {n: c.dtype.typehint for n, c in cls.__columns__.items()}

    def dtypes(cls) -> dict[str, dt.DType]:
        return {n: c.dtype for n, c in cls.__columns__.items()}

    def primary_key_columns(cls) -> list[str] | None:
        pks = [n for n, c in cls.__columns__.items() if c.primary_key]
        return pks or None

    def default_values(cls) -> dict[str, Any]:
        return {n: c.default_value for n, c in cls.__columns__.items() if c.has_default}

    def np_dtypes(cls) -> dict[str, np.dtype]:
        return {n: c.dtype.np_dtype for n, c in cls.__columns__.items()}

    def __or__(cls, other: "SchemaMetaclass") -> "SchemaMetaclass":
        dtypes = cls.dtypes()
        for n, d in other.dtypes().items():
            if n in dtypes and dtypes[n] != d:
                raise ValueError(f"schema union conflict on column {n!r}")
            dtypes[n] = d
        return schema_from_dtypes(dtypes, name=f"{cls.__name__}|{other.__name__}")

    def without(cls, *names: str) -> "SchemaMetaclass":
        dtypes = {n: d for n, d in cls.dtypes().items() if n not in names}
        return schema_from_dtypes(dtypes, name=f"{cls.__name__}.without")

    def update_types(cls, **new_types: Any) -> "SchemaMetaclass":
        dtypes = cls.dtypes()
        for n, h in new_types.items():
            if n not in dtypes:
                raise ValueError(f"unknown column {n!r}")
            dtypes[n] = dt.wrap(h)
        return schema_from_dtypes(dtypes, name=f"{cls.__name__}.updated")

    with_types = update_types

    def update_properties(cls, **kwargs: Any) -> "SchemaMetaclass":
        return cls

    def __repr__(cls) -> str:
        cols = ", ".join(f"{n}: {d!r}" for n, d in cls.dtypes().items())
        return f"<Schema {cls.__name__}({cols})>"


def _resolve_string_annotation(hint: str, module_name: str | None) -> Any:
    """Resolve ``from __future__ import annotations``-style string hints."""
    import sys
    import typing

    ns: dict[str, Any] = {"Any": Any, "Optional": typing.Optional, "Union": typing.Union}
    ns.update(
        {
            "int": int,
            "float": float,
            "bool": bool,
            "str": str,
            "bytes": bytes,
            "tuple": tuple,
            "list": list,
            "dict": dict,
            "np": np,
        }
    )
    if module_name and module_name in sys.modules:
        ns.update(vars(sys.modules[module_name]))
    try:
        return eval(hint, ns)  # noqa: S307 — controlled schema annotation context
    except Exception:
        return Any


class Schema(metaclass=SchemaMetaclass):
    """User-facing schema base class: subclass with annotations.

    >>> class InputSchema(pw.Schema):
    ...     name: str
    ...     age: int = pw.column_definition(primary_key=True)
    """


def schema_from_dtypes(
    dtypes: Mapping[str, dt.DType],
    name: str = "AnonymousSchema",
    primary_keys: list[str] | None = None,
    defaults: Mapping[str, Any] | None = None,
) -> SchemaMetaclass:
    namespace: dict[str, Any] = {"__annotations__": {}}
    defaults = defaults or {}
    for n, d in dtypes.items():
        namespace["__annotations__"][n] = Any
        namespace[n] = ColumnDefinition(
            dtype=d,
            primary_key=bool(primary_keys and n in primary_keys),
            default_value=defaults.get(n),
            has_default=n in defaults,
            name=n,
        )
    return SchemaMetaclass(name, (Schema,), namespace)


def schema_from_types(_name: str = "AnonymousSchema", **types: Any) -> SchemaMetaclass:
    return schema_from_dtypes({n: dt.wrap(h) for n, h in types.items()}, name=_name)


def schema_from_dict(
    columns: Mapping[str, Any], name: str = "AnonymousSchema"
) -> SchemaMetaclass:
    dtypes: dict[str, dt.DType] = {}
    pks: list[str] = []
    defaults: dict[str, Any] = {}
    for n, spec in columns.items():
        if isinstance(spec, dict):
            dtypes[n] = dt.wrap(spec.get("dtype", Any))
            if spec.get("primary_key"):
                pks.append(n)
            if "default_value" in spec:
                defaults[n] = spec["default_value"]
        elif isinstance(spec, ColumnDefinition):
            dtypes[n] = spec.dtype
            if spec.primary_key:
                pks.append(n)
            if spec.has_default:
                defaults[n] = spec.default_value
        else:
            dtypes[n] = dt.wrap(spec)
    return schema_from_dtypes(dtypes, name=name, primary_keys=pks or None, defaults=defaults)


def schema_from_pandas(
    df, name: str = "PandasSchema", id_from: list[str] | None = None
) -> SchemaMetaclass:
    import pandas as pd  # noqa: F401

    mapping = {"i": dt.INT, "f": dt.FLOAT, "b": dt.BOOL, "M": dt.DATE_TIME_NAIVE, "m": dt.DURATION}
    dtypes: dict[str, dt.DType] = {}
    for col in df.columns:
        kind = df[col].dtype.kind
        if kind in mapping:
            dtypes[str(col)] = mapping[kind]
        elif df[col].map(lambda v: isinstance(v, str) or v is None).all():
            dtypes[str(col)] = dt.STR
        else:
            dtypes[str(col)] = dt.ANY
    return schema_from_dtypes(dtypes, name=name, primary_keys=id_from)


def schema_from_csv(path: str, name: str = "CsvSchema", **kwargs: Any) -> SchemaMetaclass:
    import pandas as pd

    df = pd.read_csv(path, nrows=100, **kwargs)
    return schema_from_pandas(df, name=name)


def is_subschema(sub: SchemaMetaclass, sup: SchemaMetaclass) -> bool:
    sup_d = sup.dtypes()
    return all(n in sup_d and dt.is_subtype(d, sup_d[n]) for n, d in sub.dtypes().items())
