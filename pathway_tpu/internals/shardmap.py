"""Versioned shard map: ONE authority for "who owns key range R at version V".

Before r19 the reproduction had three independent re-derivations of key
ownership — the engine's ``(key & SHARD_MASK) % n_workers`` (``internals/keys``
re-derived in ``parallel/mesh`` and ``parallel/device_exchange``), the fabric's
hardcoded worker-0 route ownership (``fabric/routing.py``), and elastic
reshard-by-replay (``elastic/reshard.py``). The shard map unifies them: a
versioned table of contiguous residue *segments* over the ``SHARD_BITS`` shard
space, each owned by exactly one global worker. Version numbers are tied to the
membership version (``elastic/membership.py``) — a membership change at version
V commits the shard map for V alongside it.

Two properties make the map the right pivot for both hot paths:

- **Zero-hop routing** — any process can answer ``owner_of_keys`` locally (a
  ``searchsorted`` over at most ``n_workers`` segment starts), so every fabric
  door routes a request directly to the owning process instead of bouncing
  through worker 0 (``fabric/routing.py``).
- **O(moved-state) rescale** — :meth:`ShardMap.rebalance` produces the minimal-
  movement map for a new worker count: survivors keep their ranges up to the
  new quota and only the released residues move. :func:`diff` enumerates
  exactly the moved segments, so live migration loads/moves only the re-mapped
  ranges' operator shards (``persistence/snapshots.py``) instead of wiping
  positional shards and replaying full input logs.

The map is deterministic from (previous map, new worker count): every process
derives the same object locally; only the coordinator (pid 0) commits it to the
backend (``elastic/shardmap`` latest + immutable ``elastic/shardmap_v<N>``
history), same single-writer discipline as the membership record.

Gated by ``PATHWAY_SHARDMAP`` (default off): when off, placement stays the
pre-r19 ``(key & SHARD_MASK) % n`` modulo rule byte-for-byte.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from pathway_tpu.internals.keys import SHARD_BITS, SHARD_MASK

#: size of the residue space the map partitions (low SHARD_BITS of row keys)
SHARD_SPACE = 1 << SHARD_BITS

#: backend key of the LATEST committed shard map
_SHARDMAP = "elastic/shardmap"


@dataclass
class ShardMap:
    """Contiguous-segment ownership table over residues ``[0, SHARD_SPACE)``.

    ``starts``/``owners`` are parallel arrays: segment i covers residues
    ``[starts[i], starts[i+1])`` (the last runs to ``SHARD_SPACE``) and is
    owned by global worker ``owners[i]``. Invariants (checked by
    :meth:`validate`): starts sorted and unique, ``starts[0] == 0``, every
    owner in ``[0, n_workers)``, and every worker owns >= 1 residue.
    """

    version: int
    n_workers: int
    starts: np.ndarray = field(repr=False)
    owners: np.ndarray = field(repr=False)
    committed_unix: float = 0.0

    # ------------------------------------------------------------ construction
    @classmethod
    def initial(cls, n_workers: int, version: int = 1) -> "ShardMap":
        """Equal contiguous split: worker w owns
        ``[w*SPACE//n, (w+1)*SPACE//n)``."""
        if not (1 <= n_workers <= SHARD_SPACE):
            raise ValueError(f"n_workers must be in [1, {SHARD_SPACE}], got {n_workers}")
        starts = np.array(
            [(w * SHARD_SPACE) // n_workers for w in range(n_workers)], dtype=np.int64
        )
        owners = np.arange(n_workers, dtype=np.int32)
        return cls(version=version, n_workers=n_workers, starts=starts, owners=owners)

    def _segments(self) -> list[tuple[int, int, int]]:
        """(start, end_exclusive, owner) triples, in residue order."""
        ends = np.append(self.starts[1:], SHARD_SPACE)
        return [
            (int(s), int(e), int(o))
            for s, e, o in zip(self.starts, ends, self.owners)
        ]

    def rebalance(self, new_n_workers: int, version: int | None = None) -> "ShardMap":
        """Minimal-movement map for ``new_n_workers``: survivors keep their
        residues up to the new quota (excess trimmed from their trailing
        segments), removed workers release everything, and under-quota workers
        (including the new ones) fill from the released pool in worker order.
        Deterministic — every process derives the identical map locally.
        """
        if not (1 <= new_n_workers <= SHARD_SPACE):
            raise ValueError(
                f"n_workers must be in [1, {SHARD_SPACE}], got {new_n_workers}"
            )
        new_v = self.version + 1 if version is None else version
        if new_n_workers == self.n_workers:
            # same shape: a true no-op — re-deriving quotas could shuffle
            # ±1-residue remainders on a drifted map and move state for nothing
            return ShardMap(
                starts=self.starts.copy(),
                owners=self.owners.copy(),
                n_workers=self.n_workers,
                version=new_v,
            )
        quota = [
            SHARD_SPACE // new_n_workers + (1 if w < SHARD_SPACE % new_n_workers else 0)
            for w in range(new_n_workers)
        ]
        # survivors keep a prefix (in residue order) of their current segments
        # up to quota; everything else goes to the free pool
        owned: dict[int, list[list[int]]] = {w: [] for w in range(new_n_workers)}
        free: list[list[int]] = []  # [start, end) ranges, residue order
        kept = [0] * new_n_workers
        for s, e, o in self._segments():
            if o >= new_n_workers:
                free.append([s, e])
                continue
            room = quota[o] - kept[o]
            if room <= 0:
                free.append([s, e])
            elif e - s <= room:
                owned[o].append([s, e])
                kept[o] += e - s
            else:
                owned[o].append([s, s + room])
                free.append([s + room, e])
                kept[o] += room
        # under-quota workers adopt from the pool, lowest worker first,
        # lowest residue first — deterministic fill
        fi = 0
        for w in range(new_n_workers):
            need = quota[w] - kept[w]
            while need > 0:
                s, e = free[fi]
                take = min(need, e - s)
                owned[w].append([s, s + take])
                free[fi][0] = s + take
                if free[fi][0] >= e:
                    fi += 1
                kept[w] += take
                need -= take
        # flatten back to a sorted segment table, coalescing adjacent
        # segments with the same owner
        triples = sorted(
            (s, e, w) for w, ranges in owned.items() for s, e in ranges
        )
        cs: list[int] = []
        co: list[int] = []
        for s, _e, w in triples:
            if co and co[-1] == w:
                continue
            cs.append(s)
            co.append(w)
        m = ShardMap(
            version=new_v,
            n_workers=new_n_workers,
            starts=np.asarray(cs, dtype=np.int64),
            owners=np.asarray(co, dtype=np.int32),
        )
        m.validate()
        return m

    # ------------------------------------------------------------------ lookup
    def owner_of_keys(self, keys: np.ndarray) -> np.ndarray:
        """Owning global worker for each row key (vectorized)."""
        res = (np.asarray(keys).astype(np.uint64, copy=False) & SHARD_MASK).astype(
            np.int64
        )
        idx = np.searchsorted(self.starts, res, side="right") - 1
        return self.owners[idx].astype(np.int32, copy=False)

    def owner_of_residues(self, residues: np.ndarray) -> np.ndarray:
        """Owning worker for raw residues (already ``& SHARD_MASK``)."""
        idx = np.searchsorted(
            self.starts, np.asarray(residues, dtype=np.int64), side="right"
        ) - 1
        return self.owners[idx].astype(np.int32, copy=False)

    def ranges_of(self, worker: int) -> list[tuple[int, int]]:
        """``[start, end)`` residue ranges owned by ``worker``."""
        return [(s, e) for s, e, o in self._segments() if o == worker]

    def key_ranges(self) -> dict[int, str]:
        """worker → human-readable owned ranges (/status and docs)."""
        out: dict[int, str] = {}
        for w in range(self.n_workers):
            out[w] = " ∪ ".join(
                f"[{s}, {e})" for s, e in self.ranges_of(w)
            ) or "∅"
        return out

    # --------------------------------------------------------------- integrity
    def validate(self) -> None:
        if len(self.starts) != len(self.owners) or len(self.starts) == 0:
            raise ValueError("shardmap: malformed segment table")
        if int(self.starts[0]) != 0:
            raise ValueError("shardmap: first segment must start at residue 0")
        if np.any(np.diff(self.starts) <= 0):
            raise ValueError("shardmap: segment starts must be strictly increasing")
        if int(self.starts[-1]) >= SHARD_SPACE:
            raise ValueError("shardmap: segment start beyond shard space")
        if np.any(self.owners < 0) or np.any(self.owners >= self.n_workers):
            raise ValueError("shardmap: owner outside [0, n_workers)")
        present = set(int(o) for o in self.owners)
        if present != set(range(self.n_workers)):
            missing = sorted(set(range(self.n_workers)) - present)
            raise ValueError(f"shardmap: workers own no residues: {missing}")

    # ------------------------------------------------------------ serialization
    def to_dict(self) -> dict[str, Any]:
        return {
            "version": self.version,
            "n_workers": self.n_workers,
            "starts": [int(s) for s in self.starts],
            "owners": [int(o) for o in self.owners],
            "committed_unix": self.committed_unix,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ShardMap":
        return cls(
            version=int(d["version"]),
            n_workers=int(d["n_workers"]),
            starts=np.asarray(d["starts"], dtype=np.int64),
            owners=np.asarray(d["owners"], dtype=np.int32),
            committed_unix=float(d.get("committed_unix", 0.0)),
        )


# -------------------------------------------------------------------- diffing


def diff(old: ShardMap, new: ShardMap) -> list[tuple[int, int, int, int]]:
    """Moved residue segments between two maps:
    ``(start, end_exclusive, old_owner, new_owner)`` with old != new owner.
    Linear merge over both segment tables."""
    bounds = np.union1d(old.starts, new.starts)
    ends = np.append(bounds[1:], SHARD_SPACE)
    o_own = old.owner_of_residues(bounds)
    n_own = new.owner_of_residues(bounds)
    out: list[tuple[int, int, int, int]] = []
    for s, e, a, b in zip(bounds, ends, o_own, n_own):
        if int(a) != int(b):
            if out and out[-1][1] == int(s) and out[-1][2] == int(a) and out[-1][3] == int(b):
                out[-1] = (out[-1][0], int(e), int(a), int(b))
            else:
                out.append((int(s), int(e), int(a), int(b)))
    return out


def overlap_sources(old: ShardMap, new: ShardMap, worker: int) -> list[int]:
    """OLD workers whose owned residues intersect ``worker``'s NEW ranges —
    i.e. exactly the old operator shards a migrating restore must read to
    rebuild ``worker``'s state. For an unmoved worker this is ``[worker]``
    plus the donors of whatever ranges it gained, so reads stay
    O(moved + local), never O(n_workers * state)."""
    srcs: set[int] = set()
    for s, e in new.ranges_of(worker):
        bounds = old.starts
        lo = int(np.searchsorted(bounds, s, side="right")) - 1
        hi = int(np.searchsorted(bounds, e - 1, side="right"))
        for o in old.owners[lo:hi]:
            srcs.add(int(o))
    return sorted(srcs)


def moved_fraction(old: ShardMap, new: ShardMap) -> float:
    """Fraction of the residue space that changes owner old → new."""
    moved = sum(e - s for s, e, _, _ in diff(old, new))
    return moved / float(SHARD_SPACE)


# ------------------------------------------------------------------ backend IO


def read_shardmap(backend: Any) -> ShardMap | None:
    """Latest committed shard map, or None (pre-shardmap storage)."""
    raw = backend.get(_SHARDMAP)
    if raw is None:
        return None
    m = ShardMap.from_dict(pickle.loads(raw))
    m.validate()
    return m


def read_shardmap_version(backend: Any, version: int) -> ShardMap | None:
    raw = backend.get(f"elastic/shardmap_v{version:06d}")
    if raw is None:
        return None
    return ShardMap.from_dict(pickle.loads(raw))


def commit_shardmap(backend: Any, m: ShardMap) -> ShardMap:
    """Publish ``m`` as latest + immutable history entry (single writer: the
    coordinator, pid 0 — same discipline as ``commit_membership``)."""
    import time as _time

    m.validate()
    m.committed_unix = _time.time()
    payload = pickle.dumps(m.to_dict())
    backend.put(f"elastic/shardmap_v{m.version:06d}", payload)
    backend.put(_SHARDMAP, payload)
    try:
        from pathway_tpu.internals.telemetry import record_event

        record_event(
            "elastic.shardmap_committed",
            version=m.version,
            n_workers=m.n_workers,
            segments=len(m.starts),
        )
    except Exception:  # pragma: no cover - telemetry must never block commits
        pass
    return m


def ensure_shardmap(
    backend: Any | None, n_workers: int, version: int, commit: bool = False
) -> tuple[ShardMap, ShardMap | None]:
    """Resolve the current map for an ``n_workers`` pod at membership
    ``version``: reuse the stored map when the shape matches, otherwise derive
    the minimal-movement rebalance from it. Returns ``(current, previous)``
    where ``previous`` is the stored map a reshape migrated away from (None
    when no reshape happened). Deterministic on every process; only the
    coordinator passes ``commit=True``."""
    stored = read_shardmap(backend) if backend is not None else None
    if stored is None:
        cur = ShardMap.initial(n_workers, version=version)
        if commit and backend is not None:
            commit_shardmap(backend, cur)
        return cur, None
    if stored.n_workers == n_workers:
        return stored, None
    # a cold relaunch at a new shape may not have advanced the membership
    # version — the map version must STILL be fresh, or the rebalanced map
    # would overwrite the stored map's immutable history entry (which the
    # persistence manifest pins for O(moved-state) migration diffs)
    cur = stored.rebalance(n_workers, version=max(version, stored.version + 1))
    if commit and backend is not None:
        commit_shardmap(backend, cur)
    return cur, stored
