"""``pw.this`` / ``pw.left`` / ``pw.right`` placeholders and desugaring.

Mirrors the reference's ``internals/thisclass.py`` + ``internals/desugaring.py``:
placeholders build unbound ``ColumnReference``s that table operations rebind to the
operation's target table (or join sides) before type inference and lowering.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Iterable

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class ThisPlaceholder:
    """Placeholder standing for "the table this expression is applied to"."""

    _side = "this"

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__") or name == "_side":
            raise AttributeError(name)
        ref = ColumnReference(None, name)
        ref._placeholder_side = self._side  # type: ignore[attr-defined]
        return ref

    def __getitem__(self, name: str) -> ColumnReference:
        if isinstance(name, ColumnReference):
            name = name.name
        # explicit bracket access allows any column name, including dunder
        # internals that attribute access rejects
        ref = ColumnReference(None, name)
        ref._placeholder_side = self._side  # type: ignore[attr-defined]
        return ref

    @property
    def id(self) -> ColumnReference:
        return self.__getattr__("id")

    def pointer_from(self, *args: Any, optional: bool = False, instance: Any = None):
        p = expr_mod.PointerExpression(None, *args, optional=optional, instance=instance)
        p._placeholder_side = self._side  # type: ignore[attr-defined]
        return p

    def __iter__(self):
        # ``select(*pw.this)``: unpacking yields the placeholder itself; table
        # operations expand it to all columns during desugaring
        return iter([self])

    def __repr__(self) -> str:
        return f"pw.{self._side}"


class LeftPlaceholder(ThisPlaceholder):
    _side = "left"


class RightPlaceholder(ThisPlaceholder):
    _side = "right"


this = ThisPlaceholder()
left = LeftPlaceholder()
right = RightPlaceholder()


def _side_of(e: ColumnExpression) -> str:
    return getattr(e, "_placeholder_side", "this")


def bind_expression(
    e: ColumnExpression,
    this_table: "Table",
    left_table: "Table | None" = None,
    right_table: "Table | None" = None,
) -> ColumnExpression:
    """Rebind placeholder refs to concrete tables, recursively."""

    def resolve(side: str) -> "Table":
        if side == "left":
            if left_table is None:
                raise ValueError("pw.left used outside of a join")
            return left_table
        if side == "right":
            if right_table is None:
                raise ValueError("pw.right used outside of a join")
            return right_table
        return this_table

    if isinstance(e, ColumnReference):
        if e.table is None:
            table = resolve(_side_of(e))
            if e.name != "id" and e.name not in table.schema.column_names():
                raise KeyError(
                    f"column {e.name!r} not in table (has: {table.schema.column_names()})"
                )
            return table[e.name] if e.name != "id" else ColumnReference(table, "id")
        return e
    if isinstance(e, expr_mod.PointerExpression) and e.table is None:
        table = resolve(_side_of(e))
        args = tuple(bind_expression(a, this_table, left_table, right_table) for a in e.args)
        return expr_mod.PointerExpression(table, *args, optional=e.optional, instance=e.instance)
    args = e._args()
    if not args:
        return e
    new_args = tuple(bind_expression(a, this_table, left_table, right_table) for a in args)
    return e._with_args(new_args)


def expand_args(
    args: Iterable[Any], this_table: "Table"
) -> list[ColumnExpression]:
    """Expand ``*pw.this`` / ``*table`` into all-column references."""
    out: list[ColumnExpression] = []
    for a in args:
        if isinstance(a, ThisPlaceholder):
            for name in this_table.schema.column_names():
                out.append(this_table[name])
        elif hasattr(a, "schema") and hasattr(a, "__getitem__"):  # a Table
            for name in a.schema.column_names():
                out.append(a[name])
        else:
            out.append(a)
    return out
