"""``pw.Json`` wrapper (reference: ``python/pathway/internals/json.py``) — an
immutable-ish view over parsed JSON values with convenience accessors."""

from __future__ import annotations

import json as _json
from typing import Any


class Json:
    __slots__ = ("_value",)

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value._value
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    @classmethod
    def parse(cls, s: str | bytes) -> "Json":
        return cls(_json.loads(s))

    @classmethod
    def dumps(cls, obj: Any) -> str:
        if isinstance(obj, Json):
            obj = obj._value
        return _json.dumps(obj, separators=(",", ":"), sort_keys=True, default=_default)

    def __getitem__(self, item: Any) -> "Json":
        return Json(self._value[item])

    def get(self, key: Any, default: Any = None) -> Any:
        if isinstance(self._value, dict):
            v = self._value.get(key, default)
            return Json(v) if isinstance(v, (dict, list)) else v
        return default

    def as_int(self) -> int:
        return int(self._value)

    def as_float(self) -> float:
        return float(self._value)

    def as_str(self) -> str:
        return str(self._value) if not isinstance(self._value, str) else self._value

    def as_bool(self) -> bool:
        return bool(self._value)

    def as_list(self) -> list:
        return list(self._value)

    def as_dict(self) -> dict:
        return dict(self._value)

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return iter(self._value)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self) -> int:
        return hash(Json.dumps(self._value))

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return Json.dumps(self._value)

    NULL: "Json"


def _default(o: Any) -> Any:
    if isinstance(o, Json):
        return o._value
    raise TypeError(f"not JSON serializable: {type(o)}")


Json.NULL = Json(None)
