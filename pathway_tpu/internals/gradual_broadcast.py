"""``Table._gradual_broadcast`` — churn-minimizing threshold broadcast.

Counterpart of the reference's ``gradual_broadcast.rs`` timely operator: a
(lower, value, upper) triplet stream apportions the key space so that a
``(value - lower) / (upper - lower)`` fraction of the rows (by uint64 key
order) carry ``upper`` as their ``apx_value`` and the rest carry ``lower``.
When the triplet moves, only the rows whose keys lie between the old and new
threshold flip — the whole point of the operator (used by Adaptive RAG to roll
a new parameter out to a growing fraction of queries without retracting every
row).

The columnar twist here: row keys are kept as a sorted array, so a threshold
move finds the flipped span with two ``searchsorted`` calls and emits one
block — no per-row work.
"""

from __future__ import annotations

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import SOLO, Node
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.universe import Universe

# shy of 2**64 so float rounding can never overflow the uint64 conversion
_KEY_MAX = 2**64 - 2**12


class GradualBroadcastNode(Node):
    name = "gradual_broadcast"

    snapshot_attrs = ("keys_sorted", "triplet")

    def __init__(self, lower_col: str, value_col: str, upper_col: str):
        super().__init__(n_inputs=2)  # 0: main rows, 1: threshold triplet
        self.lower_col = lower_col
        self.value_col = value_col
        self.upper_col = upper_col
        self.keys_sorted = np.empty(0, dtype=np.uint64)
        self.triplet: tuple[float, float, float] | None = None

    def exchange_key(self, port):
        return SOLO  # threshold is a broadcast scalar; key space is global

    def _threshold_key(self) -> np.uint64:
        lower, value, upper = self.triplet
        if upper == lower:
            frac = 1.0
        else:
            frac = min(max((value - lower) / (upper - lower), 0.0), 1.0)
        return np.uint64(int(frac * _KEY_MAX))

    def _emit(self, keys: np.ndarray, diffs: np.ndarray, time: int) -> DeltaBatch:
        lower, _value, upper = self.triplet
        thr = self._threshold_key()
        vals = np.where(keys < thr, upper, lower)
        return DeltaBatch(keys, diffs, {"apx_value": vals}, time)

    def process(self, inputs, time):
        out: list[DeltaBatch] = []
        thr_batch = inputs[1]
        main_batch = inputs[0]
        # threshold moves first: flips apply to the rows present *before*
        # this tick's row additions (those emit against the new triplet)
        if thr_batch is not None and len(thr_batch):
            ins = np.flatnonzero(thr_batch.diffs > 0)
            if len(ins):
                i = ins[-1]  # latest triplet wins within a tick
                new_triplet = (
                    float(thr_batch.data[self.lower_col][i]),
                    float(thr_batch.data[self.value_col][i]),
                    float(thr_batch.data[self.upper_col][i]),
                )
                old = self.triplet
                if old is not None and len(self.keys_sorted):
                    old_thr = self._threshold_key()
                    self.triplet = new_triplet
                    new_thr = self._threshold_key()
                    lo, hi = min(old_thr, new_thr), max(old_thr, new_thr)
                    a = int(np.searchsorted(self.keys_sorted, lo))
                    b = int(np.searchsorted(self.keys_sorted, hi))
                    span = self.keys_sorted[a:b]
                    if len(span) or old[0] != new_triplet[0] or old[2] != new_triplet[2]:
                        # bounds moved or rows flipped: retract old rows, emit new
                        flipped = (
                            self.keys_sorted
                            if old[0] != new_triplet[0] or old[2] != new_triplet[2]
                            else span
                        )
                        self.triplet = old
                        out.append(
                            self._emit(flipped, np.full(len(flipped), -1, dtype=np.int64), time)
                        )
                        self.triplet = new_triplet
                        out.append(
                            self._emit(flipped, np.ones(len(flipped), dtype=np.int64), time)
                        )
                else:
                    self.triplet = new_triplet
                    if len(self.keys_sorted):
                        # rows that arrived before the first triplet emit now
                        out.append(
                            self._emit(
                                self.keys_sorted,
                                np.ones(len(self.keys_sorted), dtype=np.int64),
                                time,
                            )
                        )
        if main_batch is not None and len(main_batch):
            ins = main_batch.keys[main_batch.diffs > 0]
            dels = main_batch.keys[main_batch.diffs < 0]
            if self.triplet is not None:
                if len(dels):
                    out.append(self._emit(dels, np.full(len(dels), -1, dtype=np.int64), time))
                if len(ins):
                    out.append(self._emit(ins, np.ones(len(ins), dtype=np.int64), time))
            if len(dels):
                self.keys_sorted = self.keys_sorted[
                    ~np.isin(self.keys_sorted, dels.astype(np.uint64))
                ]
            if len(ins):
                merged = np.concatenate([self.keys_sorted, ins.astype(np.uint64)])
                merged.sort()
                self.keys_sorted = merged
        return out


def gradual_broadcast_impl(table, threshold_table, lower, value, upper):
    from pathway_tpu.internals import schema as schema_mod
    from pathway_tpu.internals.table import Table

    lower_ref = threshold_table._bind(lower)
    value_ref = threshold_table._bind(value)
    upper_ref = threshold_table._bind(upper)
    node = LogicalNode(
        lambda: GradualBroadcastNode(lower_ref.name, value_ref.name, upper_ref.name),
        [table._node, threshold_table._node],
        name="gradual_broadcast",
    )
    apx = Table(
        node,
        schema_mod.schema_from_dtypes({"apx_value": dt.FLOAT}),
        table._universe,
    )
    return table.with_columns(apx_value=apx.apx_value)
