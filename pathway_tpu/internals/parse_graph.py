"""Global declarative graph registry.

Mirrors the reference's ``internals/parse_graph.py`` (global mutable ``ParseGraph G``
with node-id sequence, scope stack for ``iterate``, error-log stack and statistics).
Nodes here are the logical operators created by Table methods; ``pw.run()`` walks
from requested outputs and instantiates the engine dataflow.
"""

from __future__ import annotations

import itertools
from collections import Counter
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from pathway_tpu.internals.logical import LogicalNode


#: monotone graph generation, bumped by every ``G.clear()`` — registries
#: that outlive the graph (REST route states, served-table stores) stamp it
#: at definition time so a later run can tell current entries from leftovers
_GENERATION = itertools.count()


class ParseGraph:
    def __init__(self) -> None:
        self.generation = next(_GENERATION)
        self.node_seq = itertools.count()
        self.nodes: list["LogicalNode"] = []
        self.outputs: list[Any] = []  # output/subscribe logical nodes
        self.error_log_tables: list[Any] = []
        self.cache: dict[Any, Any] = {}

    def register(self, node: "LogicalNode") -> "LogicalNode":
        node.node_id = next(self.node_seq)
        self.nodes.append(node)
        return node

    def register_output(self, node: "LogicalNode") -> "LogicalNode":
        self.register(node)
        self.outputs.append(node)
        return node

    def clear(self) -> None:
        self.__init__()
        # the error log is scoped to the graph (reference: per-graph log
        # streams, parse_graph.py:183-238)
        from pathway_tpu.internals import error_log

        error_log.clear()

    def statistics(self) -> dict[str, int]:
        return dict(Counter(type(n).__name__ for n in self.nodes))


G = ParseGraph()
