"""Declarative joins.

Mirrors the reference's ``internals/joins.py`` (join desugaring incl. outer-join
universe logic at ``internals/joins.py:135,1105``): equality conditions between
``pw.left``/``pw.right`` expressions become a shared join-key hash materialized on
both sides; the engine JoinNode does the incremental symmetric hash join; ``select``
over the result rewrites left/right references onto the joined block's prefixed
columns. Join row ids derive from both side ids (``id=pw.left.id`` keeps left ids,
used by asof_now/ix-style lookups).
"""

from __future__ import annotations

from typing import Any


from pathway_tpu.engine import operators as ops
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.expression import (
    TYPE_ENV,
    BinOpExpression,
    ColumnExpression,
    ColumnReference,
)
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class JoinResult:
    """Lazy join; call ``.select``/``.reduce`` to materialize columns."""

    def __init__(
        self,
        left: Table,
        right: Table,
        on: tuple,
        how: str = "inner",
        id_expr: Any = None,
        left_instance: Any = None,
        right_instance: Any = None,
        exact_match: bool = False,
    ):
        self.left = left
        self.right = right
        self.how = how
        self.id_expr = id_expr
        self.left_on: list[ColumnExpression] = []
        self.right_on: list[ColumnExpression] = []
        for cond in on:
            l_e, r_e = split_join_condition(cond, left, right)
            self.left_on.append(l_e)
            self.right_on.append(r_e)
        if left_instance is not None:
            self.left_on.append(thisclass.bind_expression(expr_mod.wrap(left_instance), left))
            self.right_on.append(thisclass.bind_expression(expr_mod.wrap(right_instance), right))
        self._joined: Table | None = None

    # -------------------------------------------------------------- lowering

    def _materialize(self) -> Table:
        if self._joined is not None:
            return self._joined
        left, right = self.left, self.right
        left_id_only = False
        if self.id_expr is not None:
            bound = thisclass.bind_expression(
                expr_mod.wrap(self.id_expr), left, left, right
            )
            if isinstance(bound, ColumnReference) and bound.name == "id" and bound.table is left:
                left_id_only = True

        l_cols = left.column_names()
        r_cols = right.column_names()
        # empty `on` = cross join (reference: statistics-style joins against a
        # 1-row aggregate); PointerExpression with no args would key per row
        l_jk = expr_mod.PointerExpression(left, *self.left_on) if self.left_on else 0
        r_jk = expr_mod.PointerExpression(right, *self.right_on) if self.right_on else 0
        pre_l = left.select(
            **{f"__v_{n}": left[n] for n in l_cols},
            __jk__=l_jk,
        )
        pre_r = right.select(
            **{f"__v_{n}": right[n] for n in r_cols},
            __jk__=r_jk,
        )
        out_columns = (
            ["__left_id__", "__right_id__"]
            + [f"__l__{n}" for n in l_cols]
            + [f"__r__{n}" for n in r_cols]
        )
        how = self.how
        l_opt = how in ("right", "outer")
        r_opt = how in ("left", "outer")
        dtypes: dict[str, dt.DType] = {
            "__left_id__": dt.Optional(dt.POINTER) if l_opt else dt.POINTER,
            "__right_id__": dt.Optional(dt.POINTER) if r_opt else dt.POINTER,
        }
        for n in l_cols:
            d = left._schema.dtypes()[n]
            dtypes[f"__l__{n}"] = dt.Optional(d) if l_opt else d
        for n in r_cols:
            d = right._schema.dtypes()[n]
            dtypes[f"__r__{n}"] = dt.Optional(d) if r_opt else d
        node = LogicalNode(
            lambda: ops.JoinNode(
                left_cols=[f"__v_{n}" for n in l_cols],
                right_cols=[f"__v_{n}" for n in r_cols],
                left_on="__jk__",
                right_on="__jk__",
                how=how,
                out_columns=out_columns,
                left_id_only=left_id_only,
            ),
            [pre_l._node, pre_r._node],
            name=f"join_{how}",
        )
        uni = left._universe.subset() if left_id_only else Universe()
        self._joined = Table(node, schema_mod.schema_from_dtypes(dtypes), uni)
        return self._joined

    def _rewrite(self, e: ColumnExpression, joined: Table) -> ColumnExpression:
        if isinstance(e, ColumnReference):
            if e.table is self.left:
                return joined["__left_id__"] if e.name == "id" else joined[f"__l__{e.name}"]
            if e.table is self.right:
                return joined["__right_id__"] if e.name == "id" else joined[f"__r__{e.name}"]
            if e.table is None or not isinstance(e.table, Table):
                raise ValueError("unbound reference in join select")
            return e
        args = e._args()
        if not args:
            return e
        return e._with_args(tuple(self._rewrite(a, joined) for a in args))

    def _bind_joinside(self, e: Any) -> ColumnExpression:
        """Bind pw.this to left-then-right column resolution."""
        e = expr_mod.wrap(e)

        def bind(x: ColumnExpression) -> ColumnExpression:
            if isinstance(x, ColumnReference) and x.table is None:
                side = getattr(x, "_placeholder_side", "this")
                if side == "left":
                    return self.left[x.name] if x.name != "id" else self.left.id
                if side == "right":
                    return self.right[x.name] if x.name != "id" else self.right.id
                # pw.this: resolve by name, left first
                if x.name in self.left.column_names():
                    return self.left[x.name]
                if x.name in self.right.column_names():
                    return self.right[x.name]
                raise KeyError(f"column {x.name!r} in neither join side")
            args = x._args()
            if not args:
                return x
            return x._with_args(tuple(bind(a) for a in args))

        return bind(e)

    # -------------------------------------------------------------- API

    def select(self, *args: Any, **kwargs: Any) -> Table:
        joined = self._materialize()
        exprs: dict[str, ColumnExpression] = {}
        expanded: list[Any] = []
        for a in args:
            if isinstance(a, thisclass.LeftPlaceholder):
                expanded.extend(self.left[n] for n in self.left.column_names())
            elif isinstance(a, thisclass.RightPlaceholder):
                expanded.extend(self.right[n] for n in self.right.column_names())
            elif isinstance(a, thisclass.ThisPlaceholder):
                expanded.extend(self.left[n] for n in self.left.column_names())
                expanded.extend(
                    self.right[n]
                    for n in self.right.column_names()
                    if n not in self.left.column_names()
                )
            else:
                expanded.append(a)
        for a in expanded:
            bound = self._bind_joinside(a)
            name = expr_mod.smart_name(bound)
            if name is None:
                raise ValueError("positional join select args must be column refs")
            exprs[name] = bound
        for name, e in kwargs.items():
            exprs[name] = self._bind_joinside(e)
        final = {n: self._rewrite(e, joined) for n, e in exprs.items()}
        return joined.select(**final)

    def _rebind(self, e: Any, joined: Table) -> ColumnExpression:
        return self._rewrite(self._bind_joinside(e), joined)

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        return self.groupby().reduce(*args, **kwargs)

    def groupby(self, *args: Any, **kwargs: Any):
        joined = self._materialize()
        grouping = [self._rebind(a, joined) for a in args]
        inner = joined.groupby(*grouping, **kwargs)
        return _JoinGroupedTable(self, joined, inner)

    def filter(self, expression: Any) -> "JoinResult":
        joined = self._materialize()
        bound = self._rewrite(self._bind_joinside(expression), joined)
        new = JoinResult.__new__(JoinResult)
        new.left = self.left
        new.right = self.right
        new.how = self.how
        new.id_expr = self.id_expr
        new.left_on = self.left_on
        new.right_on = self.right_on
        new._joined = joined.filter(bound)
        return new


class _JoinGroupedTable:
    """GroupedTable over a join result: rewrites pw.left/pw.right refs in reduce
    expressions onto the joined block before delegating."""

    def __init__(self, join_result: JoinResult, joined: Table, inner: Any):
        self._jr = join_result
        self._joined = joined
        self._inner = inner

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        rw_args = [self._jr._rebind(a, self._joined) for a in args]
        rw_kwargs = {k: self._jr._rebind(v, self._joined) for k, v in kwargs.items()}
        return self._inner.reduce(*rw_args, **rw_kwargs)


def split_join_condition(
    cond: Any, left: Table, right: Table
) -> tuple[ColumnExpression, ColumnExpression]:
    if isinstance(cond, ColumnReference):
        # shorthand: single ref means same-named column on both sides
        name = cond.name
        return left[name], right[name]
    if not (isinstance(cond, BinOpExpression) and cond.op == "=="):
        raise ValueError("join conditions must be equalities (left expr == right expr)")
    l_e = thisclass.bind_expression(cond.left, left, left, right)
    r_e = thisclass.bind_expression(cond.right, left, left, right)
    if _belongs_to(l_e, right) and _belongs_to(r_e, left):
        l_e, r_e = r_e, l_e
    return l_e, r_e


def _belongs_to(e: ColumnExpression, table: Table) -> bool:
    if isinstance(e, ColumnReference):
        return e.table is table
    return any(_belongs_to(a, table) for a in e._args())


def join_on_key_cols(
    left: Table,
    right: Table,
    left_key_expr: ColumnExpression,
    how: str,
    left_id_only: bool,
    take_right_only: bool,
    universe: Universe,
) -> Table:
    """ix-style lookup: match ``left_key_expr`` (a pointer) against right ids."""
    l_cols = left.column_names()
    r_cols = right.column_names()
    pre_l = left.select(
        **{f"__v_{n}": left[n] for n in l_cols},
        __jk__=left_key_expr,
    )
    pre_r = right.select(
        **{f"__v_{n}": right[n] for n in r_cols},
        __jk__=ColumnReference(right, "id"),
    )
    out_columns = (
        ["__left_id__", "__right_id__"]
        + [f"__l__{n}" for n in l_cols]
        + [f"__r__{n}" for n in r_cols]
    )
    node = LogicalNode(
        lambda: ops.JoinNode(
            left_cols=[f"__v_{n}" for n in l_cols],
            right_cols=[f"__v_{n}" for n in r_cols],
            left_on="__jk__",
            right_on="__jk__",
            how=how,
            out_columns=out_columns,
            left_id_only=left_id_only,
        ),
        [pre_l._node, pre_r._node],
        name="ix",
    )
    dtypes: dict[str, dt.DType] = {
        "__left_id__": dt.POINTER,
        "__right_id__": dt.Optional(dt.POINTER),
    }
    for n in l_cols:
        dtypes[f"__l__{n}"] = left._schema.dtypes()[n]
    for n in r_cols:
        dtypes[f"__r__{n}"] = dt.Optional(right._schema.dtypes()[n])
    joined = Table(node, schema_mod.schema_from_dtypes(dtypes), universe)
    if take_right_only:
        return joined.select(
            **{n: joined[f"__r__{n}"] for n in r_cols}
        )
    return joined
