"""``pw.export_table`` / ``pw.import_table`` — cross-graph composition.

Counterpart of the reference's ``ExportedTable`` trait + ``Scope.export_table``
/ ``Scope.import_table`` (``src/engine/graph.rs:614-624``,
``graph_runner/operator_handler.py:155,206``): one graph exports a table as a
thread-safe buffered diff stream with a frontier; another graph — typically a
later ``pw.run`` or an interactive-mode LiveTable consumer — imports it as a
live source. Keys and diffs are preserved exactly; logical times are
re-assigned by the importing graph's clock (each graph owns its frontier, as
in the reference where imported streams enter a fresh input session).
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable

from pathway_tpu.engine.graph import END_OF_STREAM, SOLO, Node
from pathway_tpu.internals.logical import LogicalNode


class ExportedTable:
    """Buffered (key, values, time, diff) stream + frontier + callbacks —
    the ``ExportedTable`` trait surface (``graph.rs:614-624``)."""

    def __init__(self, column_names: list[str], dtypes: dict[str, Any]):
        self.column_names = list(column_names)
        self.dtypes = dict(dtypes)
        self._lock = threading.Lock()
        self._rows: list[tuple[int, tuple, int, int]] = []
        self._frontier = -1  # last completed logical time
        self._closed = False
        self._failed = False
        self._callbacks: list[Callable[[], None]] = []

    # -- reader surface ------------------------------------------------------
    def failed(self) -> bool:
        return self._failed

    @property
    def closed(self) -> bool:
        return self._closed

    def frontier(self) -> int:
        return self._frontier

    def data_from_offset(self, offset: int) -> tuple[list, int]:
        """Rows appended since ``offset`` and the next offset to poll from."""
        with self._lock:
            return self._rows[offset:], len(self._rows)

    def subscribe(self, callback: Callable[[], None]) -> None:
        """``callback()`` fires after every frontier advance and on close."""
        with self._lock:
            self._callbacks.append(callback)

    def snapshot_at(self, frontier: int | None = None) -> list[tuple[int, tuple]]:
        """Consolidated live rows at ``frontier`` (default: everything),
        sorted by key — ``ExportedTable::snapshot_at`` semantics.

        Nets on (key, values) pairs like engine consolidation (advisor r4): a
        key holding several distinct value tuples keeps each with its own
        multiplicity, and a retraction for values never inserted can't pin
        those values into the snapshot. Rows with multiplicity m appear m
        times, matching the engine's multiset semantics."""
        # values tuples may hold unhashable cells (ndarray columns) — net on a
        # hashable digest, keep the original tuple for the result
        def hkey(values: tuple):
            try:
                hash(values)
                return values
            except TypeError:
                from pathway_tpu.internals.keys import stable_hash_obj

                return ("__digest__", int(stable_hash_obj(values)))

        net: dict[tuple[int, Any], list] = {}  # (key, digest) -> [values, count]
        with self._lock:
            rows = list(self._rows)
        for key, values, t, diff in rows:
            if frontier is not None and t > frontier:
                continue
            hk = (key, hkey(values))
            ent = net.get(hk)
            if ent is None:
                net[hk] = [values, diff]
            else:
                ent[1] += diff
        # stable sort by key only: value tuples may be incomparable (None vs int)
        return sorted(
            ((key, vals) for (key, _), (vals, d) in net.items() if d > 0 for _ in range(d)),
            key=lambda r: r[0],
        )

    # -- writer surface (ExportNode only) ------------------------------------
    def _append(self, rows: list[tuple[int, tuple, int, int]]) -> None:
        with self._lock:
            self._rows.extend(rows)

    def _advance(self, frontier: int) -> None:
        with self._lock:
            if frontier > self._frontier:
                self._frontier = frontier
            callbacks = list(self._callbacks)
        for cb in callbacks:
            cb()

    def _close(self, failed: bool = False) -> None:
        with self._lock:
            self._closed = True
            self._failed = failed
            callbacks = list(self._callbacks)
        for cb in callbacks:
            cb()


class ExportNode(Node):
    """Output node appending every diff to an :class:`ExportedTable`."""

    name = "export_table"

    def exchange_key(self, port):
        return SOLO  # output order discipline, like other sinks

    def __init__(self, columns: list[str], exported: ExportedTable):
        super().__init__(n_inputs=1)
        self.columns = columns
        self.exported = exported

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        self.exported._append(
            [(key, tuple(row), time, diff) for key, diff, row in batch.rows()]
        )
        return []

    def on_tick_complete(self, time):
        # advance only once the tick fully settled (frontier rounds included):
        # a live reader seeing frontier()==t must see ALL of tick t's rows
        if time != END_OF_STREAM:
            self.exported._advance(time)

    def on_end(self):
        self.exported._close()


def fail_close_exports(runtime) -> None:
    """A crashed run never reaches ``scheduler.close()``/``on_end``; close its
    exported tables as FAILED so importers stop polling instead of hanging."""
    scheduler = getattr(runtime, "scheduler", None)
    graphs = []
    if scheduler is not None and getattr(scheduler, "graph", None) is not None:
        graphs.append(scheduler.graph)
    for w in getattr(runtime, "workers", None) or []:
        if getattr(w, "graph", None) is not None:
            graphs.append(w.graph)
    for g in graphs:
        for node in g.nodes:
            if isinstance(node, ExportNode) and not node.exported.closed:
                node.exported._close(failed=True)


def export_table(table) -> ExportedTable:
    """Register ``table`` for export; the returned handle fills during
    ``pw.run`` and stays readable afterwards."""
    exported = ExportedTable(table.column_names(), dict(table._schema.dtypes()))
    node = LogicalNode(
        lambda: ExportNode(exported.column_names, exported),
        [table._node],
        name="export_table",
    )
    node._register_as_output()
    return exported


def import_table(exported: ExportedTable):
    """A live source table over an :class:`ExportedTable` (same columns, keys
    and diffs preserved). If the exporting run already finished, the import
    is a bounded replay; if it is still running (interactive mode), rows
    stream in as the exporter's frontier advances."""
    from pathway_tpu import io as pw_io
    from pathway_tpu.internals import schema as schema_mod

    schema = schema_mod.schema_from_dtypes(dict(exported.dtypes))

    class _ImportSubject(pw_io.python.ConnectorSubject):
        def _push_rows(self, rows) -> None:
            if rows:
                assert self._node is not None
                self._node.push_many(
                    (key, values, diff) for key, values, _t, diff in rows
                )

        def run(self) -> None:
            offset = 0
            while True:
                if exported.closed:
                    # close implies every appended row is finalized
                    rows, offset = exported.data_from_offset(offset)
                    self._push_rows(rows)
                    if exported.failed():
                        raise RuntimeError(
                            "import_table: the exporting run failed before "
                            "completing its stream"
                        )
                    break
                # only finalized ticks cross the graph boundary: rows past the
                # exporter's frontier may still be revised within their tick
                # (pad-then-correct churn the exporter's own subscribers never
                # see). Appends are time-ordered, so the finalized rows form a
                # prefix.
                f = exported.frontier()
                rows, _next = exported.data_from_offset(offset)
                n_fin = 0
                for r in rows:
                    if r[2] > f:
                        break
                    n_fin += 1
                self._push_rows(rows[:n_fin])
                offset += n_fin
                _time.sleep(0.002)

    return pw_io.python.read(_ImportSubject(), schema=schema, name="import_table")
