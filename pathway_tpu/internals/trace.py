"""User stack-frame capture for diagnostics (reference: ``internals/trace.py``
``trace_user_frame``): every logical operator remembers the user code line
that created it, and engine failures annotate the raised exception with that
provenance — so a traceback deep in the block kernels still says which
``select``/``join``/``reduce`` in the user's pipeline it belongs to."""

from __future__ import annotations

import os
import sys
import threading

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep

# the engine node whose method is currently executing on this thread — lets
# row-level failure reporting (``errors.report_error`` → the live error log)
# attribute a UDF raise to its operator without threading ids through every
# expression-VM call
_tls = threading.local()


def current_node():
    return getattr(_tls, "node", None)


def user_frame() -> tuple[str, int, str] | None:
    """(filename, lineno, code line description) of the nearest caller frame
    outside the pathway_tpu package."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR) and "importlib" not in fn:
            return (fn, f.f_lineno, f.f_code.co_name)
        f = f.f_back
    return None


def format_frame(frame: tuple[str, int, str] | None) -> str | None:
    if frame is None:
        return None
    fn, line, func = frame
    return f"{fn}:{line} in {func}"


def annotate(exc: BaseException, op_name: str, frame: tuple[str, int, str] | None) -> None:
    """Attach operator provenance to an in-flight exception (PEP 678 note)."""
    where = format_frame(frame)
    note = f"while running operator {op_name!r}"
    if where:
        note += f" created at {where}"
    try:
        exc.add_note(note)
    except AttributeError:  # pre-3.11: emulate PEP 678's __notes__ list
        try:
            notes = getattr(exc, "__notes__", None)
            if notes is None:
                notes = exc.__notes__ = []
            notes.append(note)
        except Exception:
            pass


def run_annotated(node, method, *args):
    """Call an engine-node method, annotating any exception with the node's
    user provenance — the ONE wrapper every runtime shares. Also pins the
    node as this thread's current operator so row-level error reports
    attribute to it."""
    prev = getattr(_tls, "node", None)
    _tls.node = node
    try:
        return method(*args)
    except Exception as e:
        annotate(e, node.name, getattr(node, "user_trace", None))
        raise
    finally:
        _tls.node = prev
