"""Global error-log table + live per-operator error counters (reference:
``parse_graph.py:183-238`` — schema: operator_id, message, trace; rows appear
when ``terminate_on_error=False`` routes row-level failures to
``Value::Error`` + a log stream).

r12 wires this previously-orphaned log into the live observability plane:
every logged error also increments a per-operator counter, surfaced on
``/status`` (``errors`` section) and ``/metrics``
(``pathway_operator_errors_total{op}``). The operator label resolves from the
explicit ``operator_id`` when the caller has one, else from the engine node
currently executing on this thread (``internals.trace.current_node`` — set by
the shared ``run_annotated`` wrapper every runtime routes node calls
through), else ``"(unattributed)"``.
"""

from __future__ import annotations

import threading

from pathway_tpu.internals import schema as schema_mod

_lock = threading.Lock()
_entries: list[tuple[int, str, str]] = []
_op_counts: dict[str, int] = {}


def _operator_label(operator_id: int) -> str:
    if operator_id >= 0:
        return f"op:{operator_id}"
    from pathway_tpu.internals.trace import current_node

    node = current_node()
    if node is not None:
        return f"{node.name}:{node.node_index}"
    return "(unattributed)"


_recent: list[dict] = []  # bounded mirror with resolved operator labels


def log_error(operator_id: int, message: str, trace: str = "") -> None:
    label = _operator_label(operator_id)
    with _lock:
        _entries.append((operator_id, message, trace))
        _op_counts[label] = _op_counts.get(label, 0) + 1
        _recent.append({"operator": label, "message": message[:500]})
        if len(_recent) > 64:
            del _recent[:32]


def clear() -> None:
    with _lock:
        _entries.clear()
        _op_counts.clear()
        _recent.clear()


def operator_error_counts() -> dict[str, int]:
    """operator label -> errors logged (live plane: /status + /metrics)."""
    with _lock:
        return dict(_op_counts)


def summary() -> dict:
    """The ``/status`` ``errors`` section: total + per-operator counts + the
    most recent messages (bounded — the full log lives in
    ``pw.global_error_log()``)."""
    with _lock:
        return {
            "total": len(_entries),
            "by_operator": dict(_op_counts),
            "recent": list(_recent[-16:]),
        }


ERROR_LOG_SCHEMA = schema_mod.schema_from_types(
    operator_id=int, message=str, trace=str
)


def global_error_log():
    from pathway_tpu.debug import table_from_rows

    with _lock:
        rows = list(_entries)
    return table_from_rows(ERROR_LOG_SCHEMA, rows)
