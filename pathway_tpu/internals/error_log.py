"""Global error-log table (reference: ``parse_graph.py:183-238`` — schema:
operator_id, message, trace; rows appear when ``terminate_on_error=False`` routes
row-level failures to ``Value::Error`` + a log stream)."""

from __future__ import annotations

import threading

from pathway_tpu.internals import schema as schema_mod

_lock = threading.Lock()
_entries: list[tuple[int, str, str]] = []


def log_error(operator_id: int, message: str, trace: str = "") -> None:
    with _lock:
        _entries.append((operator_id, message, trace))


def clear() -> None:
    with _lock:
        _entries.clear()


ERROR_LOG_SCHEMA = schema_mod.schema_from_types(
    operator_id=int, message=str, trace=str
)


def global_error_log():
    from pathway_tpu.debug import table_from_rows

    with _lock:
        rows = list(_entries)
    return table_from_rows(ERROR_LOG_SCHEMA, rows)
