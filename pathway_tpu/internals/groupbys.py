"""GroupedTable & reduce desugaring.

Mirrors the reference's ``internals/groupbys.py`` (GroupedTable.reduce): reducer
expressions inside ``reduce(...)`` are split out into engine reducer slots, the
grouping columns and reducer arguments are materialized by a pre-select, the engine
GroupByNode aggregates incrementally, and a post-select rebuilds the user's output
expressions over the aggregate slots.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.expression import (
    TYPE_ENV,
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
)
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class GroupedTable:
    def __init__(
        self,
        source: Table,
        grouping: list[ColumnReference],
        set_id: ColumnExpression | None = None,
        sort_by: ColumnExpression | None = None,
        instance: ColumnExpression | None = None,
    ):
        self.source = source
        self.grouping = grouping
        self.set_id = set_id
        self.sort_by = sort_by
        self.instance = instance
        if instance is not None:
            self.grouping = [*grouping]  # instance joins the grouping key
        self._window_args: dict[str, Any] | None = None  # used by temporal windowby

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        source = self.source
        out_exprs: dict[str, ColumnExpression] = {}
        for a in thisclass.expand_args(args, source):
            bound = thisclass.bind_expression(expr_mod.wrap(a), source)
            name = expr_mod.smart_name(bound)
            if name is None:
                raise ValueError("positional reduce args must be column references")
            out_exprs[name] = bound
        for name, e in kwargs.items():
            out_exprs[name] = thisclass.bind_expression(expr_mod.wrap(e), source)

        # --- collect reducers and grouping slots ------------------------------
        grouping_exprs = list(self.grouping)
        if self.instance is not None:
            grouping_exprs.append(self.instance)  # type: ignore[arg-type]
        group_slot_of: dict[tuple[int, str], int] = {}
        for i, g in enumerate(grouping_exprs):
            group_slot_of[(id(g.table), g.name)] = i

        reducer_slots: list[ReducerExpression] = []

        def collect(e: ColumnExpression) -> None:
            if isinstance(e, ReducerExpression):
                reducer_slots.append(e)
                return  # don't descend into reducer args (they're row-level)
            for a in e._args():
                collect(a)

        for e in out_exprs.values():
            collect(e)

        # --- pre-select materializes grouping cols + reducer args -------------
        pre_cols: dict[str, ColumnExpression] = {}
        for i, g in enumerate(grouping_exprs):
            pre_cols[f"__g{i}"] = g
        sort_key_expr = self.sort_by if self.sort_by is not None else ColumnReference(source, "id")
        arg_names_per_slot: list[list[str]] = []
        for j, r in enumerate(reducer_slots):
            names: list[str] = []
            for k, a in enumerate(r.args):
                nm = f"__a{j}_{k}"
                pre_cols[nm] = a
                names.append(nm)
            if r.reducer.append_id:
                nm = f"__a{j}_id"
                pre_cols[nm] = ColumnReference(source, "id")
                names.append(nm)
            if r.reducer.append_sort_key:
                nm = f"__a{j}_sk"
                pre_cols[nm] = sort_key_expr
                names.append(nm)
            arg_names_per_slot.append(names)
        if self.set_id is not None:
            pre_cols["__setid"] = self.set_id

        pre = source.select(**pre_cols)

        # --- engine groupby ----------------------------------------------------
        group_col_names = [f"__g{i}" for i in range(len(grouping_exprs))]
        specs = []
        inter_dtypes: dict[str, dt.DType] = {}
        for i, g in enumerate(grouping_exprs):
            inter_dtypes[f"__g{i}"] = g._dtype(TYPE_ENV)
        for j, r in enumerate(reducer_slots):
            arg_dtypes = [a._dtype(TYPE_ENV) for a in r.args]
            impl = r.reducer.make_impl(arg_dtypes)
            specs.append((f"__r{j}", impl, arg_names_per_slot[j]))
            inter_dtypes[f"__r{j}"] = r.reducer.result_dtype(arg_dtypes)

        key_col = "__setid" if self.set_id is not None else None
        node = LogicalNode(
            lambda: ops.GroupByNode(
                group_col_names,
                specs,
                key_col=key_col,
                out_group_cols=group_col_names,
            ),
            [pre._node],
            name="groupby",
        )
        inter = Table(node, schema_mod.schema_from_dtypes(inter_dtypes), Universe())

        # --- post-select rebuilds user expressions over slots ------------------
        slot_index = {id(r): j for j, r in enumerate(reducer_slots)}

        def rewrite(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ReducerExpression):
                return inter[f"__r{slot_index[id(e)]}"]
            if isinstance(e, ColumnReference):
                if e.name == "id" and isinstance(e.table, Table):
                    # pw.this.id inside reduce = the group's id
                    return ColumnReference(inter, "id")
                slot = group_slot_of.get((id(e.table), e.name))
                if slot is None:
                    raise ValueError(
                        f"column {e.name!r} used in reduce() outside a reducer and "
                        "not in groupby()"
                    )
                return inter[f"__g{slot}"]
            args = e._args()
            if not args:
                return e
            return e._with_args(tuple(rewrite(a) for a in args))

        final_exprs = {name: rewrite(e) for name, e in out_exprs.items()}
        return inter.select(**final_exprs)

    def windowby_reduce_context(self) -> Table:
        raise NotImplementedError
