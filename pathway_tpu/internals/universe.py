"""Universes (row key-sets) and subset reasoning.

Mirrors the role of the reference's ``internals/universe.py`` +
``internals/universe_solver.py``: a ``Universe`` is the identity of a table's key set;
operators derive sub/super/equal universes, and ``promise_*`` calls let users assert
relations the solver can't infer. Powers ``with_universe_of``, same-universe checks in
``update_cells``/zip-like ``select`` across tables, and restrict/intersect typing.
"""

from __future__ import annotations

import itertools

_ids = itertools.count()


class Universe:
    __slots__ = ("id",)

    def __init__(self) -> None:
        self.id = next(_ids)

    def __repr__(self) -> str:
        return f"Universe({self.id})"

    def subset(self) -> "Universe":
        u = Universe()
        solver().register_subset(u, self)
        return u

    def superset(self) -> "Universe":
        u = Universe()
        solver().register_subset(self, u)
        return u


class UniverseSolver:
    """Tracks equality (union-find) and subset (DAG over representatives)."""

    def __init__(self) -> None:
        self._parent: dict[int, int] = {}
        self._subsets: dict[int, set[int]] = {}  # rep -> set of reps it is a subset of

    def _find(self, x: int) -> int:
        p = self._parent.get(x, x)
        if p == x:
            return x
        r = self._find(p)
        self._parent[x] = r
        return r

    def register_equal(self, a: Universe, b: Universe) -> None:
        ra, rb = self._find(a.id), self._find(b.id)
        if ra != rb:
            self._parent[ra] = rb
            self._subsets.setdefault(rb, set()).update(self._subsets.pop(ra, set()))

    def register_subset(self, sub: Universe, sup: Universe) -> None:
        self._subsets.setdefault(self._find(sub.id), set()).add(self._find(sup.id))

    def query_are_equal(self, a: Universe, b: Universe) -> bool:
        return self._find(a.id) == self._find(b.id)

    def query_is_subset(self, sub: Universe, sup: Universe) -> bool:
        start, goal = self._find(sub.id), self._find(sup.id)
        if start == goal:
            return True
        seen: set[int] = set()
        stack = [start]
        while stack:
            cur = stack.pop()
            if cur == goal:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self._find(s) for s in self._subsets.get(cur, ()))
        return False


_solver = UniverseSolver()


def solver() -> UniverseSolver:
    return _solver


def reset_solver() -> None:
    global _solver
    _solver = UniverseSolver()
