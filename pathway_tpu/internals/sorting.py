"""Sorted prev/next neighbor maintenance (``Table.sort``).

Counterpart of the reference's ``prev_next.rs`` timely operator (built on its patched
bidirectional differential cursors, SURVEY §2.9): for every row, emit pointers to the
previous/next row in ``key`` order within its ``instance`` partition. Output universe
equals the input universe; columns are ``prev``/``next`` Optional[Pointer].

Incrementality: each instance's order lives in a blocked sorted list
(``_BlockedSortedList`` — list-of-blocks, the sortedcontainers design), so a
1-row change costs O(log n) search + an O(sqrt n) block memmove instead of the
flat list's O(n) memmove; neighbor queries are block-local with edge
spillover, the role of the reference's O(1) bidirectional cursors. Only the
mutated rows' neighborhoods re-derive. Instances are independent, so the node
shards by instance hash across workers (SOLO only for the global
single-instance sort).
"""

from __future__ import annotations

import bisect
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.logical import LogicalNode


class _BlockedSortedList:
    """Sorted multiset of comparable items in ~sqrt(n) blocks.

    insert/remove: O(log n) block search + O(block) memmove. neighbors:
    block-local lookups spilling into adjacent blocks at the edges."""

    LOAD = 512

    __slots__ = ("_blocks", "_maxes", "_len")

    def __init__(self) -> None:
        self._blocks: list[list] = []
        self._maxes: list = []
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def _block_of(self, item) -> int:
        b = bisect.bisect_left(self._maxes, item)
        return min(b, len(self._blocks) - 1)

    def insert(self, item) -> None:
        if not self._blocks:
            self._blocks.append([item])
            self._maxes.append(item)
            self._len = 1
            return
        b = self._block_of(item)
        block = self._blocks[b]
        bisect.insort(block, item)
        self._maxes[b] = block[-1]
        self._len += 1
        if len(block) > 2 * self.LOAD:
            half = len(block) // 2
            right = block[half:]
            del block[half:]
            self._blocks.insert(b + 1, right)
            self._maxes[b] = block[-1]
            self._maxes.insert(b + 1, right[-1])

    def remove(self, item) -> bool:
        if not self._blocks:
            return False
        b = self._block_of(item)
        block = self._blocks[b]
        pos = bisect.bisect_left(block, item)
        if pos >= len(block) or block[pos] != item:
            return False
        block.pop(pos)
        self._len -= 1
        if not block:
            del self._blocks[b]
            del self._maxes[b]
        elif len(block) < self.LOAD // 2 and len(self._blocks) > 1:
            # merge undersized blocks (sortedcontainers discipline) so churn
            # cannot degrade toward one-element blocks / O(n) block lists
            nb = b + 1 if b + 1 < len(self._blocks) else b - 1
            lo, hi = min(b, nb), max(b, nb)
            merged = self._blocks[lo] + self._blocks[hi]
            self._blocks[lo] = merged
            self._maxes[lo] = merged[-1]
            del self._blocks[hi]
            del self._maxes[hi]
            if len(merged) > 2 * self.LOAD:
                half = len(merged) // 2
                right = merged[half:]
                del merged[half:]
                self._blocks.insert(lo + 1, right)
                self._maxes[lo] = merged[-1]
                self._maxes.insert(lo + 1, right[-1])
        else:
            self._maxes[b] = block[-1]
        return True

    def neighbors(self, item) -> tuple[Any, Any]:
        """(previous item, next item) around ``item`` (which must be present),
        None at the ends."""
        b = self._block_of(item)
        block = self._blocks[b]
        pos = bisect.bisect_left(block, item)
        prev_item = None
        next_item = None
        if pos > 0:
            prev_item = block[pos - 1]
        elif b > 0:
            prev_item = self._blocks[b - 1][-1]
        if pos + 1 < len(block):
            next_item = block[pos + 1]
        elif b + 1 < len(self._blocks):
            next_item = self._blocks[b + 1][0]
        return prev_item, next_item

    def __contains__(self, item) -> bool:
        if not self._blocks:
            return False
        b = self._block_of(item)
        block = self._blocks[b]
        pos = bisect.bisect_left(block, item)
        return pos < len(block) and block[pos] == item


class SortNode(Node):
    name = "sort"

    snapshot_attrs = ("_row_info", "_orders", "_emitted")

    def exchange_key(self, port):
        if self.instance_fn is None:
            from pathway_tpu.engine.graph import SOLO

            return SOLO  # one global order: serial
        # Per-instance orders are independent: shard by instance hash. Engine
        # contract note: updates arrive as retract+insert pairs, and each leg
        # carries its own row values — the retraction hashes the OLD instance
        # and reaches the shard holding the old entry. A bare re-insert that
        # CHANGES the instance (out of contract) would leave stale state on
        # the old shard; the in-node upsert defense below still covers bare
        # re-inserts that keep their instance (same shard).
        from pathway_tpu.internals.keys import hash_column

        fn = self.instance_fn

        def key_fn(batch):
            vals = np.asarray(fn(batch))
            if vals.dtype.kind not in "OUS":
                return hash_column(vals)
            out = np.empty(len(vals), dtype=object)
            out[:] = list(vals)
            return hash_column(out)

        return key_fn

    def __init__(
        self,
        key_fn: Callable[[DeltaBatch], np.ndarray],
        instance_fn: Callable[[DeltaBatch], np.ndarray] | None,
    ):
        super().__init__(n_inputs=1)
        self.key_fn = key_fn
        self.instance_fn = instance_fn
        # row key -> (instance, sort_key); instance -> blocked sorted list of
        # (sort_key, row_key)
        self._row_info: dict[int, tuple[Any, Any]] = {}
        self._orders: dict[Any, _BlockedSortedList] = {}
        # row key -> (prev, next) currently emitted
        self._emitted: dict[int, tuple[int | None, int | None]] = {}

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        sort_keys = self.key_fn(batch)
        instances = (
            self.instance_fn(batch)
            if self.instance_fn is not None
            else np.zeros(len(batch), dtype=np.int64)
        )
        # only the NEIGHBORHOODS of mutated rows can change their (prev, next)
        # pair — collect affected keys instead of rescanning whole instances
        affected: dict = {}

        def note_neighbors(inst, item) -> None:
            order = self._orders.get(inst)
            if order is None or item not in order:
                return
            prev_item, next_item = order.neighbors(item)
            aff = affected.setdefault(inst, set())
            if prev_item is not None:
                aff.add(prev_item[1])
            if next_item is not None:
                aff.add(next_item[1])

        for i in range(len(batch)):
            key = int(batch.keys[i])
            if batch.diffs[i] > 0:
                old_info = self._row_info.get(key)
                if old_info is not None:
                    # upsert: a re-inserted key must not duplicate its entry
                    note_neighbors(old_info[0], (old_info[1], key))
                    oorder = self._orders.get(old_info[0])
                    if oorder is not None:
                        oorder.remove((old_info[1], key))
                info = (instances[i], sort_keys[i])
                self._row_info[key] = info
                order = self._orders.get(info[0])
                if order is None:
                    order = self._orders[info[0]] = _BlockedSortedList()
                order.insert((info[1], key))
                aff = affected.setdefault(info[0], set())
                aff.add(key)
                note_neighbors(info[0], (info[1], key))
            else:
                info = self._row_info.pop(key, None)
                if info is None:
                    continue
                note_neighbors(info[0], (info[1], key))
                order = self._orders.get(info[0])
                if order is not None:
                    order.remove((info[1], key))

        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []

        def emit(key: int, pair: tuple, diff: int) -> None:
            out_keys.append(key)
            out_diffs.append(diff)
            out_rows.append(pair)

        for inst, keys in affected.items():
            order = self._orders.get(inst)
            for key in sorted(keys):
                info = self._row_info.get(key)
                if info is None:
                    continue  # deleted this batch; retraction emitted below
                prev_item, next_item = order.neighbors((info[1], key))
                prev_key = prev_item[1] if prev_item is not None else None
                next_key = next_item[1] if next_item is not None else None
                pair = (prev_key, next_key)
                old = self._emitted.get(key)
                if old == pair:
                    continue
                if old is not None:
                    emit(key, old, -1)
                emit(key, pair, +1)
                self._emitted[key] = pair
        # rows deleted from the order need their last emission retracted
        for i in range(len(batch)):
            key = int(batch.keys[i])
            if batch.diffs[i] < 0 and key not in self._row_info:
                old = self._emitted.pop(key, None)
                if old is not None:
                    emit(key, old, -1)
        if not out_keys:
            return []
        return [
            DeltaBatch.from_rows(out_keys, out_rows, ["prev", "next"], time, diffs=out_diffs)
        ]


def sort_impl(table, key_expr, instance_expr=None):
    from pathway_tpu.internals import schema as schema_mod
    from pathway_tpu.internals.table import Table, _compile_single

    key_fn = _compile_single(key_expr, table)
    inst_fn = _compile_single(instance_expr, table) if instance_expr is not None else None
    node = LogicalNode(lambda: SortNode(key_fn, inst_fn), [table._node], name="sort")
    schema = schema_mod.schema_from_dtypes(
        {"prev": dt.Optional(dt.Pointer()), "next": dt.Optional(dt.Pointer())}
    )
    # same universe: every input row gets exactly one (prev, next) row
    return Table(node, schema, table._universe)
