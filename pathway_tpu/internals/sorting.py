"""Sorted prev/next neighbor maintenance (``Table.sort``).

Counterpart of the reference's ``prev_next.rs`` timely operator (built on its patched
bidirectional differential cursors, SURVEY §2.9): for every row, emit pointers to the
previous/next row in ``key`` order within its ``instance`` partition. Output universe
equals the input universe; columns are ``prev``/``next`` Optional[Pointer].

Incrementality: the node keeps each instance's order as a sorted list and the
previously-emitted (prev, next) per key; a delta re-derives only the mutated rows'
neighborhoods (cursor-local, like the reference's bidirectional cursors) — a 1-row
change does O(log n) python work plus the list memmove, not an instance rescan.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.logical import LogicalNode


class SortNode(Node):
    name = "sort"

    snapshot_attrs = ("_row_info", "_orders", "_emitted")

    def exchange_key(self, port):
        from pathway_tpu.engine.graph import SOLO

        return SOLO  # global-watermark / ordered state: serial on worker 0

    def __init__(
        self,
        key_fn: Callable[[DeltaBatch], np.ndarray],
        instance_fn: Callable[[DeltaBatch], np.ndarray] | None,
    ):
        super().__init__(n_inputs=1)
        self.key_fn = key_fn
        self.instance_fn = instance_fn
        # row key -> (instance, sort_key); instance -> sorted [(sort_key, row_key)]
        self._row_info: dict[int, tuple[Any, Any]] = {}
        self._orders: dict[Any, list[tuple[Any, int]]] = {}
        # row key -> (prev, next) currently emitted
        self._emitted: dict[int, tuple[int | None, int | None]] = {}

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        sort_keys = self.key_fn(batch)
        instances = (
            self.instance_fn(batch)
            if self.instance_fn is not None
            else np.zeros(len(batch), dtype=np.int64)
        )
        # only the NEIGHBORHOODS of mutated rows can change their (prev, next)
        # pair — collect affected keys instead of rescanning whole instances
        # (the rescan made a 1-row delta cost O(instance) in python; VERDICT r2
        # carried this from r1)
        affected: dict = {}
        for i in range(len(batch)):
            key = int(batch.keys[i])
            if batch.diffs[i] > 0:
                old_info = self._row_info.get(key)
                if old_info is not None:
                    # upsert: a re-inserted key must not duplicate its entry
                    oorder = self._orders.get(old_info[0], [])
                    opos = bisect.bisect_left(oorder, (old_info[1], key))
                    if opos < len(oorder) and oorder[opos] == (old_info[1], key):
                        oorder.pop(opos)
                        oaff = affected.setdefault(old_info[0], set())
                        if opos > 0:
                            oaff.add(oorder[opos - 1][1])
                        if opos < len(oorder):
                            oaff.add(oorder[opos][1])
                info = (instances[i], sort_keys[i])
                self._row_info[key] = info
                order = self._orders.setdefault(info[0], [])
                pos = bisect.bisect_left(order, (info[1], key))
                order.insert(pos, (info[1], key))
                aff = affected.setdefault(info[0], set())
                aff.add(key)
                if pos > 0:
                    aff.add(order[pos - 1][1])
                if pos + 1 < len(order):
                    aff.add(order[pos + 1][1])
            else:
                info = self._row_info.pop(key, None)
                if info is None:
                    continue
                order = self._orders.get(info[0], [])
                pos = bisect.bisect_left(order, (info[1], key))
                if pos < len(order) and order[pos] == (info[1], key):
                    order.pop(pos)
                aff = affected.setdefault(info[0], set())
                if pos > 0:
                    aff.add(order[pos - 1][1])
                if pos < len(order):
                    aff.add(order[pos][1])

        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []

        def emit(key: int, pair: tuple, diff: int) -> None:
            out_keys.append(key)
            out_diffs.append(diff)
            out_rows.append(pair)

        for inst, keys in affected.items():
            order = self._orders.get(inst, [])
            for key in sorted(keys):
                info = self._row_info.get(key)
                if info is None:
                    continue  # deleted this batch; retraction emitted below
                pos = bisect.bisect_left(order, (info[1], key))
                prev_key = order[pos - 1][1] if pos > 0 else None
                next_key = order[pos + 1][1] if pos + 1 < len(order) else None
                pair = (prev_key, next_key)
                old = self._emitted.get(key)
                if old == pair:
                    continue
                if old is not None:
                    emit(key, old, -1)
                emit(key, pair, +1)
                self._emitted[key] = pair
        # rows deleted from the order need their last emission retracted
        for i in range(len(batch)):
            key = int(batch.keys[i])
            if batch.diffs[i] < 0 and key not in self._row_info:
                old = self._emitted.pop(key, None)
                if old is not None:
                    emit(key, old, -1)
        if not out_keys:
            return []
        return [
            DeltaBatch.from_rows(out_keys, out_rows, ["prev", "next"], time, diffs=out_diffs)
        ]


def sort_impl(table, key_expr, instance_expr=None):
    from pathway_tpu.internals import schema as schema_mod
    from pathway_tpu.internals.table import Table, _compile_single

    key_fn = _compile_single(key_expr, table)
    inst_fn = _compile_single(instance_expr, table) if instance_expr is not None else None
    node = LogicalNode(lambda: SortNode(key_fn, inst_fn), [table._node], name="sort")
    schema = schema_mod.schema_from_dtypes(
        {"prev": dt.Optional(dt.Pointer()), "next": dt.Optional(dt.Pointer())}
    )
    # same universe: every input row gets exactly one (prev, next) row
    return Table(node, schema, table._universe)
