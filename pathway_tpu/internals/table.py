"""``pw.Table`` — the central user object.

Mirrors the reference's ``python/pathway/internals/table.py`` (~70 methods:
select/filter/groupby/reduce/join*/concat/update_rows/update_cells/with_id_from/
flatten/difference/intersect/restrict/with_universe_of/ix/sort/windowby/...). Methods
are declarative: they create LogicalNodes; nothing computes until ``pw.run`` /
``pw.debug.compute_and_print``. Lowering targets block-oriented engine operators
instead of the reference's per-row differential operators.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import numpy as np

from pathway_tpu.engine import operators as ops
from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.expression_vm import EvalContext, eval_expr
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
    TYPE_ENV,
)
from pathway_tpu.internals.keys import row_keys, sequential_keys
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.universe import Universe, solver

_RESERVED = {"id"}


class Table:
    """A (possibly live) keyed table of rows; all operations are lazy."""

    def __init__(
        self,
        node: LogicalNode,
        schema: schema_mod.SchemaMetaclass,
        universe: Universe | None = None,
    ):
        object.__setattr__(self, "_node", node)
        object.__setattr__(self, "_schema", schema)
        object.__setattr__(self, "_universe", universe or Universe())

    # ------------------------------------------------------------- properties

    @property
    def schema(self) -> schema_mod.SchemaMetaclass:
        return self._schema

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    @property
    def C(self) -> "Table":
        return self

    def column_names(self) -> list[str]:
        return self._schema.column_names()

    def keys(self) -> list[str]:
        return self.column_names()

    def typehints(self) -> dict[str, Any]:
        return self._schema.typehints()

    def __getattr__(self, name: str) -> ColumnReference:
        # allow temporal marker columns (_pw_window etc.) through; other
        # underscore names are internal attributes
        if name.startswith("_") and not name.startswith("_pw_"):
            raise AttributeError(name)
        if name not in self._schema.column_names():
            raise AttributeError(
                f"no column {name!r} in table (has: {self._schema.column_names()})"
            )
        return ColumnReference(self, name)

    def __getitem__(self, name) -> ColumnReference:
        if isinstance(name, ColumnReference):
            name = name.name
        if isinstance(name, list):
            return self.select(*[self[n] for n in name])
        if name == "id":
            return self.id
        if name not in self._schema.column_names():
            raise KeyError(name)
        return ColumnReference(self, name)

    def __iter__(self):
        return iter(self.column_names())

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}" for n in self.column_names())
        return f"<pw.Table ({cols})>"

    # ------------------------------------------------------------- helpers

    def _bind(self, e: Any) -> ColumnExpression:
        return thisclass.bind_expression(expr_mod.wrap(e), self)

    def _named_exprs(self, args: Iterable[Any], kwargs: dict[str, Any]) -> dict[str, ColumnExpression]:
        out: dict[str, ColumnExpression] = {}
        for a in thisclass.expand_args(args, self):
            bound = self._bind(a)
            name = expr_mod.smart_name(bound)
            if name is None:
                raise ValueError(f"positional select args must be column refs, got {a!r}")
            out[name] = bound
        for name, e in kwargs.items():
            if name in _RESERVED:
                raise ValueError(f"column name {name!r} is reserved")
            out[name] = self._bind(e)
        return out

    def _infer_schema(self, exprs: dict[str, ColumnExpression]) -> schema_mod.SchemaMetaclass:
        return schema_mod.schema_from_dtypes({n: e._dtype(TYPE_ENV) for n, e in exprs.items()})

    def pointer_from(self, *args: Any, optional: bool = False, instance: Any = None):
        # args stay unbound: they resolve in the context where the expression is
        # used (``other.select(p=target.pointer_from(pw.this.x))``)
        return expr_mod.PointerExpression(
            self, *[expr_mod.wrap(a) for a in args], optional=optional, instance=instance
        )

    # ------------------------------------------------------------- select family

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        exprs = self._named_exprs(args, kwargs)
        tables = _referenced_tables(exprs.values())
        tables.pop(self, None)
        if not tables:
            schema = self._infer_schema(exprs)
            micro = _microbatch_factory(exprs, self, schema)
            if micro is not None:
                node = LogicalNode(micro, [self._node], name="select_microbatch")
                return Table(node, schema, self._universe)
            program = _compile_program(exprs, self)
            expensive = any(_has_apply(e) for e in exprs.values())
            node = LogicalNode(
                lambda: ops.RowwiseNode(program, expensive=expensive, exprs=exprs),
                [self._node],
                name="select",
            )
            return Table(node, schema, self._universe)
        return _multi_table_select(self, list(tables), exprs, self._infer_schema(exprs))

    def with_columns(self, *args: Any, **kwargs: Any) -> "Table":
        keep = {n: ColumnReference(self, n) for n in self.column_names()}
        new = self._named_exprs(args, kwargs)
        keep.update(new)
        return self.select(**keep)

    def without(self, *columns: Any) -> "Table":
        names = {c.name if isinstance(c, ColumnReference) else c for c in columns}
        remaining = [n for n in self.column_names() if n not in names]
        return self.select(*[ColumnReference(self, n) for n in remaining])

    def rename(self, names_mapping: dict | None = None, **kwargs: Any) -> "Table":
        mapping: dict[str, str] = {}
        if names_mapping:
            for old, new in names_mapping.items():
                old_n = old.name if isinstance(old, ColumnReference) else old
                new_n = new.name if isinstance(new, ColumnReference) else new
                mapping[old_n] = new_n
        for new_n, old in kwargs.items():
            mapping[old.name if isinstance(old, ColumnReference) else old] = new_n
        exprs = {}
        for n in self.column_names():
            exprs[mapping.get(n, n)] = ColumnReference(self, n)
        return self.select(**exprs)

    rename_columns = rename
    rename_by_dict = rename

    def cast_to_types(self, **types: Any) -> "Table":
        exprs: dict[str, ColumnExpression] = {}
        for n in self.column_names():
            if n in types:
                exprs[n] = expr_mod.cast(types[n], ColumnReference(self, n))
            else:
                exprs[n] = ColumnReference(self, n)
        return self.select(**exprs)

    def update_types(self, **types: Any) -> "Table":
        node = LogicalNode(lambda: ops.SelectColumnsNode(self.column_names()), [self._node], name="update_types")
        return Table(node, self._schema.update_types(**types), self._universe)

    def copy(self) -> "Table":
        node = LogicalNode(lambda: ops.SelectColumnsNode(self.column_names()), [self._node], name="copy")
        return Table(node, self._schema, self._universe)

    # ------------------------------------------------------------- filter family

    def filter(self, filter_expression: Any) -> "Table":
        bound = self._bind(filter_expression)
        predicate = _compile_single(bound, self)
        node = LogicalNode(
            lambda: ops.FilterNode(predicate, expr=bound), [self._node], name="filter"
        )
        return Table(node, self._schema, self._universe.subset())

    def split(self, split_expression: Any) -> tuple["Table", "Table"]:
        pos = self.filter(split_expression)
        neg = self.filter(~expr_mod.wrap(split_expression))
        return pos, neg

    # ------------------------------------------------------------- groupby / reduce

    def groupby(
        self,
        *args: Any,
        id: Any = None,  # noqa: A002
        sort_by: Any = None,
        instance: Any = None,
        **kwargs: Any,
    ):
        from pathway_tpu.internals.groupbys import GroupedTable

        grouping = [self._bind(a) for a in args]
        for g in grouping:
            if not isinstance(g, ColumnReference):
                raise ValueError("groupby arguments must be column references")
        return GroupedTable(
            self,
            grouping,
            set_id=self._bind(id) if id is not None else None,
            sort_by=self._bind(sort_by) if sort_by is not None else None,
            instance=self._bind(instance) if instance is not None else None,
        )

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        return self.groupby().reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value: Any = None,
        instance: Any = None,
        acceptor: Callable | None = None,
        name: str | None = None,
    ) -> "Table":
        from pathway_tpu.internals.deduplicate import deduplicate_impl

        return deduplicate_impl(self, value=value, instance=instance, acceptor=acceptor)

    # ------------------------------------------------------------- joins

    def join(self, other: "Table", *on: Any, id: Any = None, how: Any = None, **kw) -> Any:  # noqa: A002
        from pathway_tpu.internals.joins import JoinResult

        mode = how if isinstance(how, str) else (how.value if how is not None else "inner")
        return JoinResult(self, other, on, how=mode or "inner", id_expr=id, **kw)

    def join_inner(self, other: "Table", *on: Any, id: Any = None, **kw) -> Any:  # noqa: A002
        return self.join(other, *on, id=id, how="inner", **kw)

    def join_left(self, other: "Table", *on: Any, id: Any = None, **kw) -> Any:  # noqa: A002
        return self.join(other, *on, id=id, how="left", **kw)

    def join_right(self, other: "Table", *on: Any, id: Any = None, **kw) -> Any:  # noqa: A002
        return self.join(other, *on, id=id, how="right", **kw)

    def join_outer(self, other: "Table", *on: Any, id: Any = None, **kw) -> Any:  # noqa: A002
        return self.join(other, *on, id=id, how="outer", **kw)

    def asof_join(self, other: "Table", t_left: Any, t_right: Any, *on: Any, **kw):
        from pathway_tpu.stdlib.temporal import asof_join

        return asof_join(self, other, t_left, t_right, *on, **kw)

    def asof_now_join(self, other: "Table", *on: Any, **kw):
        from pathway_tpu.stdlib.temporal import asof_now_join

        return asof_now_join(self, other, *on, **kw)

    def ix(self, expression: Any, *, optional: bool = False, context: Any = None) -> "Table":
        """Foreign-key lookup: rows of ``self`` re-pointed through a pointer
        expression into this table (reference ``internals/table.py`` ``ix``)."""
        source = context if context is not None else _table_of(expression)
        if source is None:
            raise ValueError("ix needs a context table (expression has no table)")
        return _ix_impl(self, source, source._bind(expression), optional)

    def ix_ref(self, *args: Any, optional: bool = False, context: Any = None, instance: Any = None) -> "Table":
        source = context
        if source is None:
            raise ValueError("ix_ref requires context=")
        ptr = source.pointer_from(*args, optional=optional, instance=instance)
        return _ix_impl(self, source, ptr, optional)

    def having(self, *indexers: ColumnReference) -> "Table":
        """Filter to rows whose id appears as a value of the given pointer columns
        (reference ``internals/table.py`` having)."""
        out = self
        for indexer in indexers:
            source = _table_of(indexer)
            sel = source.select(ptr=indexer)
            keyset = sel.with_id(sel["ptr"])
            out = out.restrict(keyset, strict=False)
        return out

    # ------------------------------------------------------------- set / universe ops

    def concat(self, *others: "Table") -> "Table":
        return _concat_impl(self, others, reindex=False)

    def concat_reindex(self, *others: "Table") -> "Table":
        return _concat_impl(self, others, reindex=True)

    def update_rows(self, other: "Table") -> "Table":
        if set(other.column_names()) != set(self.column_names()):
            raise ValueError("update_rows requires identical columns")
        cols = self.column_names()
        uni = self._universe.superset()
        solver().register_subset(other._universe, uni)
        return _combine_tables(
            [self, other],
            [ops.SideSpec(required=False), ops.SideSpec(required=False)],
            "update_rows",
            cols,
            {n: self._schema.np_dtypes()[n] for n in cols},
            schema_mod.schema_from_dtypes(
                {n: dt.types_lca(self._schema.dtypes()[n], other._schema.dtypes()[n]) for n in cols}
            ),
            uni,
            name="update_rows",
        )

    def update_cells(self, other: "Table") -> "Table":
        extra = set(other.column_names()) - set(self.column_names())
        if extra:
            raise ValueError(f"update_cells: unknown columns {extra}")
        cols = self.column_names()
        other_cols = other.column_names()
        positions = {n: i for i, n in enumerate(cols)}
        override_positions = [(j, positions[n]) for j, n in enumerate(other_cols)]
        return _combine_tables(
            [self, other],
            [ops.SideSpec(required=True), ops.SideSpec(required=False)],
            "update_cells",
            cols,
            self._schema.np_dtypes(),
            schema_mod.schema_from_dtypes(
                {
                    n: dt.types_lca(self._schema.dtypes()[n], other._schema.dtypes()[n])
                    if n in other_cols
                    else self._schema.dtypes()[n]
                    for n in cols
                }
            ),
            self._universe,
            name="update_cells",
            override_positions=override_positions,
        )

    def restrict(self, other: "Table", strict: bool = True) -> "Table":
        # query_is_subset is reflexive over equal representatives, so the
        # equality case is already covered
        if strict and not solver().query_is_subset(other._universe, self._universe):
            raise ValueError(
                "restrict: the argument's universe is not a known subset of "
                "this table's; use promise_universe_is_subset_of first"
            )
        cols = self.column_names()
        return _combine_tables(
            [self, other],
            [ops.SideSpec(required=True), ops.SideSpec(required=True)],
            "side0",
            cols,
            self._schema.np_dtypes(),
            self._schema,
            other._universe if strict else self._universe.subset(),
            name="restrict",
        )

    def intersect(self, *tables: "Table") -> "Table":
        cols = self.column_names()
        return _combine_tables(
            [self, *tables],
            [ops.SideSpec(required=True)] * (1 + len(tables)),
            "side0",
            cols,
            self._schema.np_dtypes(),
            self._schema,
            self._universe.subset(),
            name="intersect",
        )

    def difference(self, other: "Table") -> "Table":
        cols = self.column_names()
        return _combine_tables(
            [self, other],
            [ops.SideSpec(required=True), ops.SideSpec(required=True, negated=True)],
            "side0",
            cols,
            self._schema.np_dtypes(),
            self._schema,
            self._universe.subset(),
            name="difference",
        )

    def with_universe_of(self, other: "Table") -> "Table":
        solver().register_equal(self._universe, other._universe)
        node = LogicalNode(
            lambda: ops.SelectColumnsNode(self.column_names()), [self._node], name="with_universe_of"
        )
        return Table(node, self._schema, other._universe)

    def promise_universes_are_equal(self, other: "Table") -> "Table":
        solver().register_equal(self._universe, other._universe)
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        solver().register_subset(self._universe, other._universe)
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        solver().register_equal(self._universe, other._universe)
        return self

    def is_subset_of(self, other: "Table") -> bool:
        return solver().query_is_subset(self._universe, other._universe)

    # ------------------------------------------------------------- reindex / flatten

    def with_id_from(self, *args: Any, instance: Any = None) -> "Table":
        exprs = [self._bind(a) for a in args]
        salt = 0 if instance is None else hash(instance) & 0xFFFFFFFF
        key_prog = _compile_key_program(exprs, self, salt)
        node = LogicalNode(lambda: ops.ReindexNode(key_prog), [self._node], name="with_id_from")
        return Table(node, self._schema, Universe())

    def with_id(self, new_id: ColumnReference) -> "Table":
        bound = self._bind(new_id)
        key_prog = _compile_key_program_raw(bound, self)
        node = LogicalNode(lambda: ops.ReindexNode(key_prog), [self._node], name="with_id")
        return Table(node, self._schema, Universe())

    def flatten(self, to_flatten: ColumnReference, *, origin_id: str | None = None) -> "Table":
        bound = self._bind(to_flatten)
        assert isinstance(bound, ColumnReference)
        col = bound.name
        others = [n for n in self.column_names() if n != col]
        if origin_id is not None:
            others = others + ["__origin_id__"]
            base = self.with_columns(**{"__origin_id__": self.id})
        else:
            base = self
        node = LogicalNode(
            lambda: ops.FlattenNode(col, [n for n in others]),
            [base._node],
            name="flatten",
        )
        inner = self._schema.dtypes()[col]
        if isinstance(inner, dt.List):
            flat_dt = inner.wrapped_
        elif isinstance(inner, dt.Tuple) and inner.args:
            flat_dt = inner.args[0]
            for a in inner.args[1:]:
                flat_dt = dt.types_lca(flat_dt, a)
        elif inner == dt.STR:
            flat_dt = dt.STR
        else:
            flat_dt = dt.ANY
        dtypes = {col: flat_dt}
        for n in others:
            dtypes[n] = dt.POINTER if n == "__origin_id__" else self._schema.dtypes()[n]
        out = Table(node, schema_mod.schema_from_dtypes(dtypes), Universe())
        if origin_id is not None:
            out = out.rename(**{origin_id: ColumnReference(out, "__origin_id__")})
        return out

    # ------------------------------------------------------------- sort / temporal

    def sort(self, key: Any, instance: Any = None) -> "Table":
        from pathway_tpu.internals.sorting import sort_impl

        return sort_impl(self, self._bind(key), None if instance is None else self._bind(instance))

    def interpolate(self, timestamp: Any, *values: Any, mode: Any = None) -> "Table":
        from pathway_tpu.stdlib.statistical import InterpolateMode, interpolate

        return interpolate(
            self, timestamp, *values, mode=mode if mode is not None else InterpolateMode.LINEAR
        )

    def _gradual_broadcast(self, threshold_table, lower_column, value_column, upper_column) -> "Table":
        from pathway_tpu.internals.gradual_broadcast import gradual_broadcast_impl

        return gradual_broadcast_impl(
            self, threshold_table, lower_column, value_column, upper_column
        )

    def diff(self, timestamp: Any, *values: Any, instance: Any = None) -> "Table":
        from pathway_tpu.stdlib.ordered import diff_impl

        return diff_impl(self, timestamp, *values, instance=instance)

    def windowby(self, time_expr: Any, *, window: Any, instance: Any = None, behavior: Any = None, **kwargs):
        from pathway_tpu.stdlib.temporal import windowby_impl

        return windowby_impl(self, time_expr, window=window, instance=instance, behavior=behavior, **kwargs)

    def interval_join(self, other, self_time, other_time, interval, *on, how: str = "inner", **kw):
        from pathway_tpu.stdlib.temporal import interval_join

        return interval_join(self, other, self_time, other_time, interval, *on, how=how, **kw)

    def _buffer(self, threshold_column: Any, current_time_column: Any) -> "Table":
        from pathway_tpu.internals.time_ops import buffer_impl

        return buffer_impl(self, threshold_column, current_time_column)

    def _forget(self, threshold_column: Any, current_time_column: Any, mark_forgetting_records: bool = False) -> "Table":
        from pathway_tpu.internals.time_ops import forget_impl

        return forget_impl(self, threshold_column, current_time_column, mark_forgetting_records)

    def _freeze(self, threshold_column: Any, current_time_column: Any) -> "Table":
        from pathway_tpu.internals.time_ops import freeze_impl

        return freeze_impl(self, threshold_column, current_time_column)

    def _forget_immediately(self) -> "Table":
        from pathway_tpu.internals.time_ops import forget_immediately_impl

        return forget_immediately_impl(self)

    # ------------------------------------------------------------- error handling

    def remove_errors(self) -> "Table":
        from pathway_tpu.internals.errors import ERROR

        def no_errors(batch: DeltaBatch) -> np.ndarray:
            mask = np.ones(len(batch), dtype=bool)
            for col in batch.data.values():
                if col.dtype == object:
                    mask &= np.fromiter(
                        (v is not ERROR for v in col), dtype=bool, count=len(col)
                    )
            return mask

        node = LogicalNode(lambda: ops.FilterNode(no_errors), [self._node], name="remove_errors")
        return Table(node, self._schema, self._universe.subset())

    def await_futures(self) -> "Table":
        from pathway_tpu.internals.errors import PENDING

        def no_pending(batch: DeltaBatch) -> np.ndarray:
            mask = np.ones(len(batch), dtype=bool)
            for col in batch.data.values():
                if col.dtype == object:
                    mask &= np.fromiter(
                        (v is not PENDING for v in col), dtype=bool, count=len(col)
                    )
            return mask

        node = LogicalNode(lambda: ops.FilterNode(no_pending), [self._node], name="await_futures")
        dtypes = {
            n: (d.wrapped_ if isinstance(d, dt.Future) else d)
            for n, d in self._schema.dtypes().items()
        }
        return Table(node, schema_mod.schema_from_dtypes(dtypes), self._universe.subset())

    # ------------------------------------------------------------- ingress/egress helpers

    def to(self, sink: Any) -> None:
        sink(self)

    def debug(self, name: str) -> "Table":
        from pathway_tpu import debug as debug_mod

        def printer(batch: DeltaBatch, columns: list[str]) -> None:
            for key, diff, row in batch.rows():
                print(f"[{name}] @{batch.time} {'+' if diff > 0 else '-'} {dict(zip(columns, row))}")

        cols = self.column_names()
        LogicalNode(
            lambda: ops.CallbackOutputNode(cols, printer),
            [self._node],
            name=f"debug:{name}",
        )._register_as_output()
        return self

    def _subscribe_node(
        self,
        on_change: Callable | None = None,
        on_time_end: Callable | None = None,
        on_end: Callable | None = None,
        service_class: str = "interactive",
        route_by: Callable | None = None,
    ) -> LogicalNode:
        cols = self.column_names()

        def factory() -> ops.SubscribeNode:
            n = ops.SubscribeNode(
                cols, on_change, on_time_end, on_end, route_by=route_by
            )
            # flow plane SLO scope: the AIMD controller watches only
            # interactive-class sinks' latency histograms
            n.service_class = service_class
            return n

        node = LogicalNode(factory, [self._node], name="subscribe")
        return node

    # static constructors ------------------------------------------------------

    @staticmethod
    def empty(**kwargs: Any) -> "Table":
        schema = schema_mod.schema_from_types(**kwargs)
        return table_from_static_data([], [], schema)

    @staticmethod
    def from_columns(*args: Any, **kwargs: Any) -> "Table":
        """Build a table from column references sharing one universe
        (reference ``internals/table.py`` from_columns)."""
        exprs: dict[str, Any] = {}
        for a in args:
            if not isinstance(a, ColumnReference):
                raise ValueError("from_columns positional args must be column refs")
            exprs[a.name] = a
        exprs.update(kwargs)
        source = None
        for e in exprs.values():
            t = _table_of(expr_mod.wrap(e))
            if t is not None:
                source = t
                break
        if source is None:
            raise ValueError("from_columns needs at least one column reference")
        return source.select(**exprs)


def _table_of(e: Any) -> Table | None:
    if isinstance(e, ColumnReference) and isinstance(e.table, Table):
        return e.table
    if isinstance(e, expr_mod.PointerExpression) and isinstance(e.table, Table):
        return e.table
    for a in e._args() if isinstance(e, ColumnExpression) else ():
        t = _table_of(a)
        if t is not None:
            return t
    return None


# ---------------------------------------------------------------------------- lowering helpers


def _referenced_tables(exprs: Iterable[ColumnExpression]) -> dict[Table, None]:
    """Tables referenced by ``exprs``, in FIRST-REFERENCE order (an ordered
    dict used as an ordered set). Order is load-bearing: the multi-table
    select lowers into a combine whose input PORTS follow this order, and a
    cluster exchanges blocks by (node_index, port) — a ``set`` here ordered
    sides by object address, so two processes of one cluster could build the
    same logical combine with different port assignments and deliver a side's
    rows to the wrong port (observed as a KeyError — or silent column mixups
    when the schemas happen to agree)."""
    out: dict[Table, None] = {}

    def walk(e: ColumnExpression) -> None:
        if isinstance(e, ColumnReference) and isinstance(e.table, Table):
            out.setdefault(e.table)
        if isinstance(e, expr_mod.PointerExpression) and isinstance(e.table, Table):
            pass  # pointer hashing doesn't need the table's data
        for a in e._args():
            walk(a)

    for e in exprs:
        walk(e)
    return out


def _compile_program(
    exprs: dict[str, ColumnExpression], source: Table
) -> Callable[[DeltaBatch], dict[str, np.ndarray]]:
    items = list(exprs.items())

    def program(batch: DeltaBatch) -> dict[str, np.ndarray]:
        def lookup(ref: ColumnReference) -> np.ndarray:
            if ref.name == "id":
                return batch.keys
            return batch.data[ref.name]

        ctx = EvalContext(lookup, len(batch))
        return {name: np.asarray(eval_expr(e, ctx)) for name, e in items}

    return program


def _has_apply(e) -> bool:
    """Does the expression tree contain a python UDF (ApplyExpression family)?"""
    if isinstance(e, expr_mod.ApplyExpression):
        return True
    return any(_has_apply(a) for a in e._args())


def _microbatch_factory(
    exprs: dict[str, ColumnExpression], source: Table, schema: schema_mod.SchemaMetaclass
) -> Callable | None:
    """Engine-node factory for a select whose top-level columns include
    ``is_batched`` UDF calls (``BatchApplyExpression``) — the device UDF path.

    Routed through :class:`~pathway_tpu.engine.operators.MicrobatchApplyNode`
    so rows accumulate ACROSS ticks per UDF and launch as padded power-of-two
    batches (``PATHWAY_MICROBATCH``; ``off`` restores one call per delta
    block). Returns ``None`` — keep the inline RowwiseNode path — when the
    flag is off or no column is a top-level batch apply.
    """
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    mode = cfg.microbatch
    if mode == "off":
        return None
    udf_items = [
        (n, e)
        for n, e in exprs.items()
        if type(e) is expr_mod.BatchApplyExpression and len(e._args())
    ]
    if not udf_items:
        return None
    udf_names = {n for n, _ in udf_items}
    pass_names = [n for n in exprs if n not in udf_names]
    pre_program = _compile_program({n: exprs[n] for n in pass_names}, source)

    def make_args_program(e: expr_mod.BatchApplyExpression):
        arg_exprs = list(e.args_)
        kw_exprs = list(e.kwargs_.values())

        def args_program(batch: DeltaBatch):
            def lookup(ref: ColumnReference) -> np.ndarray:
                if ref.name == "id":
                    return batch.keys
                return batch.data[ref.name]

            ctx = EvalContext(lookup, len(batch))
            return (
                [np.asarray(eval_expr(a, ctx)) for a in arg_exprs],
                [np.asarray(eval_expr(a, ctx)) for a in kw_exprs],
            )

        return args_program

    specs_cfg = []
    for n, e in udf_items:
        udf = getattr(e, "udf", None)
        specs_cfg.append(
            dict(
                name=n,
                args_program=make_args_program(e),
                fn=e.fn,
                kw_names=list(e.kwargs_.keys()),
                propagate_none=e.propagate_none,
                min_bucket=int(getattr(udf, "microbatch_min_bucket", 8)),
                deterministic=bool(e.deterministic),
            )
        )
    max_batch = max(1, min(
        [cfg.microbatch_max_batch]
        + [
            int(getattr(e, "udf").microbatch_max_batch)
            for _, e in udf_items
            if getattr(getattr(e, "udf", None), "microbatch_max_batch", None)
        ]
    ))
    out_columns = list(exprs.keys())
    np_dtypes = schema.np_dtypes()
    node_mode = "pending" if mode == "pending" else "hold"
    flush_ms = cfg.microbatch_flush_ms

    def factory() -> ops.MicrobatchApplyNode:
        from pathway_tpu.internals.logical import current_build

        build = current_build()
        runtime = build.shared_runtime if build is not None else None
        return ops.MicrobatchApplyNode(
            out_columns,
            pass_names,
            pre_program,
            [ops.MicrobatchUdfSpec(**sc) for sc in specs_cfg],
            np_dtypes=np_dtypes,
            mode=node_mode,
            max_batch=max_batch,
            flush_ms=flush_ms,
            runtime=runtime,
        )

    return factory


def _compile_single(e: ColumnExpression, source: Table) -> Callable[[DeltaBatch], np.ndarray]:
    def single(batch: DeltaBatch) -> np.ndarray:
        def lookup(ref: ColumnReference) -> np.ndarray:
            if ref.name == "id":
                return batch.keys
            return batch.data[ref.name]

        return np.asarray(eval_expr(e, EvalContext(lookup, len(batch))))

    return single


def _compile_key_program(
    exprs: list[ColumnExpression], source: Table, salt: int
) -> Callable[[DeltaBatch], np.ndarray]:
    def key_program(batch: DeltaBatch) -> np.ndarray:
        def lookup(ref: ColumnReference) -> np.ndarray:
            if ref.name == "id":
                return batch.keys
            return batch.data[ref.name]

        ctx = EvalContext(lookup, len(batch))
        cols = [np.asarray(eval_expr(e, ctx)) for e in exprs]
        return row_keys(cols, n=len(batch), salt=salt)

    return key_program


def _compile_key_program_raw(e: ColumnExpression, source: Table) -> Callable[[DeltaBatch], np.ndarray]:
    prog = _compile_single(e, source)

    def key_program(batch: DeltaBatch) -> np.ndarray:
        return prog(batch).astype(np.uint64)

    return key_program


def _combine_tables(
    tables: list[Table],
    sides: list[ops.SideSpec],
    mode: str,
    out_columns: list[str],
    np_dtypes: dict,
    schema: schema_mod.SchemaMetaclass,
    universe: Universe,
    name: str,
    override_positions: list[tuple[int, int]] | None = None,
) -> Table:
    side_columns = [t.column_names() for t in tables]
    node = LogicalNode(
        lambda: ops.CombineNode(
            sides, side_columns, mode, out_columns, np_dtypes,
            override_positions=override_positions,
        ),
        [t._node for t in tables],
        name=name,
    )
    return Table(node, schema, universe)


def _multi_table_select(
    base: Table,
    others: list[Table],
    exprs: dict[str, ColumnExpression],
    schema: schema_mod.SchemaMetaclass,
) -> Table:
    """select referencing same-universe sibling tables: align by key, then map."""
    tables = [base, *others]
    for o in others:
        if not (
            solver().query_are_equal(base._universe, o._universe)
            or solver().query_is_subset(base._universe, o._universe)
        ):
            raise ValueError(
                "select references a table with a different universe; use "
                "with_universe_of / restrict first"
            )
    prefixed: list[str] = []
    for i, t in enumerate(tables):
        prefixed.extend(f"__s{i}__{n}" for n in t.column_names())

    aligned = _combine_tables(
        tables,
        [ops.SideSpec(required=True)] * len(tables),
        "concat",
        prefixed,
        {},
        schema_mod.schema_from_dtypes({p: dt.ANY for p in prefixed}),
        base._universe,
        name="align",
    )
    table_index = {id(t): i for i, t in enumerate(tables)}
    items = list(exprs.items())

    def program(batch: DeltaBatch) -> dict[str, np.ndarray]:
        def lookup(ref: ColumnReference) -> np.ndarray:
            if ref.name == "id":
                return batch.keys
            i = table_index.get(id(ref.table), 0)
            return batch.data[f"__s{i}__{ref.name}"]

        ctx = EvalContext(lookup, len(batch))
        return {name: np.asarray(eval_expr(e, ctx)) for name, e in items}

    expensive = any(_has_apply(e) for e in exprs.values())
    node = LogicalNode(
        lambda: ops.RowwiseNode(program, expensive=expensive),
        [aligned._node],
        name="select_multi",
    )
    return Table(node, schema, base._universe)


def _concat_impl(first: Table, others: tuple[Table, ...], reindex: bool) -> Table:
    tables = [first, *others]
    cols = first.column_names()
    for t in others:
        if set(t.column_names()) != set(cols):
            raise ValueError("concat requires identical column sets")
    dtypes: dict[str, dt.DType] = {}
    for n in cols:
        d = first._schema.dtypes()[n]
        for t in others:
            d = dt.types_lca(d, t._schema.dtypes()[n])
        dtypes[n] = d
    salts = list(range(1, len(tables) + 1)) if reindex else None
    node = LogicalNode(
        lambda: ops.ConcatNode(len(tables), cols, salts),
        [t._node for t in tables],
        name="concat",
    )
    return Table(node, schema_mod.schema_from_dtypes(dtypes), Universe())


def _ix_impl(target: Table, source: Table, ptr_expr: ColumnExpression, optional: bool) -> Table:
    """rows of ``source`` keyed as-is, columns fetched from ``target`` by pointer."""
    from pathway_tpu.internals.joins import join_on_key_cols

    return join_on_key_cols(
        left=source,
        right=target,
        left_key_expr=ptr_expr,
        how="left",
        left_id_only=True,
        take_right_only=True,
        universe=source._universe,
    )


def table_from_static_data(
    keys: list[int],
    rows: list[tuple],
    schema: schema_mod.SchemaMetaclass,
) -> Table:
    cols = schema.column_names()
    np_dtypes = schema.np_dtypes()

    def batch_factory(time: int) -> DeltaBatch:
        return DeltaBatch.from_rows(keys, rows, cols, time, np_dtypes=np_dtypes)

    node = LogicalNode(lambda: ops.StaticInputNode(batch_factory), [], name="static_input")
    return Table(node, schema, Universe())


def table_rows_to_static(
    dicts: list[dict[str, Any]],
    schema: schema_mod.SchemaMetaclass,
    explicit_keys: list[int] | None = None,
) -> Table:
    cols = schema.column_names()
    rows = [tuple(d.get(c) for c in cols) for d in dicts]
    pks = schema.primary_key_columns()
    if explicit_keys is not None:
        keys = list(explicit_keys)
    elif pks:
        key_cols = [np.asarray([r[cols.index(pk)] for r in rows], dtype=object) for pk in pks]
        keys = list(row_keys(key_cols, n=len(rows)))
    else:
        keys = list(sequential_keys(0, len(rows)))
    return table_from_static_data([int(k) for k in keys], rows, schema)
