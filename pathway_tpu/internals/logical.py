"""Logical operator nodes + lowering to the engine graph.

Role of the reference's ``internals/operator.py`` + ``internals/graph_runner/``:
Table methods create ``LogicalNode``s (declarative, lazy — nothing computes until
``pw.run``/``compute_and_print``); lowering walks from requested outputs, instantiates
fresh engine nodes per run (tree-shaking unused operators like
``graph_runner/__init__.py:127,246``), and wires connector drivers into the runtime.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.graph import EngineGraph, Node
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.trace import user_frame as _user_frame


class LogicalNode:
    """A lazy operator: ``factory()`` builds a fresh engine node each run."""

    def __init__(
        self,
        factory: Callable[[], Node],
        inputs: list["LogicalNode"],
        name: str = "op",
        runtime_hook: Callable[[Node, Any], None] | None = None,
    ):
        self.factory = factory
        self.inputs = inputs
        self.name = name
        self.runtime_hook = runtime_hook
        self.node_id: int = -1
        # user code provenance for error annotation (reference trace_user_frame)
        self.user_trace = _user_frame()
        G.register(self)

    def __repr__(self) -> str:
        return f"LogicalNode({self.name}#{self.node_id})"

    def _register_as_output(self) -> "LogicalNode":
        G.outputs.append(self)
        return self


#: the BuildContext currently resolving (factories may inspect worker identity
#: for partitioned sources / sharded sinks); builds are single-threaded per
#: runtime so a module global suffices
_CURRENT_BUILD: "BuildContext | None" = None


def current_build() -> "BuildContext | None":
    return _CURRENT_BUILD


class BuildContext:
    def __init__(
        self,
        runtime: Any = None,
        worker_index: int = 0,
        n_workers: int = 1,
        register: Any = None,
        shared_runtime: Any = None,
    ):
        self.graph = EngineGraph()
        self.built: dict[int, Node] = {}
        self.build_order: list[tuple[LogicalNode, Node]] = []
        self.runtime = runtime
        #: the runtime every worker's build may INSPECT (tick cadence /
        #: streaming-vs-static, e.g. microbatch flush deadlines) — distinct
        #: from ``runtime``, which is set only on the primary build because
        #: runtime_hooks (connector registration) must fire once
        self.shared_runtime = shared_runtime if shared_runtime is not None else runtime
        #: which worker this graph copy belongs to / total worker count —
        #: partitioned sources read disjoint partition sets per worker
        #: (reference: partition-per-worker Kafka, worker-architecture.md:36-47)
        self.worker_index = worker_index
        self.n_workers = n_workers
        #: connector registration available to EVERY worker's build (the
        #: runtime hook fires only on the primary build); sharded runtimes
        #: pass their register_connector so per-worker subjects get drivers
        self.register = register
        self.hooks: list[tuple[LogicalNode, Node]] = []

    def resolve(self, lnode: LogicalNode) -> Node:
        global _CURRENT_BUILD
        node = self.built.get(id(lnode))
        if node is not None:
            return node
        engine_inputs = [self.resolve(i) for i in lnode.inputs]
        prev, _CURRENT_BUILD = _CURRENT_BUILD, self
        try:
            node = lnode.factory()
        finally:
            _CURRENT_BUILD = prev
        node.user_trace = lnode.user_trace
        node.name = lnode.name
        self.graph.add_node(node, engine_inputs)
        self.built[id(lnode)] = node
        self.build_order.append((lnode, node))
        if lnode.runtime_hook is not None:
            self.hooks.append((lnode, node))
        return node

    def finish(self) -> None:
        for lnode, node in self.hooks:
            lnode.runtime_hook(node, self.runtime)


def build_engine_graph(outputs: list[LogicalNode], runtime: Any = None) -> BuildContext:
    ctx = BuildContext(
        runtime, register=None if runtime is None else runtime.register_connector
    )
    for out in outputs:
        ctx.resolve(out)
    ctx.finish()
    return ctx
