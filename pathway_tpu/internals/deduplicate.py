"""``Table.deduplicate`` — acceptor-driven per-instance latest-accepted-row.

Engine counterpart of the reference's deduplicate operator
(``src/engine/dataflow.rs`` ``deduplicate`` + ``stdlib/stateful/deduplicate.py``):
for every ``instance`` the node remembers the last *accepted* value; a new row's
value is passed to ``acceptor(new_value, previous_accepted)`` and, if accepted,
the previously emitted row for that instance is retracted and the new one
emitted. Append-only (like the reference's stateful reducers, retractions of
input rows are rejected).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals.keys import stable_hash_obj
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.universe import Universe


class DeduplicateNode(Node):
    name = "deduplicate"

    snapshot_attrs = ("state",)

    def __init__(
        self,
        columns: list[str],
        value_col: str,
        instance_col: str | None,
        acceptor: Callable[[Any, Any], bool],
    ):
        super().__init__(n_inputs=1)
        self.columns = columns
        self.value_col = value_col
        self.instance_col = instance_col
        self.acceptor = acceptor
        # instance-hash -> (accepted value, emitted row tuple)
        self.state: dict[int, tuple[Any, tuple]] = {}

    def exchange_key(self, port):
        if self.instance_col is None:
            from pathway_tpu.engine.graph import SOLO

            return SOLO  # one global instance: serial
        col = self.instance_col

        def key_fn(batch, c=col):
            arr = batch.data[c]
            return np.fromiter(
                (int(stable_hash_obj(v)) for v in arr), dtype=np.uint64, count=len(arr)
            )

        return key_fn

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None or not len(batch):
            return []
        if (batch.diffs < 0).any():
            raise RuntimeError(
                "deduplicate is append-only: retractions in its input are not supported"
            )
        cols = [batch.data[c] for c in self.columns]
        vals = batch.data[self.value_col]
        if self.instance_col is None:
            inst_keys = [0] * len(batch)
        else:
            inst_arr = batch.data[self.instance_col]
            inst_keys = [int(stable_hash_obj(v)) for v in inst_arr]
        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []
        for i in range(len(batch)):
            ik = inst_keys[i]
            prev = self.state.get(ik)
            new_val = vals[i]
            if prev is not None and not self.acceptor(new_val, prev[0]):
                continue
            row = tuple(c[i] for c in cols)
            if prev is not None:
                out_keys.append(ik)
                out_diffs.append(-1)
                out_rows.append(prev[1])
            out_keys.append(ik)
            out_diffs.append(1)
            out_rows.append(row)
            self.state[ik] = (new_val, row)
        if not out_keys:
            return []
        return [DeltaBatch.from_rows(out_keys, out_rows, self.columns, time, diffs=out_diffs)]


def deduplicate_impl(table, *, value=None, instance=None, acceptor=None):
    from pathway_tpu.internals.table import Table

    if value is None or acceptor is None:
        raise ValueError("deduplicate requires value= and acceptor=")
    value_ref = table._bind(value)
    inst_ref = table._bind(instance) if instance is not None else None
    cols = table._schema.column_names()
    pre = table  # rows flow through unchanged; the node reads raw columns
    value_name = value_ref.name
    inst_name = inst_ref.name if inst_ref is not None else None
    node = LogicalNode(
        lambda: DeduplicateNode(cols, value_name, inst_name, acceptor),
        [pre._node],
        name="deduplicate",
    )
    return Table(node, table._schema, Universe())
