"""Deterministic value fingerprints (reference: ``internals/fingerprints.py``).

Used wherever a stable pseudo-random priority is needed (e.g. louvain's
independent-set move selection). Not a cryptographic hash; stable across runs and
workers so multi-worker executions agree.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pathway_tpu.internals.keys import ref_scalar


def fingerprint(obj: Any, format: str = "u64", seed: int = 0) -> int:  # noqa: A002
    """Deterministic 64-bit fingerprint of a (possibly nested) value."""
    flat = _flatten(obj)
    h = int(ref_scalar(*flat, salt=seed & 0xFFFFFFFF))
    if format == "i64":
        return h - (1 << 64) if h >= (1 << 63) else h
    if format == "u64":
        return h
    raise ValueError(f"unknown fingerprint format {format!r}")


def _flatten(obj: Any) -> list:
    if isinstance(obj, (tuple, list)):
        out: list = []
        for o in obj:
            out.extend(_flatten(o))
            out.append("\x00sep")
        return out
    if isinstance(obj, np.generic):
        return [obj.item()]
    return [obj]
