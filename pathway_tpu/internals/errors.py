"""Error / Pending sentinel values.

Mirrors the reference's ``Value::Error`` poisoning semantics and ``Value::Pending``
(``src/engine/value.rs:207-229``): a failed row-level computation yields ERROR which
propagates through downstream expressions instead of aborting the run (when
``terminate_on_error=False``); PENDING marks fully-async UDF results not yet arrived.
"""

from __future__ import annotations


class _Error:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __bool__(self) -> bool:
        raise ValueError("Error value used in a boolean context")


class _Pending:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Pending"


ERROR = _Error()
PENDING = _Pending()


def is_error(v: object) -> bool:
    return v is ERROR


class EngineError(Exception):
    pass


class EngineErrorWithTrace(EngineError):
    pass
