"""Error / Pending sentinel values.

Mirrors the reference's ``Value::Error`` poisoning semantics and ``Value::Pending``
(``src/engine/value.rs:207-229``): a failed row-level computation yields ERROR which
propagates through downstream expressions instead of aborting the run (when
``terminate_on_error=False``); PENDING marks fully-async UDF results not yet arrived.
"""

from __future__ import annotations


class _Error:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __bool__(self) -> bool:
        raise ValueError("Error value used in a boolean context")


class _Pending:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Pending"


ERROR = _Error()
PENDING = _Pending()


def is_error(v: object) -> bool:
    return v is ERROR


class EngineError(Exception):
    pass


class EngineErrorWithTrace(EngineError):
    pass


class OtherWorkerError(EngineError):
    """A cluster peer process died or stopped responding.

    Structured counterpart of the reference's worker-panic surfacing (SURVEY
    §5.3: a worker panic propagates as ``OtherWorkerError`` to the survivors,
    recovery = restart + persistence replay). Raised by the cluster barrier /
    heartbeat plane instead of a bare ``RuntimeError`` so supervisors and
    operators can see WHICH process failed and WHEN:

    - ``process_id``: the dead peer's ``PATHWAY_PROCESS_ID`` (None if unknown —
      e.g. a startup timeout before any peer identified itself),
    - ``tick``: the last logical tick the peer was known alive at (None if it
      never reported one),
    - ``reason``: short machine-readable cause — ``"disconnected"``,
      ``"heartbeat-timeout"``, ``"barrier-timeout"``, ``"never-joined"``,
      ``"coordinator-lost"``.
    """

    def __init__(
        self,
        message: str,
        *,
        process_id: int | None = None,
        tick: int | None = None,
        reason: str = "unknown",
    ):
        super().__init__(message)
        self.process_id = process_id
        self.tick = tick
        self.reason = reason


# -- error policy (reference: terminate_on_error flag threaded into the engine,
# ``src/engine/error.rs`` + ``internals/run.py``) ------------------------------

# module default is poison-mode (debug/compute tooling inspects ERROR values);
# ``pw.run`` sets the policy from its ``terminate_on_error`` kwarg for the run
_policy = {"terminate": False}


def set_error_policy(terminate: bool) -> None:
    _policy["terminate"] = terminate


def get_error_policy() -> bool:
    return _policy["terminate"]


def report_error(message: str, trace: str = "", operator_id: int = -1):
    """Row-level failure. ``terminate_on_error=True`` (the default) aborts the
    run with the original failure; ``False`` logs to ``pw.global_error_log()``
    and returns ERROR, which poisons downstream expressions instead
    (``Value::Error`` semantics, ``src/engine/value.rs:207-229``)."""
    if _policy["terminate"]:
        raise EngineErrorWithTrace(
            f"{message}\n(set terminate_on_error=False to route row-level "
            "failures to pw.global_error_log() instead)"
        )
    from pathway_tpu.internals.error_log import log_error

    log_error(operator_id, message, trace)
    return ERROR
