"""Legacy ``@pw.transformer`` class syntax (reference:
``internals/row_transformer.py`` + ``graph_runner/row_transformer_operator_
handler.py`` — recursive per-row computers over "complex columns").

Mini-implementation with the same user contract: a transformer class holds
inner ``ClassArg`` classes (one per table); ``input_attribute()`` fields read
the input column of the same name, ``@output_attribute`` methods compute
per-row values that may read other attributes of the same row, other rows via
``self.transformer.<table>[pointer]``, and ``self.id``. Evaluation is
memoized per (table, row, attribute) with cycle detection.

Like the reference's, this API is for small control tables: each tick
re-evaluates over full table snapshots (the hot path belongs to the columnar
relational operators)."""

from __future__ import annotations

from typing import Any, Callable

import pathway_tpu as pw


class _InputAttribute:
    pass


def input_attribute(type: Any = None):  # noqa: A002 — reference-parity name
    return _InputAttribute()


class _OutputAttribute:
    def __init__(self, fn: Callable, output: bool = True):
        self.fn = fn
        self.output = output
        self.name = fn.__name__


def output_attribute(fn: Callable) -> _OutputAttribute:
    return _OutputAttribute(fn, output=True)


def attribute(fn: Callable) -> _OutputAttribute:
    """Computed per-row attribute excluded from the output schema."""
    return _OutputAttribute(fn, output=False)


def method(fn: Callable):
    raise NotImplementedError("@pw.method on row transformers is not supported yet")


def input_method(type: Any = None):  # noqa: A002
    raise NotImplementedError("input_method on row transformers is not supported yet")


class ClassArgMeta(type):
    def __new__(mcs, name, bases, ns, output: Any = None, **kwargs):
        cls = super().__new__(mcs, name, bases, ns)
        cls._output_schema = output
        cls._input_attrs = [k for k, v in ns.items() if isinstance(v, _InputAttribute)]
        cls._computed = {
            k: v for k, v in ns.items() if isinstance(v, _OutputAttribute)
        }
        return cls


class ClassArg(metaclass=ClassArgMeta):
    pass


class _RowView:
    __slots__ = ("_rt", "_table", "_key")

    def __init__(self, rt: "_EvalRuntime", table: str, key: int):
        self._rt = rt
        self._table = table
        self._key = key

    @property
    def id(self) -> int:
        return self._key

    @property
    def transformer(self) -> "_TransformerView":
        return _TransformerView(self._rt)

    def pointer_from(self, *args, **kwargs):
        raise NotImplementedError

    def __getattr__(self, name: str):
        return self._rt.eval_attr(self._table, self._key, name)


class _TableView:
    __slots__ = ("_rt", "_table")

    def __init__(self, rt: "_EvalRuntime", table: str):
        self._rt = rt
        self._table = table

    def __getitem__(self, key) -> _RowView:
        return _RowView(self._rt, self._table, int(key))


class _TransformerView:
    __slots__ = ("_rt",)

    def __init__(self, rt: "_EvalRuntime"):
        self._rt = rt

    def __getattr__(self, name: str):
        return _TableView(self._rt, name)


class _EvalRuntime:
    """Memoized recursive attribute evaluation over full-table snapshots."""

    def __init__(self, specs: dict[str, type], snapshots: dict[str, dict[int, dict]]):
        self.specs = specs
        self.snapshots = snapshots
        self.memo: dict[tuple[str, int, str], Any] = {}
        self.in_flight: set[tuple[str, int, str]] = set()

    def eval_attr(self, table: str, key: int, name: str):
        spec = self.specs[table]
        rows = self.snapshots[table]
        if key not in rows:
            raise KeyError(f"transformer: no row {key!r} in table {table!r}")
        if name in spec._input_attrs:
            return rows[key][name]
        computed = spec._computed.get(name)
        if computed is None:
            raise AttributeError(f"transformer table {table!r} has no attribute {name!r}")
        memo_key = (table, key, name)
        if memo_key in self.memo:
            return self.memo[memo_key]
        if memo_key in self.in_flight:
            raise RecursionError(
                f"transformer: cyclic attribute dependency at {table}.{name}"
            )
        self.in_flight.add(memo_key)
        try:
            value = computed.fn(_RowView(self, table, key))
        finally:
            self.in_flight.discard(memo_key)
        self.memo[memo_key] = value
        return value


def transformer(cls: type):
    """Decorator turning a class of inner ``ClassArg`` classes into a callable
    over tables; the result object exposes one output table per inner class."""
    specs: dict[str, type] = {
        k: v
        for k, v in vars(cls).items()
        if isinstance(v, type) and issubclass(v, ClassArg)
    }
    if not specs:
        raise TypeError("@pw.transformer needs at least one inner ClassArg class")
    order = list(specs)

    class _Result:
        def __init__(self, outputs: dict[str, "pw.Table"]):
            for name, table in outputs.items():
                setattr(self, name, table)

    def run(*tables: "pw.Table", **named: "pw.Table") -> _Result:
        if len(tables) > len(order):
            raise TypeError(
                f"transformer takes {len(order)} tables ({order}), got {len(tables)}"
            )
        bound: dict[str, pw.Table] = dict(zip(order, tables))
        dupes = set(bound) & set(named)
        if dupes:
            raise TypeError(f"transformer tables passed twice: {sorted(dupes)}")
        bound.update(named)
        missing = set(order) - set(bound)
        if missing:
            raise TypeError(f"transformer missing tables: {sorted(missing)}")

        # gather every table into ONE snapshot blob (tagged rows concat into a
        # single global reduce, so one empty input can't empty the others)
        col_lists = {name: bound[name].column_names() for name in order}
        tagged = []
        for n_idx, name in enumerate(order):
            t = bound[name]
            cols = col_lists[name]
            tagged.append(
                t.select(
                    p=pw.apply(
                        lambda i, *vs, tag=n_idx: (tag, int(i), vs),
                        t.id,
                        *[t[c] for c in cols],
                    )
                )
            )
        cat = tagged[0] if len(tagged) == 1 else pw.Table.concat_reindex(*tagged)
        combined = cat.reduce(all=pw.reducers.sorted_tuple(cat.p))

        outputs: dict[str, pw.Table] = {}
        for out_name in order:
            spec = specs[out_name]
            out_attrs = [k for k, v in spec._computed.items() if v.output]

            def evaluate(all_rows, out_name=out_name, out_attrs=out_attrs):
                snapshots: dict[str, dict[int, dict]] = {n: {} for n in order}
                for tag, key, vals in all_rows:
                    name = order[tag]
                    snapshots[name][key] = dict(zip(col_lists[name], vals))
                rt = _EvalRuntime(specs, snapshots)
                return tuple(
                    (key,) + tuple(rt.eval_attr(out_name, key, a) for a in out_attrs)
                    for key in snapshots[out_name]
                )

            applied = combined.select(out=pw.apply(evaluate, combined.all))
            flat = applied.flatten(applied.out)
            unpacked = flat.select(
                idd=pw.apply(lambda r: r[0], flat.out),
                **{
                    a: pw.apply(lambda r, j=j: r[1 + j], flat.out)
                    for j, a in enumerate(out_attrs)
                },
            )
            rekeyed = unpacked.with_id(unpacked.idd)
            out = rekeyed.select(**{a: rekeyed[a] for a in out_attrs})
            if spec._output_schema is not None:
                out = out.update_types(**spec._output_schema.typehints())
            outputs[out_name] = out
        return _Result(outputs)

    run.__name__ = cls.__name__
    return run
