"""Lazy column-expression AST.

Mirrors the reference's ``python/pathway/internals/expression.py`` (ColumnExpression +
~25 node types built by operator overloading: ref/const/binop/unop/reducer/apply/
async-apply/cast/convert/coalesce/require/if_else/pointer/make_tuple/get/method-call/
unwrap/fill_error) with the same user surface. Unlike the reference — which compiles
these per-row into a Rust expression VM (``src/engine/expression.rs``) — this AST is
compiled into **vectorized columnar kernels** over delta blocks
(``pathway_tpu/engine/expression_vm.py``): numpy ufuncs on the host. Offloading
relational blocks to jitted JAX was measured in ``benchmarks/jax_kernel_bench.py``
and adopted only where it won — the join probe (``engine/jax_kernels.py``); the
expression VM itself stays numpy (the measured-faster path), and device compute is
reserved for the FLOP-dense ops (encoder/KNN/reranker).
"""

from __future__ import annotations

import datetime
from typing import TYPE_CHECKING, Any, Callable, Iterable, Mapping

from pathway_tpu.internals import dtype as dt

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class ColumnExpression:
    """Base lazy expression. Build with operator overloading: ``pw.this.a + 1``."""

    _dtype_cache: dt.DType | None = None

    # --- arithmetic ---
    def __add__(self, other):
        return BinOpExpression("+", self, wrap(other))

    def __radd__(self, other):
        return BinOpExpression("+", wrap(other), self)

    def __sub__(self, other):
        return BinOpExpression("-", self, wrap(other))

    def __rsub__(self, other):
        return BinOpExpression("-", wrap(other), self)

    def __mul__(self, other):
        return BinOpExpression("*", self, wrap(other))

    def __rmul__(self, other):
        return BinOpExpression("*", wrap(other), self)

    def __truediv__(self, other):
        return BinOpExpression("/", self, wrap(other))

    def __rtruediv__(self, other):
        return BinOpExpression("/", wrap(other), self)

    def __floordiv__(self, other):
        return BinOpExpression("//", self, wrap(other))

    def __rfloordiv__(self, other):
        return BinOpExpression("//", wrap(other), self)

    def __mod__(self, other):
        return BinOpExpression("%", self, wrap(other))

    def __rmod__(self, other):
        return BinOpExpression("%", wrap(other), self)

    def __pow__(self, other):
        return BinOpExpression("**", self, wrap(other))

    def __rpow__(self, other):
        return BinOpExpression("**", wrap(other), self)

    def __matmul__(self, other):
        return BinOpExpression("@", self, wrap(other))

    def __rmatmul__(self, other):
        return BinOpExpression("@", wrap(other), self)

    def __neg__(self):
        return UnOpExpression("-", self)

    def __abs__(self):
        return ApplyExpression(abs, float, args=(self,))

    # --- comparison ---
    def __eq__(self, other):  # type: ignore[override]
        return BinOpExpression("==", self, wrap(other))

    def __ne__(self, other):  # type: ignore[override]
        return BinOpExpression("!=", self, wrap(other))

    def __lt__(self, other):
        return BinOpExpression("<", self, wrap(other))

    def __le__(self, other):
        return BinOpExpression("<=", self, wrap(other))

    def __gt__(self, other):
        return BinOpExpression(">", self, wrap(other))

    def __ge__(self, other):
        return BinOpExpression(">=", self, wrap(other))

    # --- boolean / bitwise ---
    def __and__(self, other):
        return BinOpExpression("&", self, wrap(other))

    def __rand__(self, other):
        return BinOpExpression("&", wrap(other), self)

    def __or__(self, other):
        return BinOpExpression("|", self, wrap(other))

    def __ror__(self, other):
        return BinOpExpression("|", wrap(other), self)

    def __xor__(self, other):
        return BinOpExpression("^", self, wrap(other))

    def __rxor__(self, other):
        return BinOpExpression("^", wrap(other), self)

    def __invert__(self):
        return UnOpExpression("~", self)

    def __bool__(self):
        raise RuntimeError(
            "ColumnExpression is lazy and cannot be used as a bool; "
            "use &, |, ~ instead of and/or/not"
        )

    def __hash__(self) -> int:
        return id(self)

    # --- containers ---
    def __getitem__(self, item) -> "GetExpression":
        return GetExpression(self, wrap(item), check_if_exists=False)

    def get(self, index, default=None) -> "GetExpression":
        return GetExpression(self, wrap(index), default=wrap(default), check_if_exists=True)

    # --- misc API (mirrors reference ColumnExpression methods) ---
    def is_none(self) -> "IsNoneExpression":
        return IsNoneExpression(self)

    def is_not_none(self) -> "IsNotNoneExpression":
        return IsNotNoneExpression(self)

    def as_int(self):
        return ConvertExpression(dt.INT, self)

    def as_float(self):
        return ConvertExpression(dt.FLOAT, self)

    def as_str(self):
        return ConvertExpression(dt.STR, self)

    def as_bool(self):
        return ConvertExpression(dt.BOOL, self)

    def to_string(self):
        return MethodCallExpression("gen", "to_string", (self,))

    def fill_error(self, replacement) -> "FillErrorExpression":
        return FillErrorExpression(self, wrap(replacement))

    @property
    def dt(self) -> "DateTimeNamespace":
        return DateTimeNamespace(self)

    @property
    def str(self) -> "StringNamespace":
        return StringNamespace(self)

    @property
    def num(self) -> "NumericalNamespace":
        return NumericalNamespace(self)

    # --- internals ---
    def _args(self) -> tuple["ColumnExpression", ...]:
        return ()

    def _with_args(self, args: tuple["ColumnExpression", ...]) -> "ColumnExpression":
        return self

    def _dtype(self, env: "TypeEnv") -> dt.DType:
        raise NotImplementedError


ColumnExpressionOrValue = Any


def wrap(value: ColumnExpressionOrValue) -> ColumnExpression:
    if isinstance(value, ColumnExpression):
        return value
    return ConstExpression(value)


def smart_name(expr: ColumnExpression) -> str | None:
    if isinstance(expr, ColumnReference):
        return expr.name
    return None


class TypeEnv:
    """Maps tables to schemas during static type inference (role of the reference's
    ``internals/type_interpreter.py``)."""

    def __init__(self) -> None:
        pass

    def dtype_of(self, ref: "ColumnReference") -> dt.DType:
        table = ref.table
        if table is None:
            raise RuntimeError(f"unbound column reference {ref.name!r}")
        if ref.name == "id":
            return dt.POINTER
        return table.schema.dtypes()[ref.name]


TYPE_ENV = TypeEnv()


class ColumnReference(ColumnExpression):
    """``table.colname`` / ``pw.this.colname`` (bound during desugaring)."""

    def __init__(self, table: "Table | None", name: str):
        self.table = table
        self.name = name

    def __repr__(self) -> str:
        t = "this" if self.table is None else f"<table {id(self.table):x}>"
        return f"{t}.{self.name}"

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return env.dtype_of(self)

    @property
    def _column_name(self) -> str:
        return self.name


class ConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        self.value = value

    def __repr__(self) -> str:
        return repr(self.value)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.dtype_of_value(self.value)


_ARITH = {"+", "-", "*", "/", "//", "%", "**", "@"}
_CMP = {"==", "!=", "<", "<=", ">", ">="}
_BITS = {"&", "|", "^"}


def binop_result_type(op: str, lt: dt.DType, rt: dt.DType) -> dt.DType:
    l, r = dt.unoptionalize(lt), dt.unoptionalize(rt)
    opt = lt.is_optional() or rt.is_optional()

    def out(d: dt.DType) -> dt.DType:
        return dt.Optional(d) if opt and op not in _CMP else d

    if op in _CMP:
        return dt.BOOL
    if op in _BITS:
        if l == dt.BOOL and r == dt.BOOL:
            return out(dt.BOOL)
        if l == dt.INT and r == dt.INT:
            return out(dt.INT)
        return out(dt.ANY)
    num = {dt.INT, dt.FLOAT}
    if l in num and r in num:
        if op == "/":
            return out(dt.FLOAT)
        if op in ("//", "%") and l == dt.INT and r == dt.INT:
            return out(dt.INT)
        if l == dt.FLOAT or r == dt.FLOAT or op == "/":
            return out(dt.FLOAT)
        if op == "**":
            return out(dt.INT)
        return out(dt.INT)
    if l == dt.STR and r == dt.STR and op == "+":
        return out(dt.STR)
    if l == dt.STR and r == dt.INT and op == "*":
        return out(dt.STR)
    dtm = {dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC}
    if l in dtm and r in dtm and op == "-":
        return out(dt.DURATION)
    if l in dtm and r == dt.DURATION and op in ("+", "-"):
        return out(l)
    if l == dt.DURATION and r in dtm and op == "+":
        return out(r)
    if l == dt.DURATION and r == dt.DURATION:
        if op in ("+", "-"):
            return out(dt.DURATION)
        if op == "/":
            return out(dt.FLOAT)
        if op in ("//",):
            return out(dt.INT)
        if op == "%":
            return out(dt.DURATION)
    if l == dt.DURATION and r in num and op in ("*", "/", "//"):
        return out(dt.DURATION)
    if l in num and r == dt.DURATION and op == "*":
        return out(dt.DURATION)
    if isinstance(l, dt.Array) or isinstance(r, dt.Array):
        return out(dt.ANY_ARRAY)
    if isinstance(l, dt.Tuple) and isinstance(r, dt.Tuple) and op == "+":
        return out(dt.Tuple(*(l.args + r.args)))
    return out(dt.ANY)


class BinOpExpression(ColumnExpression):
    def __init__(self, op: str, left: ColumnExpression, right: ColumnExpression):
        self.op = op
        self.left = left
        self.right = right

    def __repr__(self) -> str:
        return f"({self.left!r} {self.op} {self.right!r})"

    def _args(self):
        return (self.left, self.right)

    def _with_args(self, args):
        return BinOpExpression(self.op, *args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return binop_result_type(self.op, self.left._dtype(env), self.right._dtype(env))


class UnOpExpression(ColumnExpression):
    def __init__(self, op: str, operand: ColumnExpression):
        self.op = op
        self.operand = operand

    def _args(self):
        return (self.operand,)

    def _with_args(self, args):
        return UnOpExpression(self.op, *args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        inner = self.operand._dtype(env)
        if self.op == "~":
            return inner
        return inner  # unary minus preserves numeric dtype


class IsNoneExpression(ColumnExpression):
    def __init__(self, operand: ColumnExpression):
        self.operand = operand

    def _args(self):
        return (self.operand,)

    def _with_args(self, args):
        return IsNoneExpression(*args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.BOOL


class IsNotNoneExpression(IsNoneExpression):
    def _with_args(self, args):
        return IsNotNoneExpression(*args)


class IfElseExpression(ColumnExpression):
    """``pw.if_else(cond, then, else_)``."""

    def __init__(self, if_: ColumnExpression, then: ColumnExpression, else_: ColumnExpression):
        self.if_ = if_
        self.then = then
        self.else_ = else_

    def _args(self):
        return (self.if_, self.then, self.else_)

    def _with_args(self, args):
        return IfElseExpression(*args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.types_lca(self.then._dtype(env), self.else_._dtype(env))


class CoalesceExpression(ColumnExpression):
    def __init__(self, *args: ColumnExpression):
        self.args = tuple(wrap(a) for a in args)

    def _args(self):
        return self.args

    def _with_args(self, args):
        return CoalesceExpression(*args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        out: dt.DType | None = None
        for a in self.args:
            d = a._dtype(env)
            out = d if out is None else dt.types_lca(out, d)
        assert out is not None
        # if last arg is non-optional, the whole coalesce is non-optional
        if not self.args[-1]._dtype(env).is_optional() and isinstance(out, dt.Optional):
            return out.wrapped
        return out


class RequireExpression(ColumnExpression):
    """``pw.require(val, *conds)`` — None if any cond is None."""

    def __init__(self, val: ColumnExpression, *args: ColumnExpression):
        self.val = wrap(val)
        self.conds = tuple(wrap(a) for a in args)

    def _args(self):
        return (self.val, *self.conds)

    def _with_args(self, args):
        return RequireExpression(*args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.Optional(self.val._dtype(env))


class ApplyExpression(ColumnExpression):
    """``pw.apply(fn, *args)`` — per-row python call (sync)."""

    def __init__(
        self,
        fn: Callable,
        return_type: Any,
        args: tuple = (),
        kwargs: Mapping[str, Any] | None = None,
        propagate_none: bool = False,
        deterministic: bool = True,
    ):
        self.fn = fn
        self.return_type = dt.wrap(return_type) if return_type is not None else dt.ANY
        self.args_ = tuple(wrap(a) for a in args)
        self.kwargs_ = {k: wrap(v) for k, v in (kwargs or {}).items()}
        self.propagate_none = propagate_none
        self.deterministic = deterministic

    def _args(self):
        return self.args_ + tuple(self.kwargs_.values())

    def _with_args(self, args):
        n = len(self.args_)
        new = type(self)(
            self.fn,
            self.return_type,
            args=tuple(args[:n]),
            kwargs=dict(zip(self.kwargs_.keys(), args[n:])),
            propagate_none=self.propagate_none,
            deterministic=self.deterministic,
        )
        if hasattr(self, "udf"):
            # rebinding (pw.this / join / groupby arg resolution) must not
            # strip the UDF backref — the microbatch planner reads its knobs
            new.udf = self.udf
        return new

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return self.return_type


class AsyncApplyExpression(ApplyExpression):
    """``pw.apply_async`` — batched through the microbatcher instead of the
    reference's one-boxed-future-per-row (``src/engine/dataflow.rs:1924-1962``)."""


class BatchApplyExpression(ApplyExpression):
    """``fn`` receives whole columns (lists, one per arg) and returns a list —
    the dispatch shape for TPU model UDFs (embedders/rerankers): one jitted call
    per delta block instead of a Python call per row."""


class FullyAsyncApplyExpression(ApplyExpression):
    """Returns Pending immediately, result arrives as a later update."""

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.Future(self.return_type)


class CastExpression(ColumnExpression):
    def __init__(self, target: Any, expr: ColumnExpression):
        self.target = dt.wrap(target)
        self.expr = wrap(expr)

    def _args(self):
        return (self.expr,)

    def _with_args(self, args):
        return CastExpression(self.target, *args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        if self.expr._dtype(env).is_optional():
            return dt.Optional(self.target)
        return self.target


class ConvertExpression(ColumnExpression):
    """Json/any → concrete type conversion (``as_int`` etc.)."""

    def __init__(self, target: dt.DType, expr: ColumnExpression, unwrap: bool = False):
        self.target = target
        self.expr = wrap(expr)
        self.unwrap_ = unwrap

    def _args(self):
        return (self.expr,)

    def _with_args(self, args):
        return ConvertExpression(self.target, *args, unwrap=self.unwrap_)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return self.target if self.unwrap_ else dt.Optional(self.target)


class DeclareTypeExpression(ColumnExpression):
    def __init__(self, target: Any, expr: ColumnExpression):
        self.target = dt.wrap(target)
        self.expr = wrap(expr)

    def _args(self):
        return (self.expr,)

    def _with_args(self, args):
        return DeclareTypeExpression(self.target, *args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return self.target


class UnwrapExpression(ColumnExpression):
    def __init__(self, expr: ColumnExpression):
        self.expr = wrap(expr)

    def _args(self):
        return (self.expr,)

    def _with_args(self, args):
        return UnwrapExpression(*args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.unoptionalize(self.expr._dtype(env))


class FillErrorExpression(ColumnExpression):
    def __init__(self, expr: ColumnExpression, replacement: ColumnExpression):
        self.expr = wrap(expr)
        self.replacement = wrap(replacement)

    def _args(self):
        return (self.expr, self.replacement)

    def _with_args(self, args):
        return FillErrorExpression(*args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.types_lca(self.expr._dtype(env), self.replacement._dtype(env))


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args: ColumnExpression):
        self.args = tuple(wrap(a) for a in args)

    def _args(self):
        return self.args

    def _with_args(self, args):
        return MakeTupleExpression(*args)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.Tuple(*[a._dtype(env) for a in self.args])


class GetExpression(ColumnExpression):
    def __init__(
        self,
        obj: ColumnExpression,
        index: ColumnExpression,
        default: ColumnExpression | None = None,
        check_if_exists: bool = False,
    ):
        self.obj = wrap(obj)
        self.index = wrap(index)
        self.default = default if default is None else wrap(default)
        self.check_if_exists = check_if_exists

    def _args(self):
        extra = (self.default,) if self.default is not None else ()
        return (self.obj, self.index, *extra)

    def _with_args(self, args):
        if len(args) == 3:
            return GetExpression(args[0], args[1], args[2], self.check_if_exists)
        return GetExpression(args[0], args[1], None, self.check_if_exists)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        objt = dt.unoptionalize(self.obj._dtype(env))
        if isinstance(objt, dt.Tuple) and isinstance(self.index, ConstExpression):
            i = self.index.value
            if isinstance(i, int) and objt.args and -len(objt.args) <= i < len(objt.args):
                out = objt.args[i]
            else:
                out = dt.ANY
        elif isinstance(objt, dt.List):
            out = objt.wrapped_
        elif objt == dt.JSON:
            out = dt.JSON
        elif isinstance(objt, dt.Array):
            out = dt.Array(None if objt.n_dim is None else objt.n_dim - 1, objt.wrapped_) \
                if (objt.n_dim or 2) > 1 else objt.wrapped_
        else:
            out = dt.ANY
        if self.check_if_exists and self.default is not None:
            out = dt.types_lca(out, self.default._dtype(env))
        return out


class MethodCallExpression(ColumnExpression):
    """Namespace method call (``expr.dt.hour()``, ``expr.str.lower()``…)."""

    def __init__(self, namespace: str, name: str, args: tuple, result_dtype: dt.DType | None = None):
        self.namespace = namespace
        self.name = name
        self.args = tuple(wrap(a) for a in args)
        self.result_dtype = result_dtype

    def _args(self):
        return self.args

    def _with_args(self, args):
        return MethodCallExpression(self.namespace, self.name, tuple(args), self.result_dtype)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        if self.result_dtype is not None:
            return self.result_dtype
        from pathway_tpu.engine.namespaces import method_result_dtype

        return method_result_dtype(self.namespace, self.name, [a._dtype(env) for a in self.args])


class PointerExpression(ColumnExpression):
    """``table.pointer_from(*cols)`` — key hash of the argument values."""

    def __init__(self, table: "Table | None", *args: ColumnExpression, optional: bool = False, instance=None):
        self.table = table
        self.args = tuple(wrap(a) for a in args)
        self.optional = optional
        self.instance = instance

    def _args(self):
        return self.args

    def _with_args(self, args):
        return PointerExpression(self.table, *args, optional=self.optional, instance=self.instance)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return dt.Optional(dt.POINTER) if self.optional else dt.POINTER


class ReducerExpression(ColumnExpression):
    """A reducer applied inside ``groupby(...).reduce(...)``."""

    def __init__(self, reducer: "Any", *args: ColumnExpression, **kwargs: Any):
        self.reducer = reducer
        self.args = tuple(wrap(a) for a in args)
        self.kwargs = kwargs

    def __repr__(self) -> str:
        return f"{self.reducer.name}({', '.join(map(repr, self.args))})"

    def _args(self):
        return self.args

    def _with_args(self, args):
        return ReducerExpression(self.reducer, *args, **self.kwargs)

    def _dtype(self, env: TypeEnv) -> dt.DType:
        return self.reducer.result_dtype([a._dtype(env) for a in self.args])


# ----------------------------------------------------------------------------
# namespaces (subset of reference's expressions/date_time.py & string.py)
# ----------------------------------------------------------------------------


class _Namespace:
    _ns: str = ""

    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def _call(self, name: str, *args, result_dtype: dt.DType | None = None):
        return MethodCallExpression(self._ns, name, (self._expr, *args), result_dtype)


class DateTimeNamespace(_Namespace):
    _ns = "dt"

    def nanosecond(self):
        return self._call("nanosecond", result_dtype=dt.INT)

    def microsecond(self):
        return self._call("microsecond", result_dtype=dt.INT)

    def millisecond(self):
        return self._call("millisecond", result_dtype=dt.INT)

    def second(self):
        return self._call("second", result_dtype=dt.INT)

    def minute(self):
        return self._call("minute", result_dtype=dt.INT)

    def hour(self):
        return self._call("hour", result_dtype=dt.INT)

    def day(self):
        return self._call("day", result_dtype=dt.INT)

    def month(self):
        return self._call("month", result_dtype=dt.INT)

    def year(self):
        return self._call("year", result_dtype=dt.INT)

    def day_of_week(self):
        return self._call("day_of_week", result_dtype=dt.INT)

    def timestamp(self, unit: str = "ns"):
        return self._call("timestamp", wrap(unit), result_dtype=dt.FLOAT if unit != "ns" else dt.INT)

    def strftime(self, fmt):
        return self._call("strftime", wrap(fmt), result_dtype=dt.STR)

    def strptime(self, fmt, contains_timezone: bool = False):
        return self._call(
            "strptime",
            wrap(fmt),
            result_dtype=dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE,
        )

    def to_utc(self, from_timezone: str):
        return self._call("to_utc", wrap(from_timezone), result_dtype=dt.DATE_TIME_UTC)

    def to_naive_in_timezone(self, timezone: str):
        return self._call("to_naive_in_timezone", wrap(timezone), result_dtype=dt.DATE_TIME_NAIVE)

    def round(self, duration):
        return self._call("round", wrap(duration))

    def floor(self, duration):
        return self._call("floor", wrap(duration))

    def nanoseconds(self):
        return self._call("nanoseconds", result_dtype=dt.INT)

    def microseconds(self):
        return self._call("microseconds", result_dtype=dt.INT)

    def milliseconds(self):
        return self._call("milliseconds", result_dtype=dt.INT)

    def seconds(self):
        return self._call("seconds", result_dtype=dt.INT)

    def minutes(self):
        return self._call("minutes", result_dtype=dt.INT)

    def hours(self):
        return self._call("hours", result_dtype=dt.INT)

    def days(self):
        return self._call("days", result_dtype=dt.INT)

    def weeks(self):
        return self._call("weeks", result_dtype=dt.INT)

    def from_timestamp(self, unit: str):
        return self._call("from_timestamp", wrap(unit), result_dtype=dt.DATE_TIME_NAIVE)

    def utc_from_timestamp(self, unit: str):
        return self._call("utc_from_timestamp", wrap(unit), result_dtype=dt.DATE_TIME_UTC)


class StringNamespace(_Namespace):
    _ns = "str"

    def lower(self):
        return self._call("lower", result_dtype=dt.STR)

    def upper(self):
        return self._call("upper", result_dtype=dt.STR)

    def strip(self, chars=None):
        return self._call("strip", wrap(chars), result_dtype=dt.STR)

    def lstrip(self, chars=None):
        return self._call("lstrip", wrap(chars), result_dtype=dt.STR)

    def rstrip(self, chars=None):
        return self._call("rstrip", wrap(chars), result_dtype=dt.STR)

    def len(self):
        return self._call("len", result_dtype=dt.INT)

    def reversed(self):
        return self._call("reversed", result_dtype=dt.STR)

    def startswith(self, prefix):
        return self._call("startswith", wrap(prefix), result_dtype=dt.BOOL)

    def endswith(self, suffix):
        return self._call("endswith", wrap(suffix), result_dtype=dt.BOOL)

    def count(self, sub):
        return self._call("count", wrap(sub), result_dtype=dt.INT)

    def find(self, sub):
        return self._call("find", wrap(sub), result_dtype=dt.INT)

    def rfind(self, sub):
        return self._call("rfind", wrap(sub), result_dtype=dt.INT)

    def replace(self, old, new):
        return self._call("replace", wrap(old), wrap(new), result_dtype=dt.STR)

    def split(self, sep=None, maxsplit: int = -1):
        return self._call("split", wrap(sep), wrap(maxsplit), result_dtype=dt.List(dt.STR))

    def slice(self, start, end):
        return self._call("slice", wrap(start), wrap(end), result_dtype=dt.STR)

    def title(self):
        return self._call("title", result_dtype=dt.STR)

    def swapcase(self):
        return self._call("swapcase", result_dtype=dt.STR)

    def parse_int(self, optional: bool = False):
        d = dt.Optional(dt.INT) if optional else dt.INT
        return self._call("parse_int", wrap(optional), result_dtype=d)

    def parse_float(self, optional: bool = False):
        d = dt.Optional(dt.FLOAT) if optional else dt.FLOAT
        return self._call("parse_float", wrap(optional), result_dtype=d)

    def parse_bool(self, optional: bool = False):
        d = dt.Optional(dt.BOOL) if optional else dt.BOOL
        return self._call("parse_bool", wrap(optional), result_dtype=d)


class NumericalNamespace(_Namespace):
    _ns = "num"

    def abs(self):
        return self._call("abs")

    def round(self, decimals=0):
        return self._call("round", wrap(decimals))

    def fill_na(self, default_value):
        return self._call("fill_na", wrap(default_value))


# ----------------------------------------------------------------------------
# public expression-builder functions (``pw.if_else`` etc.)
# ----------------------------------------------------------------------------


def if_else(if_, then, else_) -> IfElseExpression:
    return IfElseExpression(wrap(if_), wrap(then), wrap(else_))


def coalesce(*args) -> CoalesceExpression:
    return CoalesceExpression(*args)


def require(val, *args) -> RequireExpression:
    return RequireExpression(val, *args)


def cast(target, expr) -> CastExpression:
    return CastExpression(target, wrap(expr))


def declare_type(target, expr) -> DeclareTypeExpression:
    return DeclareTypeExpression(target, wrap(expr))


def unwrap(expr) -> UnwrapExpression:
    return UnwrapExpression(wrap(expr))


def fill_error(expr, replacement) -> FillErrorExpression:
    return FillErrorExpression(wrap(expr), wrap(replacement))


def make_tuple(*args) -> MakeTupleExpression:
    return MakeTupleExpression(*args)


def apply(fn: Callable, *args, **kwargs) -> ApplyExpression:
    return_type = _infer_return_type(fn)
    return ApplyExpression(fn, return_type, args=args, kwargs=kwargs)


def apply_with_type(fn: Callable, ret_type: Any, *args, **kwargs) -> ApplyExpression:
    return ApplyExpression(fn, ret_type, args=args, kwargs=kwargs)


def apply_async(fn: Callable, *args, **kwargs) -> AsyncApplyExpression:
    return_type = _infer_return_type(fn)
    return AsyncApplyExpression(fn, return_type, args=args, kwargs=kwargs)


def _infer_return_type(fn: Callable) -> Any:
    try:
        import typing

        hints = typing.get_type_hints(fn)
        return hints.get("return", Any)
    except Exception:
        return Any


def assert_expression_bound(expr: ColumnExpression) -> None:
    for arg in expr._args():
        assert_expression_bound(arg)
    if isinstance(expr, ColumnReference) and expr.table is None:
        raise RuntimeError(f"unbound reference to column {expr.name!r}")
