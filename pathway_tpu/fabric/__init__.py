"""Distributed serving fabric: every cluster process is a front door.

``PATHWAY_FABRIC=on`` (cluster runs only) installs a :class:`FabricPlane`
per process after the dataflow builds:

- **routing** (``routing.py``): peer processes start mirror front doors for
  every registered route; requests landing on a non-owner door are forwarded
  over the fabric transport to the owning process and answered byte-identical
  to hitting the coordinator, with the r16 request trace stitching ingress
  and owner spans under one trace id;
- **replicas** (``replica.py``): ``pw.io.http.serve_table`` routes answer
  read-only lookups locally from a changelog-fed replica with bounded,
  measured staleness (``pathway_fabric_replica_lag_seconds``);
- **index replicas** (``index_replica.py``): ``/v1/retrieve``-style KNN
  routes answer locally at every door from a changelog-fed replica INDEX
  within ``PATHWAY_REPLICA_MAX_STALENESS_MS`` (``pathway_replica_lag_seconds``,
  ``pathway_replica_index_rows``), falling back to the owner forward when
  stale — read qps scales with doors instead of pinning to the owner;
- **limits** (``limits.py``): per-route token buckets and API-key auth run
  at every door (the coordinator's included — those two work without the
  fabric and without a cluster).

Lifecycle mirrors the other planes (flow/elastic/audit): ``install_from_env``
from the cluster runtime once connectors are up, ``current()`` for hot-path
guards, ``shutdown()`` with the run.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.fabric import index_replica, limits, replica, transport  # noqa: F401
from pathway_tpu.fabric.index_replica import ReplicaIndex  # noqa: F401
from pathway_tpu.fabric.limits import ApiKeyGuard, TokenBucket  # noqa: F401
from pathway_tpu.fabric.replica import ReplicaStore, serve_table  # noqa: F401
from pathway_tpu.fabric.transport import FabricUnavailable  # noqa: F401

_plane = None


def current():
    """The installed fabric plane, or None (single-process runs, fabric off)."""
    return _plane


def install_from_env(runtime: Any):
    """Install the fabric on a cluster runtime when ``PATHWAY_FABRIC=on``.
    Called after the graph builds and connectors start (the route registry
    and the owner's webserver are live by then); a single-process run or
    ``off`` installs nothing and costs nothing."""
    global _plane
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    if cfg.fabric == "off" or cfg.processes <= 1:
        _plane = None
        return None
    from pathway_tpu.fabric.routing import FabricPlane

    _plane = FabricPlane(runtime, cfg)
    _plane.install()
    return _plane


def shutdown() -> None:
    global _plane
    if _plane is not None:
        _plane.close()
    _plane = None


def status(runtime: Any) -> dict | None:
    """The ``/status`` fabric section: the plane's view on cluster runs, or
    a replica-only view when ``serve_table`` routes live without a fabric
    (single-process runs)."""
    if _plane is not None and _plane.runtime is runtime:
        return _plane.status()
    routes = replica.live_table_routes(runtime)
    iroutes = index_replica.live_index_routes(runtime)
    if not routes and not iroutes:
        return None
    return {
        "enabled": False,
        "replica": {t.route: t.replica_snapshot() for t in routes},
        "index": {r.route: r.replica_snapshot() for r in iroutes},
    }


def prometheus_lines(runtime: Any) -> list[str]:
    """``pathway_fabric_*`` exposition lines for ``/metrics``."""
    from pathway_tpu.internals.monitoring import escape_label_value

    routes = replica.live_table_routes(runtime)
    lines: list[str] = []
    if routes:
        lines.append(
            "# HELP pathway_fabric_replica_lag_seconds Measured staleness of a served table's local replica (0 on the owner)"
        )
        lines.append("# TYPE pathway_fabric_replica_lag_seconds gauge")
        for t in routes:
            lag = t.store.lag_s()
            if lag is not None:
                label = f'route="{escape_label_value(t.route)}"'
                lines.append(
                    f"pathway_fabric_replica_lag_seconds{{{label}}} {round(lag, 6)}"
                )
        lines.append(
            "# HELP pathway_fabric_replica_rows Rows held by a served table's local store"
        )
        lines.append("# TYPE pathway_fabric_replica_rows gauge")
        for t in routes:
            label = f'route="{escape_label_value(t.route)}"'
            lines.append(f"pathway_fabric_replica_rows{{{label}}} {len(t.store)}")
        lines.append(
            "# HELP pathway_fabric_replica_local_answers_total Lookups answered from the local store"
        )
        lines.append("# TYPE pathway_fabric_replica_local_answers_total counter")
        for t in routes:
            label = f'route="{escape_label_value(t.route)}"'
            lines.append(
                f"pathway_fabric_replica_local_answers_total{{{label}}} {t.local_answers}"
            )
        lines.append(
            "# HELP pathway_fabric_replica_fallback_total Stale-replica lookups forwarded to the owner"
        )
        lines.append("# TYPE pathway_fabric_replica_fallback_total counter")
        for t in routes:
            label = f'route="{escape_label_value(t.route)}"'
            lines.append(
                f"pathway_fabric_replica_fallback_total{{{label}}} {t.fallbacks}"
            )
    iroutes = index_replica.live_index_routes(runtime)
    if iroutes:
        plane = _plane if _plane is not None and _plane.runtime is runtime else None
        n_proc = plane.n_proc if plane is not None else None
        series = [
            (
                "pathway_replica_lag_seconds",
                "gauge",
                "Worst-peer staleness of the local replica index (absent while unsynced)",
            ),
            (
                "pathway_replica_index_rows",
                "gauge",
                "Rows held by the local replica index",
            ),
            (
                "pathway_replica_local_answers_total",
                "counter",
                "Retrieval requests answered from the local replica index",
            ),
            (
                "pathway_replica_fallback_total",
                "counter",
                "Retrieval requests forwarded to the owner (stale/unsynced/unanswerable)",
            ),
            (
                "pathway_replica_gaps_total",
                "counter",
                "Changelog sequence gaps detected (each triggers a snapshot resync)",
            ),
            (
                "pathway_replica_resyncs_total",
                "counter",
                "Snapshot resyncs completed against peer slices",
            ),
        ]
        snaps = [(r, r.replica_snapshot(n_proc)) for r in iroutes]
        keys = {
            "pathway_replica_index_rows": "rows",
            "pathway_replica_local_answers_total": "local_answers",
            "pathway_replica_fallback_total": "fallbacks",
            "pathway_replica_gaps_total": "gaps_total",
            "pathway_replica_resyncs_total": "resyncs_total",
        }
        for name, mtype, help_text in series:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")
            for r, snap in snaps:
                label = f'route="{escape_label_value(r.route)}"'
                if name == "pathway_replica_lag_seconds":
                    lag = snap.get("lag_s")
                    if lag is not None:
                        lines.append(f"{name}{{{label}}} {round(lag, 6)}")
                else:
                    lines.append(f"{name}{{{label}}} {snap.get(keys[name], 0)}")
    if _plane is not None and _plane.runtime is runtime:
        lines.append(
            "# HELP pathway_fabric_forward_errors_total Forwards that failed at the fabric transport"
        )
        lines.append("# TYPE pathway_fabric_forward_errors_total counter")
        lines.append(
            f"pathway_fabric_forward_errors_total {_plane.forward_errors_total}"
        )
        lines.append(
            "# HELP pathway_fabric_replica_casts_total Changelog broadcasts sent by the owner"
        )
        lines.append("# TYPE pathway_fabric_replica_casts_total counter")
        lines.append(f"pathway_fabric_replica_casts_total {_plane.casts_total}")
    return lines


__all__ = [
    "ApiKeyGuard",
    "FabricUnavailable",
    "ReplicaIndex",
    "index_replica",
    "ReplicaStore",
    "TokenBucket",
    "current",
    "install_from_env",
    "limits",
    "prometheus_lines",
    "replica",
    "serve_table",
    "shutdown",
    "status",
    "transport",
]
