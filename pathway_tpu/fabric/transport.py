"""Fabric control/data transport: one RPC + broadcast plane per process.

The cluster already owns three port bands off ``PATHWAY_FIRST_PORT``: the
barrier coordinator (``first_port``), the peer block links
(``first_port + 1 + pid``) and the heartbeat monitor
(``first_port + processes + 1``). The fabric claims the next band —
``first_port + processes + 2 + pid`` — one listener per process, carrying:

- **requests** (``call``): length-prefixed pickle ``("req", corr, kind,
  payload)`` answered by ``("res", corr, result)`` / ``("err", corr, msg)``
  on the same socket. Handlers are registered per ``kind`` and receive a
  ``reply`` callable — they may answer immediately (table lookups) or hand
  the reply off to another thread/event loop and return (forwarded REST
  requests resolve when the engine answers);
- **casts** (``cast``): fire-and-forget ``("cast", kind, payload)`` — the
  replica changelog feed and frontier stamps.

Connections are lazy and directional: the initiator's receive loop handles
only responses; the acceptor's loop handles requests and casts. Framing is
the cluster plane's length-prefixed pickle, kept local (no import coupling
with the runtime the fabric rides on). A dead peer surfaces as
:class:`FabricUnavailable` on ``call`` — the front door maps it to a 503,
never a hang: every wait is bounded by the caller's timeout.
"""

from __future__ import annotations

import itertools
import pickle
import socket
import struct
import threading
import time as _time
from typing import Any, Callable


class FabricUnavailable(RuntimeError):
    """The target process's fabric endpoint is gone or did not answer in
    time — the ingress door answers 503 with this as the reason."""


def fabric_port(first_port: int, processes: int, pid: int) -> int:
    """The fabric listener port of process ``pid`` (the band directly above
    the heartbeat port)."""
    return first_port + processes + 2 + pid


def _send(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv(sock: socket.socket) -> Any:
    buf = b""
    while len(buf) < 8:
        chunk = sock.recv(8 - len(buf))
        if not chunk:
            return None
        buf += chunk
    (n,) = struct.unpack("<Q", buf)
    payload = b""
    while len(payload) < n:
        chunk = sock.recv(n - len(payload))
        if not chunk:
            return None
        payload += chunk
    return pickle.loads(payload)


class _OutLink:
    """One outgoing connection: sends requests/casts, receives responses."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.send_lock = threading.Lock()
        #: corr -> (event, result-slot list) of in-flight calls
        self.pending: dict[int, tuple[threading.Event, list]] = {}
        self.pending_lock = threading.Lock()
        self.dead = False


class FabricNode:
    """This process's fabric endpoint: a listener plus lazy outgoing links."""

    def __init__(
        self, pid: int, n_proc: int, first_port: int, host: str = "127.0.0.1"
    ):
        self.pid = pid
        self.n_proc = n_proc
        self.first_port = first_port
        self.host = host
        #: kind -> fn(payload, reply); ``reply(result)`` may be called from
        #: any thread, exactly once. A handler raise answers an error frame.
        self.req_handlers: dict[str, Callable[[Any, Callable[[Any], None]], None]] = {}
        #: kind -> fn(payload)
        self.cast_handlers: dict[str, Callable[[Any], None]] = {}
        self._corr = itertools.count(1)
        self._out: dict[int, _OutLink] = {}
        self._out_lock = threading.Lock()
        self._closed = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, fabric_port(first_port, n_proc, pid)))
        self._listener.listen(max(4, n_proc * 2))
        self.port = self._listener.getsockname()[1]
        self._accepted: list[socket.socket] = []
        threading.Thread(
            target=self._accept_loop, name=f"fabric-accept-p{pid}", daemon=True
        ).start()

    # ------------------------------------------------------------ server side
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._accepted.append(conn)
            threading.Thread(
                target=self._serve_loop, args=(conn,), daemon=True
            ).start()

    def _serve_loop(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()

        def conn_send(obj: Any) -> None:
            try:
                with send_lock:
                    _send(conn, obj)
            except OSError:
                pass  # requester gone; its timeout owns the failure

        try:
            while not self._closed:
                msg = _recv(conn)
                if msg is None:
                    return
                tag = msg[0]
                if tag == "req":
                    _tag, corr, kind, payload = msg
                    fn = self.req_handlers.get(kind)
                    if fn is None:
                        conn_send(("err", corr, f"no fabric handler for {kind!r}"))
                        continue

                    def reply(result: Any, _corr=corr) -> None:
                        conn_send(("res", _corr, result))

                    try:
                        fn(payload, reply)
                    except Exception as e:  # handler bug -> error frame
                        conn_send(("err", corr, f"{type(e).__name__}: {e}"))
                elif tag == "cast":
                    _tag, kind, payload = msg
                    fn = self.cast_handlers.get(kind)
                    if fn is not None:
                        try:
                            fn(payload)
                        except Exception:
                            pass  # a cast must never kill the transport
                else:
                    return  # protocol violation: drop the connection
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------------ client side
    def _link_to(self, peer: int, connect_timeout: float) -> _OutLink:
        with self._out_lock:
            link = self._out.get(peer)
            if link is not None and not link.dead:
                return link
        deadline = _time.monotonic() + connect_timeout
        while True:
            try:
                sock = socket.create_connection(
                    (self.host, fabric_port(self.first_port, self.n_proc, peer)),
                    timeout=min(5.0, connect_timeout),
                )
                break
            except OSError:
                if _time.monotonic() > deadline:
                    raise FabricUnavailable(
                        f"fabric endpoint of process {peer} unreachable"
                    ) from None
                _time.sleep(0.05)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        link = _OutLink(sock)
        with self._out_lock:
            cur = self._out.get(peer)
            if cur is not None and not cur.dead:  # lost the race
                try:
                    sock.close()
                except OSError:
                    pass
                return cur
            self._out[peer] = link
        threading.Thread(
            target=self._response_loop, args=(peer, link), daemon=True
        ).start()
        return link

    def _response_loop(self, peer: int, link: _OutLink) -> None:
        try:
            while not self._closed:
                msg = _recv(link.sock)
                if msg is None:
                    break
                tag, corr, body = msg
                with link.pending_lock:
                    ent = link.pending.pop(corr, None)
                if ent is not None:
                    event, slot = ent
                    slot.append((tag, body))
                    event.set()
        except Exception:
            pass
        finally:
            link.dead = True
            # wake every in-flight caller with the failure
            with link.pending_lock:
                pending, link.pending = dict(link.pending), {}
            for event, slot in pending.values():
                slot.append(("err", f"fabric link to process {peer} lost"))
                event.set()
            try:
                link.sock.close()
            except OSError:
                pass

    def call(self, peer: int, kind: str, payload: Any, timeout: float = 30.0) -> Any:
        """Blocking RPC to ``peer``; raises :class:`FabricUnavailable` on a
        dead link or timeout."""
        link = self._link_to(peer, timeout)
        corr = next(self._corr)
        event = threading.Event()
        slot: list = []
        with link.pending_lock:
            link.pending[corr] = (event, slot)
        try:
            with link.send_lock:
                _send(link.sock, ("req", corr, kind, payload))
        except OSError:
            link.dead = True
            with link.pending_lock:
                link.pending.pop(corr, None)
            raise FabricUnavailable(
                f"fabric link to process {peer} lost on send"
            ) from None
        if not event.wait(timeout):
            with link.pending_lock:
                link.pending.pop(corr, None)
            raise FabricUnavailable(
                f"fabric call {kind!r} to process {peer} timed out after {timeout}s"
            )
        tag, body = slot[0]
        if tag == "err":
            raise FabricUnavailable(str(body))
        return body

    def cast(self, peer: int, kind: str, payload: Any, connect_timeout: float = 5.0) -> bool:
        """Best-effort fire-and-forget to ``peer``; returns delivery-attempt
        success (the peer applying it is not acknowledged)."""
        try:
            link = self._link_to(peer, connect_timeout)
            with link.send_lock:
                _send(link.sock, ("cast", kind, payload))
            return True
        except (FabricUnavailable, OSError):
            return False

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_lock:
            links = list(self._out.values())
            self._out.clear()
        for link in links:
            link.dead = True
            try:
                link.sock.close()
            except OSError:
                pass
        for conn in self._accepted:
            try:
                conn.close()
            except OSError:
                pass
