"""Per-route front-door protection: token-bucket rate limits + API-key auth.

Every front door — the coordinator's REST server and, with the fabric on,
each peer's — runs the SAME two checks before admission:

- :class:`TokenBucket`: a classic refill bucket (``PATHWAY_SERVE_RATE``
  requests/second, ``PATHWAY_SERVE_BURST`` capacity). An empty bucket sheds
  with ``429`` and an exact ``Retry-After`` computed from the refill rate —
  the client is told precisely when a token will exist, not a constant.
- :class:`ApiKeyGuard`: static API keys (``PATHWAY_SERVE_API_KEYS``,
  or per-route ``api_keys=``) presented as ``X-API-Key`` or
  ``Authorization: Bearer``. A missing key answers ``401``, a wrong key
  ``403`` — the two failure modes are distinguishable in the counters, so
  "clients without credentials" and "clients with revoked credentials" are
  separate signals.

Both shed BEFORE admission (in-flight budget, ingest credit) and before the
request body is read: an unauthorized or rate-limited flood costs one header
inspection per request, never an engine row. Counters live on the route's
serving state and merge pod-wide over the heartbeat telemetry block
(``observability/aggregate.py``), so ``/status`` on the coordinator reports
exact cluster-wide shed/auth-failure totals.
"""

from __future__ import annotations

import math
import threading
import time as _time


class TokenBucket:
    """Thread-safe token bucket. ``rate`` tokens/second refill up to
    ``burst`` capacity; the bucket starts full. ``clock`` is injectable for
    deterministic tests (must be monotone seconds)."""

    def __init__(self, rate: float, burst: int | None = None, clock=None):
        if rate <= 0:
            raise ValueError(f"TokenBucket rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst if burst else max(1, math.ceil(rate)))
        self._clock = clock or _time.monotonic
        self._tokens = self.burst
        self._stamp = self._clock()
        self._lock = threading.Lock()

    def try_take(self, n: int = 1) -> float:
        """Take ``n`` tokens. Returns 0.0 on success, else the seconds until
        ``n`` tokens will exist (the exact ``Retry-After``)."""
        now = self._clock()
        with self._lock:
            self._tokens = min(
                self.burst, self._tokens + (now - self._stamp) * self.rate
            )
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate

    def available(self) -> float:
        now = self._clock()
        with self._lock:
            return min(self.burst, self._tokens + (now - self._stamp) * self.rate)


#: auth outcomes (``None`` = pass)
UNAUTHORIZED = "unauthorized"  # no key presented -> 401
FORBIDDEN = "forbidden"  # a key presented, but not an accepted one -> 403


class ApiKeyGuard:
    """Static API-key check for one route."""

    def __init__(self, keys):
        self.keys = frozenset(keys)

    def check(self, presented: str | None) -> str | None:
        if not self.keys:
            return None
        if presented is None or presented == "":
            return UNAUTHORIZED
        if presented not in self.keys:
            return FORBIDDEN
        return None


def extract_api_key(headers) -> str | None:
    """The presented key from request headers: ``X-API-Key`` wins, else a
    ``Bearer`` authorization. ``headers`` is any case-insensitive mapping
    (aiohttp's ``CIMultiDict``) or a plain dict with canonical names."""
    key = headers.get("X-API-Key")
    if key:
        return key
    auth = headers.get("Authorization")
    if auth and auth.startswith("Bearer "):
        return auth[len("Bearer ") :].strip() or None
    return None


def retry_after_header(seconds: float) -> str:
    """``Retry-After`` is integer seconds per RFC 9110 — round UP so the
    client never retries before a token exists."""
    return str(max(1, math.ceil(seconds)))
