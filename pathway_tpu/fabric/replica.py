"""Read-only serving replicas: serve a table from every process.

``pw.io.http.serve_table(table, route=..., key_column=...)`` turns a live
table into a GET lookup endpoint. The authoritative copy lives where the
table's changelog lands (the subscribe sink on worker 0 — the write pod);
with the fabric on, every OTHER process keeps a :class:`ReplicaStore` fed by
the changelog casts the owner broadcasts at tick end, and its front door
answers lookups LOCALLY — query fan-out scales beyond the write pod, which
is the whole point of a serving replica.

Staleness is bounded and measured, never silent: every cast (delta or
empty frontier stamp) carries the owner's wall clock; a replica's lag is
``now - last_stamp``, exposed per route on ``/status`` and as the
``pathway_fabric_replica_lag_seconds`` gauge. A replica whose lag exceeds
``PATHWAY_FABRIC_MAX_STALENESS_MS`` stops answering locally and forwards
the lookup to the owner (counted as a fallback) until the feed catches up.
A replica that detects a sequence gap (it missed a cast — e.g. it joined
late or a cast send failed) re-syncs by pulling a full snapshot over the
fabric RPC plane; per-key last-write-wins application makes overlapping
snapshot+delta replay convergent.

Single-process runs serve the same route from the authoritative store with
zero staleness — ``serve_table`` needs no fabric to be useful.
"""

from __future__ import annotations

import json as _json
import threading
import time as _time
import weakref
from typing import Any

#: every serve_table route ever defined (weak; the fabric filters by graph
#: generation, exactly like the REST route registry)
_TABLE_ROUTES: "weakref.WeakSet[TableRoute]" = weakref.WeakSet()


class ReplicaStore:
    """One table route's key→row state plus changelog bookkeeping."""

    def __init__(self, route: str, key_column: str):
        self.route = route
        self.key_column = key_column
        self._lock = threading.Lock()
        self.rows: dict[str, dict] = {}
        #: last applied changelog sequence (one per owner tick that changed
        #: the table); replicas detect missed casts by gaps here
        self.seq = 0
        #: owner wall-clock stamp of the last applied cast/frontier — the
        #: measured-staleness anchor (0.0 = never synced)
        self.synced_unix = 0.0
        self.applied_total = 0
        #: True on the process whose subscribe feeds this store directly
        self.is_owner = False
        # ---- shard-map mode (PATHWAY_SHARDMAP=on): ownership of a served
        # table is PER KEY RANGE, so the changelog has one authoritative
        # source per process and freshness must be tracked per source —
        # a replica fresh for p1's slice may be stale for p2's
        #: this process's source id (its pid) once the fabric binds it
        self.self_src: int | None = None
        #: per-source last applied changelog sequence
        self.src_seq: dict[int, int] = {}
        #: per-source owner wall-clock stamp of the last cast/frontier
        self.src_synced: dict[int, float] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self.rows)

    def apply(self, deltas: list, seq: int, ts_unix: float) -> None:
        """Apply one changelog batch: ``(key_str, row_dict, diff)`` in emission
        order (retract-then-insert within a key is an upsert). Last write
        wins per key, so replaying an overlap (snapshot + already-applied
        deltas) converges instead of corrupting."""
        with self._lock:
            for k, row, diff in deltas:
                if diff > 0:
                    self.rows[k] = row
                else:
                    self.rows.pop(k, None)
            if seq > self.seq:
                self.seq = seq
            if ts_unix > self.synced_unix:
                self.synced_unix = ts_unix
            self.applied_total += len(deltas)

    def frontier(self, seq: int, ts_unix: float) -> None:
        """An empty cast: nothing changed, but the owner is alive at
        ``ts_unix`` — freshness advances without data."""
        with self._lock:
            if seq > self.seq:
                self.seq = seq
            if ts_unix > self.synced_unix:
                self.synced_unix = ts_unix

    def install_snapshot(self, rows: dict, seq: int, ts_unix: float) -> None:
        with self._lock:
            if seq < self.seq:
                return  # raced an already-newer delta feed; keep it
            self.rows = dict(rows)
            self.seq = seq
            if ts_unix > self.synced_unix:
                self.synced_unix = ts_unix

    def lookup(self, key: str) -> dict | None:
        with self._lock:
            return self.rows.get(key)

    def lag_s(self, now_unix: float | None = None) -> float | None:
        """Measured staleness in seconds: 0 on the owner, ``None`` on a
        replica that has never synced (maximally stale), else the age of the
        last owner stamp."""
        if self.is_owner:
            return 0.0
        if self.synced_unix == 0.0:
            return None
        return max(0.0, (now_unix or _time.time()) - self.synced_unix)

    # ---------------------------------------------------- shard-map (per-src)
    def apply_from(self, src: int, deltas: list, seq: int, ts_unix: float) -> None:
        """:meth:`apply`, attributed to one authoritative source process —
        the shard-map replica feed where every process casts its own slice."""
        with self._lock:
            for k, row, diff in deltas:
                if diff > 0:
                    self.rows[k] = row
                else:
                    self.rows.pop(k, None)
            if seq > self.src_seq.get(src, 0):
                self.src_seq[src] = seq
            if ts_unix > self.src_synced.get(src, 0.0):
                self.src_synced[src] = ts_unix
            self.applied_total += len(deltas)

    def frontier_from(self, src: int, seq: int, ts_unix: float) -> None:
        with self._lock:
            if seq > self.src_seq.get(src, 0):
                self.src_seq[src] = seq
            if ts_unix > self.src_synced.get(src, 0.0):
                self.src_synced[src] = ts_unix

    def src_gap(self, src: int, prev_seq: int) -> bool:
        """True when ``src``'s pending deltas don't connect to local state."""
        with self._lock:
            return prev_seq > self.src_seq.get(src, 0)

    def lag_from(self, src: int, now_unix: float | None = None) -> float | None:
        """Staleness of ``src``'s slice: 0 when this process IS the source,
        ``None`` when that slice never synced, else the stamp's age."""
        if src == self.self_src:
            return 0.0
        ts = self.src_synced.get(src, 0.0)
        if ts == 0.0:
            return None
        return max(0.0, (now_unix or _time.time()) - ts)

    def install_slice(
        self, src: int, rows: dict, seq: int, ts_unix: float, owned_fn
    ) -> None:
        """Install a snapshot of ONE source's slice: drop every local row the
        source owns (``owned_fn(key) -> True``) that the snapshot no longer
        carries, then last-write-wins the snapshot rows in — convergent under
        concurrent delta casts from the same source."""
        with self._lock:
            if seq < self.src_seq.get(src, 0):
                return  # raced an already-newer delta feed; keep it
            for k in [k for k in self.rows if owned_fn(k) and k not in rows]:
                del self.rows[k]
            self.rows.update(rows)
            self.src_seq[src] = seq
            if ts_unix > self.src_synced.get(src, 0.0):
                self.src_synced[src] = ts_unix


class TableRoute:
    """One served table: route metadata + the local store + replica counters."""

    def __init__(self, route: str, key_column: str, state: Any, store: ReplicaStore):
        self.route = route
        self.key_column = key_column
        self.state = state  # the _RouteServing carrying door counters/limits
        self.store = store
        self.local_answers = 0  # lookups answered from the local store
        self.fallbacks = 0  # stale-replica lookups forwarded to the owner
        self.casts_out = 0  # owner: changelog casts broadcast

    def replica_snapshot(self) -> dict[str, Any]:
        store = self.store
        lag = store.lag_s()
        out = {
            "route": self.route,
            "rows": len(store),
            "seq": store.seq,
            "lag_s": None if lag is None else round(lag, 3),
            "is_owner": store.is_owner,
            "local_answers": self.local_answers,
            "fallbacks": self.fallbacks,
            "applied_total": store.applied_total,
        }
        if store.src_seq:  # shard-map mode only: per-source feed positions
            out["srcs"] = {str(s): store.src_seq[s] for s in sorted(store.src_seq)}
        return out


def live_table_routes(runtime=None) -> list[TableRoute]:
    """Table routes attached to ``runtime`` (its driver hook or the fabric
    bound them), or — with ``runtime=None`` — the current graph generation's."""
    if runtime is not None:
        return sorted(
            (t for t in list(_TABLE_ROUTES) if t.state.runtime is runtime),
            key=lambda t: t.route,
        )
    from pathway_tpu.internals.parse_graph import G

    return sorted(
        (t for t in list(_TABLE_ROUTES) if t.state.graph_gen == G.generation),
        key=lambda t: t.route,
    )


def lookup_response(troute: TableRoute, key: str | None) -> tuple[int, str]:
    """(status, body) of one lookup against a store — shared by the owner's
    aiohttp handler, replica doors and the owner-side fabric RPC, so every
    door's bytes match."""
    if key is None:
        return 400, _json.dumps({"error": f"missing {troute.key_column}="})
    row = troute.store.lookup(str(key))
    if row is None:
        return 404, _json.dumps({"error": "unknown key", troute.key_column: key})
    from pathway_tpu.io.http._server import _jsonable

    return 200, _json.dumps(_jsonable(row))


def serve_table(
    table: Any,
    *,
    route: str,
    key_column: str,
    host: str = "0.0.0.0",
    port: int = 8080,
    webserver: Any = None,
    documentation: Any = None,
    rate_limit: float | None = None,
    api_keys: Any = None,
) -> TableRoute:
    """Serve ``table`` as a read-only GET lookup endpoint at ``route``.

    ``GET {route}?{key_column}=<value>`` answers the current row whose
    ``key_column`` stringifies to ``<value>`` (404 for unknown keys) — the
    classic serving-cache shape. The backing store applies the table's own
    changelog (a subscribe sink), so answers track the live dataflow; with
    the fabric on, every cluster process answers locally from its replica
    within the configured staleness bound. Front-door protection
    (``rate_limit`` / ``api_keys`` / the ``PATHWAY_SERVE_*`` env knobs)
    applies exactly like ``rest_connector`` routes.
    """
    from pathway_tpu.internals import schema as schema_mod
    from pathway_tpu.io.http import _server as S

    ws = webserver or S.PathwayWebserver(host=host, port=port)
    store = ReplicaStore(route, key_column)
    # the lookup key arrives as a query-param string; the schema documents it
    schema = schema_mod.schema_from_types(**{key_column: str})
    state = S._RouteServing(route, ("GET",), schema)
    if rate_limit is not None:
        state.rate_limit_override = float(rate_limit)
    if api_keys is not None:
        state.api_keys_override = tuple(api_keys)
    S._ROUTES.add(state)
    troute = TableRoute(route, key_column, state, store)
    _TABLE_ROUTES.add(troute)
    state.extra_snapshot = troute.replica_snapshot

    import aiohttp.web as web

    async def handler(request: "web.Request") -> "web.Response":
        state.requests_total += 1
        gated = S.gate_check(state, request.headers)
        if gated is not None:
            status, body, hdrs = gated
            return web.json_response(body, status=status, headers=hdrs or None)
        t0 = _time.time_ns()
        key = request.rel_url.query.get(key_column)
        from pathway_tpu import fabric as _fabric

        plane = _fabric.current()
        if plane is not None and getattr(plane, "shardmap", None) is not None:
            # shard-map mode: this door's store is authoritative only for its
            # own key ranges — route the lookup exactly like a peer door does
            status, body, headers = await plane.serve_table_lookup(troute, key)
        else:
            status, body = lookup_response(troute, key)
            troute.local_answers += 1
            lag = store.lag_s()
            headers = {
                "X-Pathway-Fabric": "owner" if store.is_owner else "local",
                **(
                    {"X-Pathway-Replica-Lag-Ms": str(round(lag * 1e3, 1))}
                    if lag is not None
                    else {}
                ),
            }
        if status == 200:
            state.responses_total += 1
            state.latency.observe((_time.time_ns() - t0) / 1e9)
        else:
            state.errors_total += 1
        return web.Response(
            text=body,
            status=status,
            content_type="application/json",
            headers=headers,
        )

    ws._add_route(
        route,
        ["GET"],
        handler,
        meta={
            "schema": schema,
            "documentation": documentation,
            "serving": state,
            "table_route": troute,
        },
    )

    # the changelog feed: a subscribe sink on the served table. Callbacks run
    # on the process owning worker 0 (subscribe is SOLO-exchanged) — that
    # process is the authoritative store; at tick end the batch applies
    # locally and queues for the fabric's replica cast.
    columns = table.column_names()
    pending: list = []

    def on_change(key: int, row: dict, time: int, is_addition: bool) -> None:
        k = str(row.get(key_column))
        pending.append(
            (k, {c: row.get(c) for c in columns}, 1 if is_addition else -1)
        )

    def on_time_end(time: int) -> None:
        if not pending:
            return
        batch, pending[:] = list(pending), []
        store.apply(batch, store.seq + 1, _time.time())
        from pathway_tpu import fabric as _fabric

        plane = _fabric.current()
        if plane is not None:
            plane.replica_publish(troute, batch)

    from pathway_tpu.flow import validate_service_class
    from pathway_tpu.internals.config import get_pathway_config

    # shard-map mode: route each changelog row to the worker owning the
    # LOOKUP key's hash — the same hash a door computes from the query param
    # (``stable_hash_obj(str(value))``) — so every process's subscribe slice
    # is exactly the key ranges it serves authoritatively
    route_by = None
    if get_pathway_config().shardmap == "on":
        import numpy as _np

        from pathway_tpu.internals.keys import hash_column

        def route_by(batch):
            col = batch.data.get(key_column)
            if col is None:
                return batch.keys
            return hash_column(_np.array([str(v) for v in col], dtype=object))

    sub_lnode = table._subscribe_node(
        on_change=on_change,
        on_time_end=on_time_end,
        on_end=None,
        service_class=validate_service_class("interactive"),
        route_by=route_by,
    )
    sub_lnode._register_as_output()

    class _TableRouteDriver:
        """Starts the owner's webserver for the run (the rest_connector
        driver's little sibling — no engine input to flush)."""

        virtual = False

        def start(self) -> None:
            state.configure()
            store.is_owner = True
            ws.start()

        def is_finished(self) -> bool:
            return False  # a server runs until runtime.request_stop()

        def stop(self) -> None:
            with state.lock:
                state.closed = True
            ws.stop()

    def hook(node: Any, runtime: Any) -> None:
        if runtime is not None:
            state.runtime = runtime
            runtime.register_connector(_TableRouteDriver())

    # piggyback the driver registration on the subscribe node's build: the
    # hook fires once, on the primary build (worker 0's process)
    sub_lnode.runtime_hook = hook
    return troute
