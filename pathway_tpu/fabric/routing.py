"""The fabric plane: every cluster process becomes a front door.

Pre-r18, a REST route lived on the coordinator alone — the process hosting
global worker 0 starts the webserver, and "millions of users" funnel through
one aiohttp loop that is also running the engine. The fabric turns the route
table (populated identically on every process at graph-definition time —
every process executes the same program) into a pod-wide serving surface:

- **Peer front doors.** Each non-owner process starts a mirror webserver per
  registered ``PathwayWebserver`` (port offset by
  ``PATHWAY_FABRIC_PORT_STRIDE × pid``; stride 0 on multi-host pods where
  every host binds the same port). Engine-backed routes get a *forwarding*
  handler; ``serve_table`` routes get a *replica* handler; ``/_schema`` and
  404 semantics come from the same ``PathwayWebserver`` machinery, so every
  door presents the same API surface.
- **Forwarding.** An ingress door runs the full front-door gauntlet locally
  — auth, token bucket, in-flight budget, payload parse, request_validator —
  then mints the request key (pid-salted, so the request id and its derived
  trace id are pod-unique), registers the flight with the r16 request-trace
  plane, and calls the owning process over the fabric transport. The owner
  injects the parsed row into the route's serving state through the SAME
  admission/coalesce/response machinery the coordinator's own door uses, so
  the answer is byte-identical to hitting the coordinator; the ingress door
  relays status, body and ``Retry-After`` verbatim and stamps
  ``X-Pathway-Fabric: forwarded:p<owner>``. The engine's own key-range
  exchange does the scatter/gather across worker shards once the row is in.
- **Tracing.** Ingress and owner both register the SAME request id, so both
  sides' kept traces materialize under one derived trace id: the ingress
  contributes ``serve/admission`` + ``fabric/forward`` spans, the owner the
  engine decomposition — one flight, stitched across processes.
- **Ownership.** Route inputs are SOLO sources on global worker 0, so the
  owning process is the one hosting worker 0 (pid 0 — confirmed against the
  r17 membership table when the elastic plane is live; replica casts carry
  the membership version and stale-generation payloads are dropped).
- **Zero-hop mode (r19, ``PATHWAY_SHARDMAP=on``).** With the shard map
  live, ownership is per KEY RANGE, not per process, and the forward hop
  disappears from the serving hot path entirely: every door mints request
  keys it owns (``mint_local_key``), pushes them into its OWN copy of the
  route input (keyed exchange keeps the row local), and the response
  subscribe — also routed by key — resolves the future on the same process.
  Doors stamp ``X-Pathway-Fabric: owner:p<pid>`` because each one IS the
  owner of every request it admits; the only cross-process traffic left is
  the rate-limited tick nudge to the coordinator (pid 0 owns the inter-tick
  sleep) and the replica feed, which becomes all-to-all: each process casts
  the changelog slice it owns, replicas track freshness per source, and a
  stale lookup forwards to the *key's* owner — never a fixed pid 0.
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time as _time
from typing import Any

import numpy as np

from pathway_tpu.fabric import index_replica as _ireplica
from pathway_tpu.fabric import replica as _replica
from pathway_tpu.fabric.transport import FabricNode, FabricUnavailable
from pathway_tpu.internals.telemetry import record_event

#: minimum seconds between owner frontier casts while tables are idle — the
#: replica staleness clock must keep advancing without data
_FRONTIER_INTERVAL_S = 0.25


def _dumps(obj: Any) -> str:
    import json

    return json.dumps(obj)


class FabricPlane:
    """Per-run fabric state on one process (installed by the cluster runtime
    after connectors start, torn down with the run)."""

    def __init__(self, runtime: Any, cfg: Any):
        self.runtime = runtime
        self.pid = cfg.process_id
        self.n_proc = cfg.processes
        self.stride = cfg.fabric_port_stride
        self.timeout = cfg.fabric_timeout
        self.max_staleness_s = cfg.fabric_max_staleness_ms / 1000.0
        self.owner_pid = 0  # the process hosting global worker 0
        #: shard-map mode: the runtime's versioned ownership table, or None —
        #: None keeps the r18 single-owner behaviour bit-for-bit
        self.shardmap = getattr(runtime, "shardmap", None)
        self.threads = max(1, int(getattr(runtime, "threads", 1)))
        self.node = FabricNode(self.pid, self.n_proc, cfg.first_port)
        self.doors: list[Any] = []
        self._route_states: dict[str, Any] = {}
        self._table_routes: dict[str, _replica.TableRoute] = {}
        #: replica-served retrieval (r20): per-route changelog-fed index
        #: replicas; every door answers KNN locally within the staleness bound
        self._index_routes: dict[str, Any] = {}
        self.replica_max_staleness_s = cfg.replica_max_staleness_ms / 1000.0
        self._memo_share = cfg.replica_memo_share == "on"
        self.memo_casts_total = 0
        self.memo_entries_out = 0
        self.memo_entries_in = 0
        self._loop: asyncio.AbstractEventLoop | None = None
        self._outbox: dict[str, list] = {}
        self._outbox_lock = threading.Lock()
        self._last_cast = 0.0
        self._last_nudge = 0.0
        self._resyncing: set = set()  # route (r18) or (route, src) (shard map)
        self.forward_errors_total = 0
        self.casts_total = 0
        self.nudges_total = 0

    # ------------------------------------------------------------------ install
    def install(self) -> None:
        from pathway_tpu.internals.parse_graph import G
        from pathway_tpu.io.http import _server as S

        gen = G.generation
        for rs in list(S._ROUTES):
            if rs.graph_gen == gen:
                self._route_states[rs.route] = rs
        for tr in _replica.live_table_routes():
            self._table_routes[tr.route] = tr
        for ir in _ireplica.live_index_routes():
            self._index_routes[ir.route] = ir
            if ir.replica is not None:
                # every process authors the changelog slice for the doc keys
                # the engine's keyed exchange placed on it
                ir.replica.self_src = self.pid
        self.node.req_handlers["serve"] = self._handle_serve
        self.node.req_handlers["canary"] = self._handle_canary
        self.node.req_handlers["table_lookup"] = self._handle_table_lookup
        self.node.req_handlers["replica_snapshot"] = self._handle_replica_snapshot
        self.node.req_handlers["index_snapshot"] = self._handle_index_snapshot
        self.node.cast_handlers["replica"] = self._handle_replica_cast
        self.node.cast_handlers["wakeup"] = self._handle_wakeup
        if self.shardmap is not None:
            # zero-hop mode: every process is an authoritative changelog
            # source for its key ranges, and peer doors must be able to wake
            # the coordinator's tick loop when they admit a request
            for tr in self._table_routes.values():
                tr.store.self_src = self.pid
            if self.pid != 0:
                self.runtime.coord_nudge = self._nudge_coordinator
        if self.pid == self.owner_pid:
            loop = asyncio.new_event_loop()
            self._loop = loop
            threading.Thread(
                target=loop.run_forever, name="fabric-serve", daemon=True
            ).start()
        else:
            # bind this process's door states to the run so /status, limits
            # and the heartbeat rollup see them (the driver hook only fires
            # on the owner)
            for rs in self._route_states.values():
                rs.runtime = self.runtime
                rs.configure()
            for tr in self._table_routes.values():
                if tr.state.route not in self._route_states:
                    tr.state.runtime = self.runtime
                    tr.state.configure()
            self._build_doors()
            for tr in self._table_routes.values():
                if self.shardmap is not None:
                    # per-source slices: pull each peer's authoritative ranges
                    for peer in range(self.n_proc):
                        if peer != self.pid:
                            self._resync(tr, wait=False, src=peer)
                else:
                    self._resync(tr, wait=False)
        # index replicas are all-to-all regardless of ownership mode (every
        # process authors its doc shard's slice): pull each peer's slice now
        # to catch up after a restart; a fresh pod converges via first casts
        # (every slice starts at seq 0, so there is no gap to detect)
        for ir in self._index_routes.values():
            if ir.replica is None:
                continue
            for peer in range(self.n_proc):
                if peer != self.pid:
                    self._resync_index(ir, peer, wait=False)
        record_event(
            "fabric.installed",
            process_id=self.pid,
            routes=len(self._route_states),
            tables=len(self._table_routes),
            index_routes=len(self._index_routes),
            doors=len(self.doors),
        )

    def _build_doors(self) -> None:
        from pathway_tpu.io.http import _server as S

        live = {id(rs) for rs in self._route_states.values()}
        live |= {id(tr.state) for tr in self._table_routes.values()}
        live_servers = []
        for ws in list(S._WEBSERVERS):
            if getattr(ws, "_fabric_door", False):
                continue
            metas = [m for _r, _m, _h, m in ws._routes if m is not None]
            if any(id(m.get("serving")) in live for m in metas):
                live_servers.append(ws)
        # a webserver's door band is [port, port + (n_proc-1)*stride]: two
        # servers on nearby ports would silently assign the same door port to
        # different servers — fail with the fix instead of a bind crash
        if self.stride > 0 and len(live_servers) > 1:
            span = (self.n_proc - 1) * self.stride
            ports = sorted(ws.port for ws in live_servers)
            for a, b in zip(ports, ports[1:]):
                if b - a <= span:
                    raise RuntimeError(
                        f"fabric door bands overlap: webservers on ports {a} "
                        f"and {b} each need {span + 1} consecutive ports with "
                        f"{self.n_proc} processes at PATHWAY_FABRIC_PORT_STRIDE="
                        f"{self.stride} — space the webserver ports at least "
                        f"{span + 1} apart, or set the stride to 0 on "
                        f"multi-host pods"
                    )
        for ws in live_servers:
            door = S.PathwayWebserver(
                host=ws.host, port=ws.port + self.pid * self.stride
            )
            door._fabric_door = True
            for route, methods, _handler, meta in ws._routes:
                if meta is None:
                    continue
                troute = meta.get("table_route")
                if troute is not None:
                    handler = self._make_table_handler(troute)
                elif self.shardmap is not None:
                    # zero-hop: the route's ORIGINAL handler already does the
                    # whole job on any process (locally-owned mint, local
                    # push, local future resolution) — the door only stamps
                    # the fabric header asserting no forward hop happened
                    handler = self._make_zerohop_handler(_handler)
                else:
                    rs = meta["serving"]
                    ir = self._index_routes.get(route)
                    if ir is not None and ir.state is rs:
                        # replica-served retrieval: answer KNN from the local
                        # changelog-fed index, forward when stale/resyncing
                        handler = self._make_retrieve_handler(ir, rs)
                    else:
                        handler = self._make_forward_handler(rs)
                door._add_route(route, list(methods), handler, meta)
            door.start()
            self.doors.append(door)

    # ---------------------------------------------------------- ingress (peers)
    def _shed_web(self, rs: Any, reason: str):
        import aiohttp.web as web

        from pathway_tpu.io.http import _server as S

        rs.shed_total += 1
        S._door_event(rs, reason)
        status = 503 if reason == "shutting_down" else 429
        return web.json_response(
            {"error": "overloaded", "reason": reason},
            status=status,
            headers={"Retry-After": "1"},
        )

    def _make_forward_handler(self, rs: Any):
        import aiohttp.web as web

        from pathway_tpu.io.http import _server as S
        from pathway_tpu.observability import requests as _req_trace

        async def handler(request: "web.Request") -> "web.Response":
            from pathway_tpu.observability import health as _health

            hp = _health.current()
            if hp is not None and request.headers.get("X-Pathway-Canary"):
                # synthetic self-probe: answered from the door state machine
                # BEFORE counters, gauntlet or the forward hop — canaries must
                # never show up as traffic or reach the owner's engine
                status, doc = hp.canary_response(rs.route)
                return web.json_response(doc, status=status)
            rs.requests_total += 1
            gated = S.gate_check(rs, request.headers)
            if gated is not None:
                status, body, hdrs = gated
                return web.json_response(body, status=status, headers=hdrs or None)
            shed = rs.try_admit()
            if shed is not None:
                return self._shed_web(rs, shed)
            payload = await S.extract_payload(rs, request)
            if rs.request_validator is not None:
                try:
                    rs.request_validator(payload)
                except Exception as e:
                    rs.errors_total += 1
                    return web.json_response({"error": str(e)}, status=400)
            values = S.build_row_values(rs, payload)
            arrival_ns = _time.time_ns()
            return await self._forward_values(rs, values, arrival_ns)

        return handler

    async def _forward_values(self, rs: Any, values: tuple, arrival_ns: int):
        """Forward one validated request row to the owning process and relay
        its answer — the post-gauntlet core of :meth:`_make_forward_handler`,
        shared with the replica-served retrieval path's stale fallback."""
        import aiohttp.web as web

        from pathway_tpu.io.http import _server as S
        from pathway_tpu.observability import requests as _req_trace

        # re-check the budget under the lock AT the point it grows: any
        # number of handlers can suspend in extract_payload between the
        # arrival-time try_admit and here (the coordinator handler's
        # registration-lock discipline, applied to fwd_inflight)
        with rs.lock:
            if rs.closed:
                shed_reason = "shutting_down"
            elif len(rs.futures) + rs.fwd_inflight >= rs.max_inflight:
                shed_reason = "max_inflight"
            else:
                shed_reason = None
                rs.fwd_inflight += 1
        if shed_reason is not None:
            return self._shed_web(rs, shed_reason)
        key = S.mint_request_key()
        rp = _req_trace.current()
        request_id = rp.begin(key, rs.route, arrival_ns) if rp is not None else None
        rs.forwarded_out_total += 1
        t0 = _time.time_ns()
        loop = asyncio.get_running_loop()
        try:
            status, body, hdrs = await loop.run_in_executor(
                None,
                lambda: self.node.call(
                    self.owner_pid,
                    "serve",
                    {
                        "route": rs.route,
                        "key": key,
                        "values": values,
                        "arrival_ns": arrival_ns,
                    },
                    self.timeout,
                ),
            )
        except FabricUnavailable as e:
            self.forward_errors_total += 1
            if rp is not None:
                rp.complete(key, "error")
            return web.json_response(
                {"error": "fabric forward failed", "reason": str(e)},
                status=503,
            )
        except asyncio.CancelledError:
            # client disconnected mid-forward (doors run with
            # handler_cancellation=True): the registered flight record
            # must not leak in the live table (it would pin plane.hot
            # forever) — the owner still answers and cleans up its side
            if rp is not None:
                rp.complete(key, "cancelled")
            raise
        finally:
            with rs.lock:
                rs.fwd_inflight -= 1
        t1 = _time.time_ns()
        headers = dict(hdrs or {})
        if request_id is not None:
            headers["X-Pathway-Request-Id"] = request_id
        headers["X-Pathway-Fabric"] = f"forwarded:p{self.owner_pid}"
        if rp is not None:
            rp.note_boundary(
                key, "fabric/forward", t0, t1, {"owner": self.owner_pid}
            )
            label = (
                "ok"
                if status == 200
                else "timeout"
                if status == 504
                else "shed"
                if status in (429, 503)
                else "error"
            )
            rp.complete(key, label, t1, _time.time_ns())
        if status == 200:
            # the OWNER's resolution pass already counted this response
            # (responses_total is where-the-answer-was-computed, so the
            # pod rollup stays exact); the ingress door keeps the
            # client-observed latency, which includes the forward hop
            rs.latency.observe((t1 - arrival_ns) / 1e9)
        return web.Response(
            text=body,
            status=status,
            content_type="application/json",
            headers=headers,
        )

    # -------------------------------------------------- replica-served retrieval
    def _replica_unready(self, ir: Any) -> str | None:
        """Why this door must forward instead of answering from its replica
        index, or None when the replica is serveable. Never answer past the
        bound: staleness is measured against the WORST peer slice — a replica
        is only as fresh as its most-lagged source."""
        rep = ir.replica
        if rep is None or ir.composite:
            return "unarmed"
        if not rep.self_authoritative:
            # this process restored from an operator snapshot: its own slice
            # can't be re-derived, so its answers (and its snapshot RPC) are
            # off until fresh ops rebuild authority
            return "restored"
        if any(
            isinstance(tok, tuple) and len(tok) == 3 and tok[:2] == ("ix", ir.route)
            for tok in self._resyncing
        ):
            return "resync"
        lag = rep.remote_lag_s(self.n_proc)
        if lag is None:
            return "never_synced"
        if lag > self.replica_max_staleness_s:
            return "stale"
        return None

    def _make_retrieve_handler(self, ir: Any, rs: Any):
        import aiohttp.web as web

        from pathway_tpu.io.http import _server as S
        from pathway_tpu.observability import requests as _req_trace

        async def handler(request: "web.Request") -> "web.Response":
            from pathway_tpu.observability import health as _health

            hp = _health.current()
            if hp is not None and request.headers.get("X-Pathway-Canary"):
                # synthetic self-probe: state-machine answer only, no engine
                # or replica work, no user-facing counters
                status, doc = hp.canary_response(rs.route)
                return web.json_response(doc, status=status)
            rs.requests_total += 1
            gated = S.gate_check(rs, request.headers)
            if gated is not None:
                status, body, hdrs = gated
                return web.json_response(body, status=status, headers=hdrs or None)
            shed = rs.try_admit()
            if shed is not None:
                return self._shed_web(rs, shed)
            payload = await S.extract_payload(rs, request)
            if rs.request_validator is not None:
                try:
                    rs.request_validator(payload)
                except Exception as e:
                    rs.errors_total += 1
                    return web.json_response({"error": str(e)}, status=400)
            values = S.build_row_values(rs, payload)
            arrival_ns = _time.time_ns()
            reason = self._replica_unready(ir)
            if reason is None:
                vals = dict(zip(rs.schema_columns, values))
                key = S.mint_request_key()
                rp = _req_trace.current()
                request_id = (
                    rp.begin(key, rs.route, arrival_ns) if rp is not None else None
                )
                loop = asyncio.get_running_loop()
                res = await loop.run_in_executor(
                    None, lambda: _ireplica.local_retrieve_response(ir, vals)
                )
                if res is not None:
                    body, spans = res
                    t1 = _time.time_ns()
                    lag = ir.replica.remote_lag_s(self.n_proc) or 0.0
                    headers = {
                        "X-Pathway-Fabric": f"replica:p{self.pid}",
                        "X-Pathway-Replica-Lag-Ms": str(round(lag * 1e3, 1)),
                    }
                    if request_id is not None:
                        headers["X-Pathway-Request-Id"] = request_id
                    if rp is not None:
                        for name, s0, s1, attrs in spans:
                            rp.note_boundary(key, name, s0, s1, attrs)
                        rp.complete(key, "ok", t1, _time.time_ns())
                    ir.local_answers += 1
                    rs.responses_total += 1
                    rs.latency.observe((t1 - arrival_ns) / 1e9)
                    return web.Response(
                        text=body,
                        status=200,
                        content_type="application/json",
                        headers=headers,
                    )
                # unanswerable locally (async embedder, payload-less rows, …):
                # release the flight record and take the forward hop
                reason = "unanswerable"
                if rp is not None:
                    rp.drop(key)
            ir.fallbacks += 1
            ir.fallback_reasons[reason] = ir.fallback_reasons.get(reason, 0) + 1
            return await self._forward_values(rs, values, arrival_ns)

        return handler

    # ------------------------------------------------------ shard-map helpers
    def owner_pid_of_key(self, key: int) -> int:
        """Process owning engine key ``key`` per the shard map (owner worker
        // threads-per-process); the fixed owner pid without a map."""
        sm = self.shardmap
        if sm is None:
            return self.owner_pid
        owner = int(sm.owner_of_keys(np.asarray([key], dtype=np.uint64))[0])
        return owner // self.threads

    def table_owner_pid(self, value: Any) -> int:
        """Process owning a served table's lookup key: the query-param string
        hashes exactly like the changelog's ``route_by`` (both reduce to
        ``stable_hash_obj`` of the stringified value), so door-side routing
        and engine-side placement agree byte-for-byte."""
        if self.shardmap is None:
            return self.owner_pid
        from pathway_tpu.internals.keys import stable_hash_obj

        return self.owner_pid_of_key(stable_hash_obj(str(value)))

    def _make_zerohop_handler(self, inner):
        import aiohttp.web as web  # noqa: F401 — door handlers are aiohttp

        async def handler(request):
            resp = await inner(request)
            # the assertion the r19 tests (and curious operators) read: this
            # door answered as the owner — no forward hop
            resp.headers["X-Pathway-Fabric"] = f"owner:p{self.pid}"
            return resp

        return handler

    def _handle_wakeup(self, payload: dict) -> None:
        wakeup = getattr(self.runtime, "wakeup", None)
        if wakeup is not None:
            wakeup.request(float(payload.get("delay") or 0.0))

    def _nudge_coordinator(self, delay: float) -> None:
        """Peer-door tick scheduling: pid 0 owns the inter-tick sleep, so a
        peer that admitted a request casts it a wakeup. Rate-limited to one
        cast per millisecond — coalescing happens at the wakeup itself, the
        fabric only needs to keep the clock honest."""
        now = _time.monotonic()
        if now - self._last_nudge < 0.001:
            return
        self._last_nudge = now
        if self.node.cast(0, "wakeup", {"delay": delay}, connect_timeout=0.2):
            self.nudges_total += 1

    async def serve_table_lookup(
        self, troute: _replica.TableRoute, key: str | None
    ) -> tuple[int, str, dict]:
        """Shard-map lookup path shared by every door (including the owner's
        original webserver): answer authoritatively for locally-owned keys,
        from the replica within the staleness bound for peer-owned keys, and
        forward to the KEY'S owner — never a fixed pid — when stale."""
        if key is None:
            status, body = _replica.lookup_response(troute, key)
            return status, body, {"X-Pathway-Fabric": f"owner:p{self.pid}"}
        owner = self.table_owner_pid(key)
        if owner == self.pid:
            status, body = _replica.lookup_response(troute, key)
            troute.local_answers += 1
            return status, body, {
                "X-Pathway-Fabric": f"owner:p{self.pid}",
                "X-Pathway-Replica-Lag-Ms": "0.0",
            }
        lag = troute.store.lag_from(owner)
        if lag is not None and lag <= self.max_staleness_s:
            status, body = _replica.lookup_response(troute, key)
            troute.local_answers += 1
            return status, body, {
                "X-Pathway-Fabric": f"replica:p{self.pid}",
                "X-Pathway-Replica-Lag-Ms": str(round(lag * 1e3, 1)),
            }
        # stale (or never-synced) for THIS source's slice: never answer past
        # the bound — one hop to the authoritative process, then catch up
        troute.fallbacks += 1
        loop = asyncio.get_running_loop()
        try:
            status, body, _hdrs = await loop.run_in_executor(
                None,
                lambda: self.node.call(
                    owner,
                    "table_lookup",
                    {"route": troute.route, "key": key},
                    self.timeout,
                ),
            )
        except FabricUnavailable as e:
            self.forward_errors_total += 1
            return (
                503,
                _dumps({"error": "fabric forward failed", "reason": str(e)}),
                {},
            )
        self._resync(troute, wait=False, src=owner)
        return status, body, {"X-Pathway-Fabric": f"forwarded:p{owner}"}

    def _make_table_handler(self, troute: _replica.TableRoute):
        import aiohttp.web as web

        from pathway_tpu.io.http import _server as S

        async def handler(request: "web.Request") -> "web.Response":
            rs = troute.state
            rs.requests_total += 1
            gated = S.gate_check(rs, request.headers)
            if gated is not None:
                status, body, hdrs = gated
                return web.json_response(body, status=status, headers=hdrs or None)
            t0 = _time.time_ns()
            key = request.rel_url.query.get(troute.key_column)
            if self.shardmap is not None:
                status, body, headers = await self.serve_table_lookup(troute, key)
                if status == 200:
                    rs.responses_total += 1
                    rs.latency.observe((_time.time_ns() - t0) / 1e9)
                else:
                    rs.errors_total += 1
                return web.Response(
                    text=body,
                    status=status,
                    content_type="application/json",
                    headers=headers,
                )
            lag = troute.store.lag_s()
            if lag is not None and lag <= self.max_staleness_s:
                status, body = _replica.lookup_response(troute, key)
                troute.local_answers += 1
                headers = {
                    "X-Pathway-Fabric": f"replica:p{self.pid}",
                    "X-Pathway-Replica-Lag-Ms": str(round(lag * 1e3, 1)),
                }
            else:
                # stale (or never-synced) replica: never answer past the
                # bound — forward the lookup to the authoritative store
                troute.fallbacks += 1
                loop = asyncio.get_running_loop()
                try:
                    status, body, _hdrs = await loop.run_in_executor(
                        None,
                        lambda: self.node.call(
                            self.owner_pid,
                            "table_lookup",
                            {"route": troute.route, "key": key},
                            self.timeout,
                        ),
                    )
                except FabricUnavailable as e:
                    self.forward_errors_total += 1
                    return web.json_response(
                        {"error": "fabric forward failed", "reason": str(e)},
                        status=503,
                    )
                headers = {"X-Pathway-Fabric": f"forwarded:p{self.owner_pid}"}
                self._resync(troute, wait=False)
            if status == 200:
                rs.responses_total += 1
                rs.latency.observe((_time.time_ns() - t0) / 1e9)
            else:
                rs.errors_total += 1
            return web.Response(
                text=body,
                status=status,
                content_type="application/json",
                headers=headers,
            )

        return handler

    # ------------------------------------------------------------ owner serving
    def _handle_canary(self, payload: dict, reply) -> None:
        """Health-plane link canary (r23): a tiny echo over the real request
        transport — no engine work, no user-facing counters — so the prober
        measures exactly the path real forwards take."""
        from pathway_tpu.observability import health as _health

        plane = _health.current()
        reply(
            {
                "ok": True,
                "pid": self.pid,
                "state": plane.door_state() if plane is not None else None,
                "from": payload.get("from"),
            }
        )

    def _handle_serve(self, payload: dict, reply) -> None:
        rs = self._route_states.get(payload.get("route"))
        loop = self._loop
        if rs is None or loop is None or rs.node is None:
            reply((404, _dumps({"error": "unknown route"}), {}))
            return
        rs.forwarded_in_total += 1
        asyncio.run_coroutine_threadsafe(self._serve_one(rs, payload, reply), loop)

    async def _serve_one(self, rs: Any, payload: dict, reply) -> None:
        from pathway_tpu.io.http import _server as S
        from pathway_tpu.observability import requests as _req_trace

        key = int(payload["key"])
        values = tuple(payload["values"])
        arrival_ns = int(payload["arrival_ns"])

        def shed(reason: str):
            rs.shed_total += 1
            S._door_event(rs, reason)
            status = 503 if reason == "shutting_down" else 429
            reply(
                (
                    status,
                    _dumps({"error": "overloaded", "reason": reason}),
                    {"Retry-After": "1"},
                )
            )

        fut = asyncio.get_running_loop().create_future()
        with rs.lock:
            if rs.closed:
                shed("shutting_down")
                return
            if len(rs.futures) + rs.fwd_inflight >= rs.max_inflight:
                shed("max_inflight")
                return
            rs.futures[key] = (fut, asyncio.get_running_loop(), arrival_ns, values)
        # the owner registers the SAME request id the ingress minted, so the
        # two processes' kept traces stitch under one derived trace id
        rp = _req_trace.current()
        if rp is not None:
            rp.begin(key, rs.route, arrival_ns)
        if not rs.push_admitted(key, values):
            with rs.lock:
                rs.futures.pop(key, None)
            if rp is not None:
                rp.drop(key)
            shed("no_ingest_credit")
            return
        rs.schedule_tick()
        try:
            result = await asyncio.wait_for(fut, timeout=S._REQUEST_TIMEOUT_S)
        except asyncio.TimeoutError:
            with rs.lock:
                ent = rs.futures.pop(key, None)
            rs.timeouts_total += 1
            if rp is not None:
                rp.complete(key, "timeout")
            if ent is not None and rs.delete_completed and rs.node is not None:
                rs.node._append_events([(key, values, -1)])
                rs.schedule_tick()
            reply((504, _dumps({"error": "timeout"}), {}))
            return
        if result is S._SHUTDOWN:
            if rp is not None:
                rp.drop(key)
            reply((503, _dumps({"error": "engine shutting down"}), {}))
            return
        # the response writer's resolution pass completed the owner-side
        # flight (engine decomposition) and counted the response; only the
        # bytes remain — identical to web.json_response's json.dumps
        reply((200, _dumps(S._jsonable(result)), {}))

    def _handle_table_lookup(self, payload: dict, reply) -> None:
        troute = self._table_routes.get(payload.get("route"))
        if troute is None:
            reply((404, _dumps({"error": "unknown route"}), {}))
            return
        status, body = _replica.lookup_response(troute, payload.get("key"))
        reply((status, body, {}))

    def _handle_replica_snapshot(self, payload: dict, reply) -> None:
        troute = self._table_routes.get(payload.get("route"))
        if troute is None:
            reply(None)
            return
        store = troute.store
        with store._lock:
            rows = dict(store.rows)
            seq = store.seq
            ts = store.synced_unix or _time.time()
        if self.shardmap is not None:
            # only this process's authoritative slice: the requester installs
            # it per source, and replicated peer rows here may themselves lag
            rows = {
                k: v for k, v in rows.items() if self.table_owner_pid(k) == self.pid
            }
        reply({"rows": rows, "seq": seq, "ts": ts, "src": self.pid})

    # ------------------------------------------------------------- replica feed
    def replica_publish(self, troute: _replica.TableRoute, deltas: list) -> None:
        """Owner tick-end hook (from serve_table's subscribe): queue one
        tick's changelog batch for the next cast. ``prev_seq`` records the
        sequence a replica must already hold for the accumulated deltas to
        suffice — several ticks may coalesce into one cast."""
        with self._outbox_lock:
            ent = self._outbox.get(troute.route)
            if ent is None:
                # the store's seq was bumped by the apply() that preceded
                # this publish, so the required predecessor is seq - 1
                ent = self._outbox[troute.route] = {
                    "deltas": [],
                    "prev_seq": troute.store.seq - 1,
                }
            ent["deltas"].extend(deltas)

    def _membership_version(self) -> int | None:
        from pathway_tpu import elastic as _elastic

        eplane = _elastic.current()
        if eplane is not None and eplane.membership is not None:
            return eplane.membership.version
        return None

    def on_tick_done(self, tick: int) -> None:
        """Tick-end cast: pending table changelog batches (owner only in r18
        mode, every process under the shard map), this process's INDEX
        changelog slice (always all-to-all — doc rows shard by key, so every
        process authors ops), freshly-encoded memo entries — or, at least
        every ``_FRONTIER_INTERVAL_S``, an empty frontier stamp so replica
        lag keeps measuring freshness while the pipeline is idle."""
        has_tables = bool(self._table_routes) and (
            self.shardmap is not None or self.pid == self.owner_pid
        )
        has_index = bool(self._index_routes)
        if not has_tables and not has_index:
            return
        now = _time.time()
        outbox: dict[str, Any] = {}
        if has_tables:
            with self._outbox_lock:
                outbox, self._outbox = self._outbox, {}
        index_pending = has_index and any(
            ir.outbox_pending() for ir in self._index_routes.values()
        )
        memo_out = self._drain_memo_out() if self._memo_share else None
        if (
            not outbox
            and not index_pending
            and not memo_out
            and now - self._last_cast < _FRONTIER_INTERVAL_S
        ):
            return
        self._last_cast = now
        payload: dict[str, Any] = {
            "ts": now,
            "mv": self._membership_version(),
            "src": self.pid,
        }
        if has_tables:
            tables = {}
            for route, troute in self._table_routes.items():
                ent = outbox.get(route)
                tables[route] = {
                    "deltas": ent["deltas"] if ent else [],
                    "prev_seq": ent["prev_seq"] if ent else None,
                    "seq": troute.store.seq,
                }
                troute.casts_out += 1
            payload["tables"] = tables
        if has_index:
            index = {}
            for route, ir in self._index_routes.items():
                ops, prev, seq = ir.drain_ops()
                index[route] = {"ops": ops, "prev_seq": prev, "seq": seq}
                ir.casts_out += 1
            payload["index"] = index
        if memo_out:
            payload["memo"] = memo_out
            self.memo_casts_total += 1
            self.memo_entries_out += sum(len(v) for v in memo_out.values())
        for peer in range(self.n_proc):
            if peer != self.pid:
                self.node.cast(peer, "replica", payload, connect_timeout=1.0)
        self.casts_total += 1

    def _handle_replica_cast(self, payload: dict) -> None:
        from pathway_tpu import elastic as _elastic
        from pathway_tpu.elastic.membership import check_version

        eplane = _elastic.current()
        if eplane is not None and eplane.membership is not None:
            if not check_version(
                eplane.membership.version,
                payload.get("mv"),
                f"fabric:replica:p{self.pid}",
            ):
                return  # a pre-reshard zombie's cast: drop it
        ts = float(payload.get("ts") or 0.0)
        src = payload.get("src")
        for route, entry in (payload.get("tables") or {}).items():
            troute = self._table_routes.get(route)
            if troute is None:
                continue
            deltas = entry.get("deltas") or []
            seq = int(entry.get("seq") or 0)
            store = troute.store
            if self.shardmap is not None and src is not None:
                # shard-map mode: the cast carries ONE source's slice;
                # sequence continuity and freshness are per source
                src = int(src)
                if deltas:
                    prev = int(entry.get("prev_seq") or 0)
                    if store.src_gap(src, prev):
                        self._resync(troute, wait=False, src=src)
                    store.apply_from(src, deltas, seq, ts)
                else:
                    if seq > store.src_seq.get(src, 0):
                        self._resync(troute, wait=False, src=src)
                    store.frontier_from(src, seq, ts)
                continue
            if deltas:
                prev = int(entry.get("prev_seq") or 0)
                if prev > store.seq:
                    # missed at least one cast (joined late / send failure):
                    # these deltas don't connect to local state — pull a
                    # snapshot; still apply them (last write wins converges)
                    self._resync(troute, wait=False)
                store.apply(deltas, seq, ts)
            else:
                if seq > store.seq:
                    self._resync(troute, wait=False)
                store.frontier(seq, ts)
        if src is not None:
            s = int(src)
            for route, entry in (payload.get("index") or {}).items():
                ir = self._index_routes.get(route)
                rep = ir.replica if ir is not None else None
                if rep is None or s == rep.self_src:
                    continue
                ops = entry.get("ops") or []
                seq = int(entry.get("seq") or 0)
                if ops:
                    prev = int(entry.get("prev_seq") or 0)
                    if prev == 0 and seq < rep.src_seq.get(s, 0):
                        # the source RESTARTED: its counter reset below the
                        # position we hold, which would wedge gap detection
                        # (every future seq looks "old") — rewind our cursor
                        # and let the ops + next resync converge the slice
                        rep.reset_src(s)
                    if rep.src_gap(s, prev):
                        rep.gaps_total += 1
                        self._resync_index(ir, s, wait=False)
                    rep.apply_ops(s, ops, seq, ts)
                else:
                    if seq > rep.src_seq.get(s, 0):
                        rep.gaps_total += 1
                        self._resync_index(ir, s, wait=False)
                    rep.frontier_from(s, seq, ts)
        memo = payload.get("memo")
        if memo:
            self._apply_memo_in(memo)

    def _resync(
        self, troute: _replica.TableRoute, wait: bool, src: int | None = None
    ) -> None:
        """Pull a snapshot from the authoritative process (thread — never on
        the transport recv loop); convergent under concurrent delta casts.
        Shard-map mode pulls per SOURCE slice; otherwise the pid-0 owner's
        full store."""
        if src is None:
            src = self.owner_pid
        token = (troute.route, src) if self.shardmap is not None else troute.route
        if token in self._resyncing:
            return
        self._resyncing.add(token)
        # readiness: this door serves the route from a replica that just
        # gapped — demote it to syncing until the snapshot lands
        from pathway_tpu.observability import health as _health

        _health.door_syncing(token)

        def pull() -> None:
            try:
                snap = self.node.call(
                    src,
                    "replica_snapshot",
                    {"route": troute.route},
                    timeout=min(5.0, self.timeout),
                )
                if snap is not None and self.shardmap is not None:
                    troute.store.install_slice(
                        int(snap.get("src", src)),
                        snap["rows"],
                        snap["seq"],
                        snap["ts"],
                        lambda k: self.table_owner_pid(k) == src,
                    )
                elif snap is not None:
                    troute.store.install_snapshot(
                        snap["rows"], snap["seq"], snap["ts"]
                    )
            except FabricUnavailable:
                pass  # stays stale; lookups keep falling back to the owner
            finally:
                self._resyncing.discard(token)
                _health.door_synced(token)

        if wait:
            pull()
        else:
            threading.Thread(target=pull, daemon=True).start()

    # ----------------------------------------------------- index replica feed
    def _handle_index_snapshot(self, payload: dict, reply) -> None:
        ir = self._index_routes.get(payload.get("route"))
        rep = ir.replica if ir is not None else None
        if rep is None or not rep.self_authoritative:
            # restored-from-snapshot processes can't vouch for their slice
            # (ops were never re-derived): answering would hand the peer a
            # silently-empty slice it would then serve from — refuse instead
            reply(None)
            return
        rows, seq, ts = rep.self_slice()
        reply({"rows": rows, "seq": seq, "ts": ts, "src": self.pid})

    def _resync_index(self, ir: Any, src: int, wait: bool = False) -> None:
        """Pull one peer's authoritative index slice (thread — never on the
        transport recv loop); convergent under concurrent op casts."""
        rep = ir.replica
        if rep is None:
            return
        token = ("ix", ir.route, src)
        if token in self._resyncing:
            return
        self._resyncing.add(token)
        from pathway_tpu.observability import health as _health

        _health.door_syncing(token)

        def pull() -> None:
            try:
                snap = self.node.call(
                    src,
                    "index_snapshot",
                    {"route": ir.route},
                    timeout=min(5.0, self.timeout),
                )
                if snap is not None:
                    rep.install_slice(
                        int(snap.get("src", src)),
                        snap["rows"],
                        snap["seq"],
                        snap["ts"],
                    )
                    rep.resyncs_total += 1
                else:
                    # the peer disclaimed its slice (restored, not yet
                    # re-authoritative): poison it so lag reads None and the
                    # route forwards until fresh ops arrive from that peer
                    rep.poison(src)
            except FabricUnavailable:
                pass  # stays unsynced; the route keeps forwarding
            finally:
                self._resyncing.discard(token)
                _health.door_synced(token)

        if wait:
            pull()
        else:
            threading.Thread(target=pull, daemon=True).start()

    # --------------------------------------------------------- shared memo tier
    def _drain_memo_out(self) -> dict | None:
        """Pop locally-encoded query embeddings for the cast. sys.modules
        gate: the fabric must not import xpacks — no embedders module loaded
        means no memoizing embedders exist."""
        mod = sys.modules.get("pathway_tpu.xpacks.llm.embedders")
        if mod is None:
            return None
        out = mod.drain_shared_memo(limit=64)
        return out or None

    def _apply_memo_in(self, memo: dict) -> None:
        mod = sys.modules.get("pathway_tpu.xpacks.llm.embedders")
        if mod is None:
            return
        n = 0
        for fp, entries in memo.items():
            n += mod.apply_shared_memo(fp, entries)
        self.memo_entries_in += n

    # ------------------------------------------------------------------- status
    def status(self) -> dict[str, Any]:
        return {
            "enabled": True,
            "process_id": self.pid,
            "owner_pid": self.owner_pid,
            "shardmap_version": (
                None if self.shardmap is None else self.shardmap.version
            ),
            "transport_port": self.node.port,
            "doors": [
                {
                    "host": d.host,
                    "port": d.port,
                    "routes": sorted(r for r, _m, _h, _meta in d._routes),
                }
                for d in self.doors
            ],
            "forward_errors_total": self.forward_errors_total,
            "replica_casts_total": self.casts_total,
            "replica": {
                route: troute.replica_snapshot()
                for route, troute in sorted(self._table_routes.items())
            },
            "index": {
                route: ir.replica_snapshot(self.n_proc)
                for route, ir in sorted(self._index_routes.items())
            },
            "memo_share": {
                "enabled": self._memo_share,
                "casts": self.memo_casts_total,
                "entries_out": self.memo_entries_out,
                "entries_in": self.memo_entries_in,
            },
        }

    def close(self) -> None:
        for door in self.doors:
            try:
                door.stop()
            except Exception:
                pass
        self.doors = []
        loop = self._loop
        if loop is not None:
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass
            self._loop = None
        self.node.close()
