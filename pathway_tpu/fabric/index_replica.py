"""Replica-served retrieval: KNN answered at every front door.

r18 made every cluster process a front door, but an engine-backed route —
``/v1/retrieve`` above all — still pays a fabric hop to the owner for every
request, so read qps is pinned to one process no matter how many doors the
pod opens. This module closes that gap for the dominant read-heavy RAG mix:

- **Changelog capture.** The :class:`~pathway_tpu.stdlib.indexing._engine.
  ExternalIndexNode` already applies an exact per-tick backend mutation
  sequence (the same ops r13's delta snapshots persist). When a retrieval
  route is armed, every index node instance records those ops — extended
  with the raw document payload text — into its route's :class:`IndexRoute`
  feed. Because docs shard by key across workers, each PROCESS owns a
  disjoint slice of the changelog and casts it to every peer at tick end
  over the r18 replica cast (membership-version-stamped, gap-detected,
  snapshot-RPC resync, idle frontier stamps — the plane in ``routing.py``).
- **Replica index.** Every process replays every slice through the SAME
  backend mutation API (``backend_factory()`` → ``add``/``remove``) into a
  full-corpus :class:`ReplicaIndex`, so a replica search is byte-identical
  to the owner's sharded search + merge in the exact regime (BruteForce /
  the tiered backend's exact tiers; IVF/LSH stay approximate and are
  covered by the recall@10 gate instead).
- **Local answers.** A door answers ``/v1/retrieve`` from its replica while
  every peer slice is fresher than ``PATHWAY_REPLICA_MAX_STALENESS_MS``;
  stale, never-synced, resyncing, or unembeddable-locally requests fall
  back to the r18 owner forward — counted, never silently stale. The
  response bytes reproduce ``DocumentStore.retrieve_query`` exactly: same
  filter merge, same filter-compile error semantics, same
  ``(-score, tie_order)`` ordering, same JSON shape.

Staleness caveats are explicit rather than silent: a process restored from
an operator snapshot cannot re-derive its changelog slice (the backend
rebuilds from chunks without re-running ``process()``), so it answers the
snapshot RPC with ``None`` and peers poison that source — the route falls
back to forwarding until fresh ops repopulate it. Input-log replay (the
default cluster resilience path) re-derives the slice completely and
converges by last-write-wins.
"""

from __future__ import annotations

import asyncio
import json as _json
import threading
import time as _time
import weakref
from typing import Any, Callable

#: every armed retrieval route ever defined (weak; the fabric filters by
#: graph generation, exactly like the REST route / table-route registries)
_INDEX_ROUTES: "weakref.WeakSet[IndexRoute]" = weakref.WeakSet()

#: the route being wired by DataIndex._raw_reply right now (see capturing())
_CAPTURE: "IndexRoute | None" = None

#: sentinel: the query cannot be embedded on this door (async/remote
#: embedder) — the caller must forward to the owner
_UNEMBEDDABLE = object()


def current_capture() -> "IndexRoute | None":
    """The :class:`IndexRoute` being wired right now, or None — read by
    ``DataIndex._raw_reply`` to decide whether to capture the index node."""
    return _CAPTURE


class _Capturing:
    def __init__(self, iroute: "IndexRoute | None"):
        self._iroute = iroute
        self._prev: "IndexRoute | None" = None

    def __enter__(self):
        global _CAPTURE
        self._prev = _CAPTURE
        _CAPTURE = self._iroute
        return self._iroute

    def __exit__(self, *exc):
        global _CAPTURE
        _CAPTURE = self._prev
        return False


def capturing(iroute: "IndexRoute | None") -> _Capturing:
    """Arm ``iroute`` as the capture target while a retrieval handler's
    dataflow is being defined (``capturing(None)`` is a no-op context)."""
    return _Capturing(iroute)


class ReplicaIndex:
    """Full-corpus replica of one route's index, replayed per source slice.

    ``rows`` shadows the backend with ``key -> (item, meta, payload, src)``
    so local answers can join scores back to the raw text and snapshot RPCs
    can serve exactly this process's authoritative slice. Freshness, gap
    detection and snapshot install are per SOURCE process — docs shard by
    key, so slices are disjoint and interleaving across sources is safe.
    """

    def __init__(self, backend_factory: Callable[[], Any]):
        self._backend_factory = backend_factory
        self._lock = threading.RLock()
        self.backend = backend_factory()
        self.rows: dict[int, tuple] = {}
        self.self_src: int = 0
        self.src_seq: dict[int, int] = {}
        self.src_synced: dict[int, float] = {}
        #: sources whose changelog cannot be trusted complete (their snapshot
        #: RPC was refused after a restore) — lag_from() treats them as
        #: never-synced until a snapshot installs
        self.poisoned: set[int] = set()
        #: False once this process restored its index from an operator
        #: snapshot: the slice rows were never re-derived, so the snapshot
        #: RPC must refuse rather than hand peers a silently-empty slice
        self.self_authoritative = True
        self.applied_total = 0
        self.gaps_total = 0
        self.resyncs_total = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self.rows)

    def _maintain(self) -> None:
        maintain = getattr(self.backend, "maintain", None)
        if maintain is not None:
            maintain()

    def apply_ops(
        self, src: int, ops: list, seq: int | None, ts_unix: float
    ) -> None:
        """Replay one changelog batch — ``("a", key, item, meta, payload)`` /
        ``("r", key)`` in emission order — through the backend mutation API.
        Re-adding a live key removes it first (last write wins), so replaying
        a snapshot/delta overlap converges instead of corrupting."""
        with self._lock:
            for op in ops:
                key = int(op[1])
                if op[0] == "a":
                    if key in self.rows:
                        self.backend.remove(key)
                    self.backend.add(key, op[2], op[3])
                    self.rows[key] = (op[2], op[3], op[4], src)
                else:
                    self.backend.remove(key)
                    self.rows.pop(key, None)
            self.applied_total += len(ops)
            if seq is not None and seq > self.src_seq.get(src, 0):
                self.src_seq[src] = seq
            if ts_unix > self.src_synced.get(src, 0.0):
                self.src_synced[src] = ts_unix
            if ops:
                self._maintain()

    def frontier_from(self, src: int, seq: int, ts_unix: float) -> None:
        """Empty cast: the source is alive at ``ts_unix`` — freshness
        advances without data."""
        with self._lock:
            if seq > self.src_seq.get(src, 0):
                self.src_seq[src] = seq
            if ts_unix > self.src_synced.get(src, 0.0):
                self.src_synced[src] = ts_unix

    def src_gap(self, src: int, prev_seq: int) -> bool:
        """True when a source's pending ops don't connect to local state."""
        with self._lock:
            return prev_seq > self.src_seq.get(src, 0)

    def reset_src(self, src: int) -> None:
        """A source restarted its changelog counter (first cast has
        ``prev_seq == 0`` below our held position): accept the new epoch."""
        with self._lock:
            self.src_seq[src] = 0

    def poison(self, src: int) -> None:
        with self._lock:
            self.poisoned.add(src)

    def lag_from(self, src: int, now_unix: float | None = None) -> float | None:
        """Staleness of ``src``'s slice: 0 when this process IS the source,
        None when never synced (or poisoned), else the stamp's age."""
        with self._lock:
            if src == self.self_src:
                return 0.0
            if src in self.poisoned:
                return None
            ts = self.src_synced.get(src, 0.0)
        if ts == 0.0:
            return None
        return max(0.0, (now_unix or _time.time()) - ts)

    def remote_lag_s(self, n_proc: int) -> float | None:
        """Worst-case staleness over every REMOTE slice — the number a door
        compares against the staleness bound (None = some slice never
        synced, i.e. maximally stale)."""
        worst = 0.0
        now = _time.time()
        for src in range(n_proc):
            lag = self.lag_from(src, now)
            if lag is None:
                return None
            worst = max(worst, lag)
        return worst

    def self_slice(self) -> tuple[dict, int, float]:
        """This process's authoritative slice for the snapshot RPC:
        ``key -> (item, meta, payload)`` plus its changelog position."""
        with self._lock:
            rows = {
                k: (v[0], v[1], v[2])
                for k, v in self.rows.items()
                if v[3] == self.self_src
            }
            return rows, self.src_seq.get(self.self_src, 0), _time.time()

    def install_slice(
        self, src: int, rows: dict, seq: int, ts_unix: float
    ) -> None:
        """Install a snapshot of ONE source's slice: drop local rows
        attributed to that source the snapshot no longer carries, then
        last-write-wins the snapshot rows in. Accepts sequence regressions —
        a restarted source restarts its counter and its snapshot is still
        the freshest truth for its slice."""
        with self._lock:
            self.poisoned.discard(src)
            for k in [
                k for k, v in self.rows.items() if v[3] == src and k not in rows
            ]:
                self.backend.remove(k)
                del self.rows[k]
            for k, ent in rows.items():
                k = int(k)
                if k in self.rows:
                    self.backend.remove(k)
                self.backend.add(k, ent[0], ent[1])
                self.rows[k] = (ent[0], ent[1], ent[2], src)
            self.src_seq[src] = max(seq, 0)
            if ts_unix > self.src_synced.get(src, 0.0):
                self.src_synced[src] = ts_unix
            self._maintain()

    def search_one(self, item: Any, k: int, flt: Callable) -> list[tuple]:
        """One query against the full-corpus replica: ``(key, score, row)``
        triples, backend order (the caller re-sorts by the owner's merge
        discipline)."""
        with self._lock:
            hits = self.backend.search([item], [k], [flt])[0]
            return [
                (int(key), float(score), self.rows.get(int(key)))
                for key, score in hits
            ]


class IndexRoute:
    """One armed retrieval route: capture wiring + the replica + counters."""

    def __init__(self, route: str, embedder: Any, graph_gen: int):
        self.route = route
        self.embedder = embedder
        self.graph_gen = graph_gen
        self.state: Any = None  # the route's _RouteServing, set by the server
        self.inner: Any = None  # the captured InnerIndex
        self.replica: ReplicaIndex | None = None
        #: True when more than one InnerIndex bound (hybrid/composite index):
        #: a single replica cannot reproduce the composition — always forward
        self.composite = False
        self._lock = threading.Lock()
        self._pending: list = []
        self._self_seq = 0
        self._build_token: int | None = None
        self._filter_cache: dict = {}
        self.local_answers = 0
        self.fallbacks = 0
        self.fallback_reasons: dict[str, int] = {}
        self.casts_out = 0

    # -------------------------------------------------------------- wiring
    def bind(self, inner: Any) -> None:
        """Called by ``DataIndex._raw_reply`` under ``capturing(self)``."""
        if self.inner is not None and inner is not self.inner:
            self.composite = True
            return
        self.inner = inner
        if self.replica is None and inner.backend_factory is not None:
            self.replica = ReplicaIndex(inner.backend_factory)

    def attach_node(self, node: Any) -> None:
        """Called from the captured node factory at BUILD time, on every
        worker of every process. The first attach of a new run resets the
        replica (graphs rebuild per run; stale state must not leak), then
        every instance feeds the same route."""
        from pathway_tpu.internals.logical import current_build

        b = current_build()
        token = (
            id(b.shared_runtime)
            if b is not None and b.shared_runtime is not None
            else id(b)
        )
        if token != self._build_token:
            self._build_token = token
            self.reset()
        node.replica_feed = self

    def reset(self) -> None:
        with self._lock:
            self._pending = []
            self._self_seq = 0
        if self.inner is not None and self.inner.backend_factory is not None:
            self.replica = ReplicaIndex(self.inner.backend_factory)
            from pathway_tpu.internals.config import get_pathway_config

            self.replica.self_src = get_pathway_config().process_id

    # ---------------------------------------------------------------- feed
    def note_ops(self, ops: list) -> None:
        """Engine thread: one tick's backend mutations for this worker's doc
        shard. Applied to the local replica immediately (the self slice has
        zero lag) and queued for the next peer cast."""
        rep = self.replica
        if rep is None:
            return
        rep.apply_ops(rep.self_src, ops, None, _time.time())
        with self._lock:
            self._pending.extend(ops)

    def note_restored(self) -> None:
        """The engine restored this route's index from an operator snapshot:
        the changelog slice was never re-derived, so this process must not
        serve snapshot RPCs claiming completeness."""
        rep = self.replica
        if rep is not None:
            rep.self_authoritative = False

    def outbox_pending(self) -> bool:
        with self._lock:
            return bool(self._pending)

    def drain_ops(self) -> tuple[list, int, int]:
        """Fabric tick-end drain: ``(ops, prev_seq, seq)``. The sequence
        advances only on non-empty drains, so empty frontier stamps never
        masquerade as missed data casts."""
        with self._lock:
            ops, self._pending = self._pending, []
            prev = self._self_seq
            if ops:
                self._self_seq += 1
            seq = self._self_seq
        rep = self.replica
        if rep is not None and ops:
            with rep._lock:
                if seq > rep.src_seq.get(rep.self_src, 0):
                    rep.src_seq[rep.self_src] = seq
        return ops, prev, seq

    # --------------------------------------------------------- local answer
    def _filter(self, expr: str | None):
        """Compile a merged filter with EXACTLY the engine node's error
        semantics: evaluation errors exclude the doc, a malformed filter
        yields None → the empty reply (never an exception)."""
        if expr not in self._filter_cache:
            from pathway_tpu.stdlib.indexing._filters import compile_filter

            try:
                compiled = compile_filter(expr)

                def safe(md, _f=compiled):
                    try:
                        return bool(_f(md))
                    except Exception:
                        return False

                self._filter_cache[expr] = safe
            except Exception:
                self._filter_cache[expr] = None
        return self._filter_cache[expr]

    def embed_query(self, text: str) -> Any:
        """The query item, embedded exactly like the owner's microbatch path
        embeds it, or :data:`_UNEMBEDDABLE` when this door can't reproduce
        it (async/remote embedders always forward)."""
        emb = self.embedder
        if emb is None:
            return text  # lexical backends (BM25) search the raw text
        try:
            fn = emb.func
        except Exception:
            return _UNEMBEDDABLE
        if fn is None or asyncio.iscoroutinefunction(fn):
            return _UNEMBEDDABLE
        cap = getattr(emb, "_memo_cap", None)
        try:
            if cap is not None and cap == 0:
                # unmemoized JAX embedder: the owner's microbatch dispatcher
                # pads the launch to a power-of-two bucket with replicas of
                # real rows, and length-bucketing makes final float bits
                # depend on batch composition — reproduce the solo-query pad
                from pathway_tpu.ops.microbatch import bucket_size

                n = bucket_size(
                    1,
                    min_bucket=int(getattr(emb, "microbatch_min_bucket", 8)),
                    max_bucket=int(getattr(emb, "microbatch_max_batch", 512)),
                )
                return fn([text] * n)[0]
            # memoized (the memo path re-pads deduped misses identically) or
            # batch-independent embedders: a bare single-text call matches
            return fn([text])[0]
        except Exception:
            return _UNEMBEDDABLE

    def replica_snapshot(self, n_proc: int | None = None) -> dict[str, Any]:
        rep = self.replica
        out: dict[str, Any] = {
            "route": self.route,
            "armed": rep is not None and not self.composite,
            "rows": 0 if rep is None else len(rep),
            "local_answers": self.local_answers,
            "fallbacks": self.fallbacks,
            "fallback_reasons": dict(self.fallback_reasons),
            "casts_out": self.casts_out,
        }
        if rep is not None:
            with rep._lock:
                out["applied_total"] = rep.applied_total
                out["gaps_total"] = rep.gaps_total
                out["resyncs_total"] = rep.resyncs_total
                out["self_authoritative"] = rep.self_authoritative
                out["srcs"] = {
                    str(s): rep.src_seq[s] for s in sorted(rep.src_seq)
                }
            if n_proc is not None:
                lag = rep.remote_lag_s(n_proc)
                out["lag_s"] = None if lag is None else round(lag, 3)
        return out


def live_index_routes(runtime=None) -> list[IndexRoute]:
    """Armed index routes attached to ``runtime`` (their serving state was
    bound), or — with ``runtime=None`` — the current graph generation's."""
    if runtime is not None:
        return sorted(
            (
                r
                for r in list(_INDEX_ROUTES)
                if r.state is not None and r.state.runtime is runtime
            ),
            key=lambda r: r.route,
        )
    from pathway_tpu.internals.parse_graph import G

    return sorted(
        (r for r in list(_INDEX_ROUTES) if r.graph_gen == G.generation),
        key=lambda r: r.route,
    )


def maybe_arm(route: str, document_store: Any) -> IndexRoute | None:
    """Create an :class:`IndexRoute` for a DocumentStore retrieval endpoint
    when replica serving can apply (cluster run, fabric on, replica on) —
    else None and the r18 forward path stays byte-for-byte. The caller must
    hold the returned route (the registry is weak) and define the retrieval
    dataflow under ``capturing(route)``."""
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    if cfg.replica == "off" or cfg.fabric == "off" or cfg.processes <= 1:
        return None
    from pathway_tpu.internals.parse_graph import G

    embedder = getattr(document_store.retriever_factory, "embedder", None)
    iroute = IndexRoute(route, embedder, G.generation)
    _INDEX_ROUTES.add(iroute)
    return iroute


def local_retrieve_response(
    iroute: IndexRoute, vals: dict[str, Any]
) -> tuple[str, list] | None:
    """Answer one ``/v1/retrieve`` request from the replica: ``(body, spans)``
    with bytes identical to ``DocumentStore.retrieve_query`` through the
    owner, or None → the door falls back to forwarding. ``vals`` is the
    schema-ordered row mapping (query, k, metadata_filter,
    filepath_globpattern) the door already built."""
    rep = iroute.replica
    if rep is None or iroute.composite:
        return None
    query = vals.get("query")
    k = vals.get("k")
    if query is None or k is None:
        return None  # the owner path defines the (error) behavior
    try:
        k = int(k)
    except (TypeError, ValueError):
        return None
    from pathway_tpu.xpacks.llm.document_store import _as_dict, combine_filters

    flt_expr = combine_filters(
        vals.get("metadata_filter"), vals.get("filepath_globpattern")
    )
    flt = iroute._filter(flt_expr)
    spans: list = []
    if flt is None:
        pairs: list[tuple] = []  # malformed filter → the empty reply
    else:
        e0 = _time.time_ns()
        item = iroute.embed_query(str(query))
        e1 = _time.time_ns()
        if item is _UNEMBEDDABLE:
            return None
        spans.append(("replica/embed", e0, e1, None))
        s0 = _time.time_ns()
        pairs = rep.search_one(item, k, flt)
        spans.append(("replica/search", s0, _time.time_ns(), {"rows": len(pairs)}))
    # the owner's MergeIndexRepliesNode orders the merged union by
    # (score desc, tie-order asc) and cuts to k; the groupby sort and the
    # final dist sort are stable, so reproducing that order here reproduces
    # the response bytes
    from pathway_tpu.internals.keys import tie_order

    pairs.sort(key=lambda ent: (-ent[1], tie_order(ent[0])))
    out = []
    for _key, score, row in pairs[:k]:
        if row is None or row[2] is None:
            # the row raced a removal, or its payload text was never cast
            # (restored source): the replica cannot build the owner's bytes
            return None
        out.append(
            {"text": row[2], "metadata": _as_dict(row[1]), "dist": -score}
        )
    out.sort(key=lambda d: d["dist"])
    from pathway_tpu.io.http._server import _jsonable

    return _json.dumps(_jsonable(out)), spans


def heartbeat_summary(runtime, n_proc: int | None = None) -> dict | None:
    """route → compact replica counters for this process — rides the
    heartbeat telemetry block so the coordinator can roll replica health up
    cluster-wide (satellite of the r18 ``peer_serving()`` pattern)."""
    routes = live_index_routes(runtime)
    if not routes:
        return None
    out = {}
    for r in routes:
        snap = r.replica_snapshot(n_proc)
        out[r.route] = {
            "rows": snap["rows"],
            "lag_s": snap.get("lag_s"),
            "local": snap["local_answers"],
            "fallbacks": snap["fallbacks"],
            "gaps": snap.get("gaps_total", 0),
            "resyncs": snap.get("resyncs_total", 0),
        }
    return out
