"""Idempotent sink transports for the delivery ledger.

Each transport's ``publish(sink_id, epoch, parts)`` must tolerate being called
again with the SAME frozen payload after a crash mid-publish — that is the
whole idempotence contract the ledger relies on:

- **Kafka** — a transactional producer when the client supports it (epoch rows
  + a commit marker in one transaction), else ``(sink_id, epoch, partition,
  seq)`` dedupe headers on every message plus the marker message; consumers
  read through :func:`read_committed`, which hides uncommitted tails and drops
  header-duplicate rows exactly like a ``read_committed`` Kafka consumer
  filtering aborted transactions.
- **Postgres** — one DBAPI transaction per epoch: the epoch's UPSERT/DELETE
  statements plus an ``INSERT INTO pathway_delivery (sink_id, epoch)`` marker
  row; a marker already present means the epoch landed and the transaction is
  skipped whole.
- **fs** — an offset sidecar (``<path>.delivery``, written tmp+rename):
  re-publish truncates back to the last durable offset before appending, so
  partially-written epochs never survive.
"""

from __future__ import annotations

import json as _json
import os
import zlib as _zlib
from typing import Any

#: single control topic carrying per-sink epoch commit markers (partition 0)
KAFKA_CONTROL_TOPIC = "__pathway_delivery"

#: commit-marker table every exactly-once postgres sink shares
PG_COMMIT_TABLE = "pathway_delivery"

_PG_COMMIT_DDL = (
    f"CREATE TABLE IF NOT EXISTS {PG_COMMIT_TABLE} "
    "(sink_id TEXT NOT NULL, epoch BIGINT NOT NULL, "
    "PRIMARY KEY (sink_id, epoch))"
)


def stable_partition(key: str | None, n: int) -> int:
    """Deterministic partition for a message key — ``hash()`` is salted per
    process, which would re-shuffle partitions across a restart and break the
    frozen-bytes contract."""
    if n <= 1 or key is None:
        return 0
    return _zlib.crc32(key.encode()) % n


class KafkaDeliveryTransport:
    """Publishes ledger epochs to Kafka. ``broker`` is a MockKafkaBroker (the
    in-process/file-backed fixture) or an rdkafka settings dict (real wire
    client, possibly injected via ``client_factory``). Records are
    ``(key, value)`` pairs as staged by the writer."""

    def __init__(self, broker, topic: str):
        self.broker = broker
        self.topic = topic
        self._producer = None
        self._txn_ready = False

    # -- real-client producer -------------------------------------------------
    def _real_producer(self):
        if self._producer is None:
            from pathway_tpu.io.kafka import _client_module, _conf_of

            ck = _client_module(self.broker)
            self._producer = ck.Producer(_conf_of(self.broker))
            if "transactional.id" in self.broker and hasattr(
                self._producer, "init_transactions"
            ):
                self._producer.init_transactions()
                self._txn_ready = True
        return self._producer

    @staticmethod
    def _headers(sink_id: str, epoch: int, partition: int, seq: int) -> list:
        return [
            ("pw_sink", sink_id.encode()),
            ("pw_epoch", str(epoch).encode()),
            ("pw_part", str(partition).encode()),
            ("pw_seq", str(seq).encode()),
        ]

    def publish(self, sink_id: str, epoch: int, parts: dict[int, list]) -> None:
        marker_value = _json.dumps({"sink": sink_id, "epoch": epoch})
        if isinstance(self.broker, dict):
            producer = self._real_producer()
            if self._txn_ready:
                # transactional path: epoch rows + the commit marker become
                # visible atomically; an aborted attempt is invisible to
                # read_committed consumers
                producer.begin_transaction()
                try:
                    self._produce_real(producer, sink_id, epoch, parts, marker_value)
                except Exception:
                    producer.abort_transaction()
                    raise
                producer.commit_transaction()
            else:
                # no transactions: dedupe headers carry the idempotence key;
                # consumers drop header-duplicates (read_committed contract)
                self._produce_real(producer, sink_id, epoch, parts, marker_value)
                producer.flush()
            return
        # mock broker: one locked batch append + the marker message — the
        # marker gates read_committed visibility, the headers dedupe a
        # re-publish that raced a crash mid-batch
        msgs = []
        for p, records in sorted(parts.items()):
            for seq, (key, value) in enumerate(records):
                msgs.append(
                    {
                        "topic": self.topic,
                        "partition": p,
                        "key": key,
                        "value": value,
                        "headers": {
                            "pw_sink": sink_id,
                            "pw_epoch": str(epoch),
                            "pw_part": str(p),
                            "pw_seq": str(seq),
                        },
                    }
                )
        self.broker.produce_batch(
            msgs,
            marker={
                "topic": KAFKA_CONTROL_TOPIC,
                "partition": 0,
                "key": sink_id,
                "value": marker_value,
            },
        )

    def _produce_real(self, producer, sink_id, epoch, parts, marker_value) -> None:
        for p, records in sorted(parts.items()):
            for seq, (key, value) in enumerate(records):
                producer.produce(
                    self.topic,
                    value=value,
                    key=key,
                    headers=self._headers(sink_id, epoch, p, seq),
                )
        producer.produce(
            KAFKA_CONTROL_TOPIC, value=marker_value, key=sink_id
        )


def read_committed(broker, topic: str) -> tuple[list[tuple[Any, Any]], dict]:
    """Consumer-side view of an exactly-once topic on the mock broker: only
    messages whose epoch is covered by a control-topic commit marker are
    visible, and duplicate ``(sink, epoch, part, seq)`` idempotence keys from
    a crash-window re-publish are dropped (first occurrence wins, which is
    byte-identical to the uninterrupted run). Returns ``(messages, stats)``
    where stats counts exactly what was hidden: ``duplicates`` (idempotence-key
    repeats) and ``uncommitted`` (tail past the last marker)."""
    committed: dict[str, int] = {}
    for _k, v in broker.fetch(KAFKA_CONTROL_TOPIC, 0, 0):
        rec = _json.loads(v)
        committed[rec["sink"]] = max(committed.get(rec["sink"], -1), rec["epoch"])
    out: list[tuple[Any, Any]] = []
    seen: set[tuple] = set()
    duplicates = 0
    uncommitted = 0
    plain = 0
    for p in range(max(1, broker.partitions(topic))):
        for rec in broker.fetch_records(topic, p, 0):
            h = rec.get("h") or {}
            sink = h.get("pw_sink")
            if sink is None:
                plain += 1  # a non-delivery producer shares the topic
                out.append((rec["k"], rec["v"]))
                continue
            epoch = int(h.get("pw_epoch", -1))
            if epoch > committed.get(sink, -1):
                uncommitted += 1
                continue
            ikey = (sink, epoch, h.get("pw_part"), h.get("pw_seq"))
            if ikey in seen:
                duplicates += 1
                continue
            seen.add(ikey)
            out.append((rec["k"], rec["v"]))
    return out, {
        "duplicates": duplicates,
        "uncommitted": uncommitted,
        "plain": plain,
        "committed_epochs": dict(committed),
    }


class PostgresDeliveryTransport:
    """Publishes ledger epochs as one DBAPI transaction each. Records are
    ``(op, args)`` pairs where ``op`` selects a prepared statement from
    ``statements`` (e.g. the diff-aware UPSERT/DELETE built by
    ``io.postgres``)."""

    def __init__(self, settings: dict, statements: dict[str, str]):
        self.settings = settings
        self.statements = statements
        self._con = None
        self._ddl_done = False

    def _connection(self):
        if self._con is None:
            from pathway_tpu.io.postgres import _connect

            self._con = _connect(self.settings)
        return self._con

    def publish(self, sink_id: str, epoch: int, parts: dict[int, list]) -> None:
        con = self._connection()
        try:
            with con.cursor() as cur:
                if not self._ddl_done:
                    cur.execute(_PG_COMMIT_DDL)
                    self._ddl_done = True
                cur.execute(
                    f"SELECT 1 FROM {PG_COMMIT_TABLE} "  # noqa: S608
                    "WHERE sink_id = %s AND epoch = %s",
                    (sink_id, epoch),
                )
                if cur.fetchone() is not None:
                    con.commit()  # marker present: the epoch already landed
                    return
                for _p, records in sorted(parts.items()):
                    for op, args in records:
                        cur.execute(self.statements[op], tuple(args))
                cur.execute(
                    f"INSERT INTO {PG_COMMIT_TABLE} "  # noqa: S608
                    "(sink_id, epoch) VALUES (%s, %s)",
                    (sink_id, epoch),
                )
            con.commit()
        except Exception:
            try:
                con.rollback()
            except Exception:
                pass
            raise


class FsDeliveryTransport:
    """Publishes ledger epochs as appended lines with an offset sidecar — the
    fs sink re-expressed over the ledger API. Records are ready-formatted text
    lines. The sidecar ``<path>.delivery`` records ``(offset, epoch)`` after
    every durable append (tmp+rename), so a re-publish truncates any partial
    tail first and an epoch already on disk is skipped whole."""

    def __init__(self, path: str, header: str | None = None):
        self.path = path
        self.header = header or ""
        self._sidecar = path + ".delivery"

    def _read_sidecar(self) -> dict:
        try:
            with open(self._sidecar) as fh:
                return _json.load(fh)
        except (FileNotFoundError, ValueError):
            return {"offset": None, "epoch": -1}

    def _write_sidecar(self, state: dict) -> None:
        tmp = self._sidecar + ".tmp"
        with open(tmp, "w") as fh:
            _json.dump(state, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._sidecar)

    def publish(self, sink_id: str, epoch: int, parts: dict[int, list]) -> None:
        state = self._read_sidecar()
        if state["epoch"] >= epoch:
            return  # this epoch's bytes are already durable on disk
        if state["offset"] is None:
            # first ever publish: create the file with the header
            with open(self.path, "w", newline="") as fh:
                fh.write(self.header)
                fh.flush()
                os.fsync(fh.fileno())
                state["offset"] = fh.tell()
        with open(self.path, "r+", newline="") as fh:
            fh.truncate(state["offset"])
            fh.seek(state["offset"])
            for _p, records in sorted(parts.items()):
                for line in records:
                    fh.write(line)
            fh.flush()
            os.fsync(fh.fileno())
            new_offset = fh.tell()
        self._write_sidecar({"offset": new_offset, "epoch": epoch})
