"""Exactly-once delivery plane: epoch-transactional external sinks.

Writers opt in with ``delivery="exactly_once"`` (``pw.io.kafka.write``,
``pw.io.postgres.write_snapshot``, ``pw.io.fs.write``) or the
``PATHWAY_DELIVERY`` knob. Output rows then flow through a durable
:class:`~pathway_tpu.delivery.ledger.DeliveryLedger` keyed
``(epoch, sink_id, partition)``: staged each epoch before the commit barrier,
frozen at operator-snapshot recovery points, and published to the sink with
idempotence keys — restart replays only uncommitted epochs and the sink-side
dedupe (Kafka transactions/headers, the Postgres ``pathway_delivery`` commit
table, the fs offset sidecar) keeps downstream state byte-identical across
SIGKILL, Supervisor restart, and elastic rescale. Requires
``persistence_mode="operator_persisting"`` (publication gates on recovery
points — see ``ledger.py`` for why per-epoch publication cannot be aligned
with replay).
"""

from __future__ import annotations

from pathway_tpu.delivery.ledger import (  # noqa: F401
    DeliveryLedger,
    DeliveryPlane,
    LedgerWriter,
)
from pathway_tpu.delivery.transports import (  # noqa: F401
    KAFKA_CONTROL_TOPIC,
    PG_COMMIT_TABLE,
    FsDeliveryTransport,
    KafkaDeliveryTransport,
    PostgresDeliveryTransport,
    read_committed,
    stable_partition,
)


def resolve_mode(delivery: str | None) -> str:
    """Writer-side knob resolution: an explicit ``delivery=`` argument wins,
    else ``PATHWAY_DELIVERY`` decides (default ``off``)."""
    if delivery is None:
        from pathway_tpu.internals.config import get_pathway_config

        delivery = get_pathway_config().delivery
    if delivery not in ("off", "exactly_once"):
        raise ValueError(
            f"delivery={delivery!r}: expected 'off' or 'exactly_once'"
        )
    return delivery


def plane_of(runtime) -> DeliveryPlane | None:
    """The run's delivery plane (bound on process 0 / the solo runtime when
    any sink opted in), or None."""
    persistence = getattr(runtime, "persistence", None)
    return getattr(persistence, "delivery", None)


def run_summary(runtime) -> dict | None:
    plane = plane_of(runtime)
    return plane.summary() if plane is not None else None


def heartbeat_summary(runtime) -> dict | None:
    plane = plane_of(runtime)
    return plane.heartbeat_summary() if plane is not None else None


def prometheus_lines(runtime) -> list[str]:
    """``pathway_delivery_*`` series for the /metrics endpoint."""
    plane = plane_of(runtime)
    if plane is None:
        return []
    lines = [
        "# TYPE pathway_delivery_staged_rows_total counter",
        "# TYPE pathway_delivery_published_rows_total counter",
        "# TYPE pathway_delivery_discarded_rows_total counter",
        "# TYPE pathway_delivery_publish_failures_total counter",
        "# TYPE pathway_delivery_uncommitted_epochs gauge",
        "# TYPE pathway_delivery_published_epoch gauge",
    ]
    for w in plane.writers:
        lab = f'{{sink="{w.sink_id}"}}'
        lines.append(f"pathway_delivery_staged_rows_total{lab} {w.staged_rows_total}")
        lines.append(
            f"pathway_delivery_published_rows_total{lab} {w.published_rows_total}"
        )
        lines.append(
            f"pathway_delivery_discarded_rows_total{lab} {w.discarded_rows_total}"
        )
        lines.append(
            f"pathway_delivery_publish_failures_total{lab} {w.publish_failures}"
        )
        lines.append(f"pathway_delivery_uncommitted_epochs{lab} {w.depth()}")
        lines.append(f"pathway_delivery_published_epoch{lab} {w.published_epoch}")
    return lines
