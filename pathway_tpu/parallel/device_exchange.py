"""On-device key-shard exchange for relational blocks (SURVEY §5.8 end state).

The reference exchanges records between workers over timely's channels
(shared memory / TCP); the host plane here does the same with pickled blocks
(``parallel/cluster.py``). This module is the ICI/DCN data plane the north
star calls for: NUMERIC column blocks are re-sharded **on device** with one
``lax.all_to_all`` per tick — rows ride the interconnect as dense tensors,
with the shard function identical to the host plane — both go through the
ONE placement authority ``internals/keys.shard_of_keys`` (low key bits mod
worker count, ``shard.rs`` parity). The in-kernel modulo below is the
``dest=None`` fast path only; when a versioned shard map is active
(``PATHWAY_SHARDMAP``, ``internals/shardmap``), callers compute destinations
host-side via ``shard_of_keys(..., shard_map=...)`` and pass explicit
``dest`` so the kernel never re-derives ownership.

Shape discipline (XLA needs static shapes): every device holds a fixed
``capacity``-row block with a validity mask; the kernel buckets rows by
destination into an ``(n_shards, capacity)`` staging tensor and all-to-alls
it; the output stays padded at ``n_shards*capacity`` rows per device with a
validity mask (no dynamic-shape compaction on device — consumers apply the
mask). Per-destination capacity is the full block capacity, so no row can
overflow regardless of skew; the cost is an ``n_shards×`` staging buffer,
the standard static-shape trade.

Scope (r5): this kernel is the PRODUCTION exchange for numeric blocks —
``parallel/device_plane.py`` stages eligible batches from
``ShardedRuntime``/``ClusterRuntime`` routing and flushes them through
``exchange_by_key`` at sweep-round boundaries (``PATHWAY_DEVICE_EXCHANGE``
= off/auto/on). Object columns stay on the host plane. Byte-identity with
the host exchange is enforced by ``tests/test_device_plane.py`` (the full
multiworker suite runs with the plane forced) and the multichip dryrun.
Measured on the 8-device virtual CPU mesh the host plane is faster (its
"exchange" is an intra-process pointer move; see BASELINE.md §exchange) —
auto mode therefore keeps a row threshold, and the plane's win condition is
real multi-chip ICI with HBM-resident blocks.
"""

from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

from pathway_tpu.internals.keys import SHARD_MASK


@lru_cache(maxsize=64)
def _jitted_exchange(
    mesh, axis: str, n_cols: int, with_dest: bool = False, fused: bool = False
):
    """One compiled exchange per (mesh, axis, column-count): jit caches on
    function identity, so the per-tick call must reuse one closure or every
    tick would pay a full retrace+compile. ``with_dest`` adds an explicit
    per-row destination input (cluster plane: global shard mapped to a local
    device index on host) instead of deriving it from the key bits.
    ``fused`` appends the post-collective cancellation pass (ISSUE-6): an
    extra (2, n) uint32 row-digest input rides along, and every (key, digest)
    group whose diffs sum to ZERO comes back invalidated — in-flight
    insert↔retract churn never reaches host memory. Groups with a nonzero
    net keep ALL their rows, original diffs, arrival positions (join
    arrangements carry multiplicity as physical rows; see the kernel
    comment). The output is NOT consolidated or key-sorted."""
    import jax
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    kern = _kernel(n, axis, with_dest, fused)
    in_specs = [P(None, axis), P(axis), P(axis), [P(axis)] * n_cols]
    if with_dest:
        in_specs.append(P(axis))
    if fused:
        in_specs.append(P(None, axis))
    from pathway_tpu.jax_compat import shard_map
    from pathway_tpu.observability import device as _dev_prof

    label = "device_exchange.fused_consolidate" if fused else "device_exchange.all_to_all"
    return _dev_prof.traced_jit(
        label,
        jax.jit(
            shard_map(
                kern,
                mesh=mesh,
                in_specs=tuple(in_specs),
                out_specs=(P(None, axis), P(axis), P(axis), [P(axis)] * n_cols),
                check=True,
            )
        ),
    )


def _kernel(n_shards: int, axis: str, with_dest: bool = False, fused: bool = False):
    import jax
    import jax.numpy as jnp

    def local(keys, diffs, valid, cols, *rest):
        ri = 0
        dest = rest[ri] if with_dest else None
        ri += 1 if with_dest else 0
        dig = rest[ri] if fused else None
        # keys arrive as uint32 pairs (hi, lo) — x64 stays off
        cap = keys.shape[1]
        hi, lo = keys[0], keys[1]
        if with_dest:
            shard = dest.astype(jnp.int32)
        else:
            shard = (
                (lo & jnp.uint32(SHARD_MASK & 0xFFFFFFFF)) % jnp.uint32(n_shards)
            ).astype(jnp.int32)
        shard = jnp.where(valid, shard, n_shards)  # invalid rows go nowhere
        # position of each row within its destination bucket
        onehot = (shard[None, :] == jnp.arange(n_shards)[:, None]).astype(jnp.int32)
        pos_in_dest = jnp.cumsum(onehot, axis=1) - 1  # (n, cap)
        pos = jnp.take_along_axis(
            pos_in_dest, jnp.clip(shard, 0, n_shards - 1)[None, :], axis=0
        )[0]

        def stage(arr, fill):
            buf = jnp.full((n_shards, cap) + arr.shape[1:], fill, dtype=arr.dtype)
            # invalid rows carry dest == n_shards: out of bounds, dropped —
            # a dummy in-bounds write would clobber a real row's slot
            return buf.at[shard, pos].set(arr, mode="drop")

        s_hi = stage(hi, jnp.uint32(0))
        s_lo = stage(lo, jnp.uint32(0))
        s_diff = stage(diffs, jnp.int32(0))
        s_valid = stage(valid, False)
        s_cols = [stage(c, jnp.zeros((), c.dtype)) for c in cols]
        if fused:
            s_dhi = stage(dig[0], jnp.uint32(0))
            s_dlo = stage(dig[1], jnp.uint32(0))

        a2a = partial(jax.lax.all_to_all, axis_name=axis, split_axis=0, concat_axis=0)
        r_hi, r_lo = a2a(s_hi), a2a(s_lo)
        r_diff, r_valid = a2a(s_diff), a2a(s_valid)
        r_cols = [a2a(c) for c in s_cols]
        # received: (n_shards, cap) blocks → flat (n_shards*cap) rows + mask
        flat = lambda x: x.reshape((n_shards * cap,) + x.shape[2:])  # noqa: E731
        f_hi, f_lo = flat(r_hi), flat(r_lo)
        f_diff, f_valid = flat(r_diff), flat(r_valid)
        f_cols = [flat(c) for c in r_cols]
        if not fused:
            return jnp.stack([f_hi, f_lo]), f_diff, f_valid, f_cols
        # fused consolidation — same launch, no host round-trip: the rows of
        # one key only ever co-locate HERE (post-collective), so this is the
        # earliest point deltas can net. Group by (key, digest) on a sorted
        # VIEW, segment-sum the diffs, and invalidate every row of a group
        # whose net is ZERO — the in-flight insert↔retract churn this fusion
        # targets cancels before it ever reaches host memory. Groups with a
        # nonzero net keep ALL their rows with their original diffs: stateful
        # consumers (the join arrangement) carry multiplicity as physical
        # rows, so collapsing a +1,+1 group to one diff-2 row would lose a
        # copy of their state. Surviving rows stay in arrival order —
        # byte-for-byte what the plain exchange delivers, minus cancelled
        # pairs.
        f_dhi, f_dlo = flat(a2a(s_dhi)), flat(a2a(s_dlo))
        n_rows = f_hi.shape[0]
        inv = (~f_valid).astype(jnp.uint32)
        order = jnp.lexsort((f_dlo, f_dhi, f_lo, f_hi, inv))
        hi_s, lo_s = f_hi[order], f_lo[order]
        dhi_s, dlo_s = f_dhi[order], f_dlo[order]
        v_s, d_s = f_valid[order], f_diff[order]
        same_prev = jnp.concatenate(
            [
                jnp.zeros((1,), jnp.bool_),
                (hi_s[1:] == hi_s[:-1])
                & (lo_s[1:] == lo_s[:-1])
                & (dhi_s[1:] == dhi_s[:-1])
                & (dlo_s[1:] == dlo_s[:-1])
                & (v_s[1:] == v_s[:-1]),
            ]
        )
        newg = ~same_prev
        seg = jnp.cumsum(newg) - 1
        sums = jax.ops.segment_sum(d_s, seg, num_segments=n_rows)
        keep_s = v_s & (sums[seg] != 0)
        out_valid = jnp.zeros_like(f_valid).at[order].set(keep_s)
        out_diff = jnp.where(out_valid, f_diff, 0)
        return jnp.stack([f_hi, f_lo]), out_diff, out_valid, f_cols

    return local


def exchange_by_key(mesh, axis: str, keys, diffs, cols, valid, dest=None, dig=None):
    """Re-shard padded per-device blocks so every row lands on the device
    owning its key shard (host-plane parity: ``internals/keys.shard_of_keys``,
    re-exported as ``mesh.shard_of_keys``).

    Inputs are GLOBAL arrays sharded along ``axis`` on their first dim:
    ``keys`` uint32 (2, n_dev*cap) as (hi, lo) pairs, ``diffs`` int32,
    ``valid`` bool, ``cols`` list of numeric arrays. Returns the same
    structure with per-device row counts expanded to ``n_shards*cap`` (masked).

    ``dest`` (int32, optional) routes each row to an explicit device index
    instead of its key-shard — the cluster plane uses this to map GLOBAL
    worker shards onto the process-local mesh.

    ``dig`` (uint32 (2, n) row-digest pairs, optional) selects the FUSED
    consolidate+exchange kernel: (key, digest) groups whose diffs net to
    zero are invalidated in the same launch as the collective; surviving
    rows keep their original diffs and arrival positions (cancel-only — the
    output block is byte-identical to the plain exchange minus cancelled
    pairs, not consolidated or re-sorted).
    """
    fused = dig is not None
    fn = _jitted_exchange(
        mesh, axis, len(cols), with_dest=dest is not None, fused=fused
    )
    args = [keys, diffs, valid, cols]
    if dest is not None:
        args.append(dest)
    if fused:
        args.append(dig)
    return fn(*args)


def split_keys_u64(keys: np.ndarray) -> np.ndarray:
    """uint64 host keys → (2, n) uint32 (hi, lo) device representation."""
    k = keys.astype(np.uint64)
    return np.stack(
        [(k >> np.uint64(32)).astype(np.uint32), (k & np.uint64(0xFFFFFFFF)).astype(np.uint32)]
    )


def join_keys_u64(pairs: np.ndarray) -> np.ndarray:
    return (pairs[0].astype(np.uint64) << np.uint64(32)) | pairs[1].astype(np.uint64)
