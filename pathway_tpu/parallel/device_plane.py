"""Production on-device exchange plane for the sharded runtimes.

The reference's production exchange is timely's channel fabric — shared
memory between threads, TCP between processes
(``external/timely-dataflow/communication/src/networking.rs``, configured at
``src/engine/dataflow/config.rs:63-120``). Round 4 proved the TPU-native
equivalent (``device_exchange.exchange_by_key``: one ``lax.all_to_all`` per
tick re-sharding padded row blocks over the mesh) bit-parity with the host
plane, but only as a demo. This module makes it the engine's exchange path:

- ``ShardedRuntime._route`` stages eligible key-exchange batches here instead
  of splitting them on host; at the end of every sweep round the runtime
  flushes — all staged rows ride ONE collective per (consumer, dtype-layout)
  group and land in the destination workers' input buffers.
- Eligibility = every column is fixed-width (numeric / bool / datetime);
  object columns (strings, Json) fall back to the host plane per batch.
  8-byte values (int64/float64/datetime64/uint64 keys) are transported as
  (hi, lo) uint32 pairs so x64 stays off and float bits survive exactly.
- ``mode="auto"`` stages only blocks big enough to amortize dispatch
  (``PATHWAY_DEVICE_EXCHANGE_MIN_ROWS``); ``"on"`` forces every eligible
  batch through the device plane (byte-identity suites run this way);
  ``"off"`` disables it. Same flag discipline as the XLA join probe
  (``engine/colstore.py``).

The collective is issued by the tick-coordinating thread over GLOBAL arrays
(one jax process sees the whole mesh: a TPU-VM host's chips, or the 8-device
virtual CPU mesh in tests). Cross-process meshes need ``jax.distributed`` —
the multi-host path documented in ``parallel/mesh.py``.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch

#: numpy dtype kinds transportable as dense device tensors
_FIXED_KINDS = frozenset("iufbMm")


def _encode_col(arr: np.ndarray) -> tuple[list[np.ndarray], tuple]:
    """Column → device-safe parts. 8-byte dtypes become (hi, lo) uint32 pairs
    (bit-exact under disabled x64); narrower dtypes pass through."""
    if arr.dtype.itemsize == 8:
        u = np.ascontiguousarray(arr).view(np.uint64)
        return (
            [(u >> np.uint64(32)).astype(np.uint32), (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)],
            ("u64", arr.dtype),
        )
    return [arr], ("raw", arr.dtype)


def _decode_col(parts: list[np.ndarray], meta: tuple) -> np.ndarray:
    tag, dtype = meta
    if tag == "u64":
        hi, lo = parts
        u = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
        return u.view(dtype)
    return parts[0].astype(dtype, copy=False)


class DeviceExchangePlane:
    """Stages key-exchange batches and flushes them through one
    ``all_to_all`` collective per group at sweep-round boundaries."""

    def __init__(self, n_workers: int, force: bool = False, min_rows: int = 4096):
        self.n_workers = n_workers
        self.force = force
        self.min_rows = min_rows
        self.axis = "data"
        self.mesh = None
        self._unavailable = False
        self._lock = threading.Lock()
        # (consumer_index, port) -> [(src_worker, route_keys u64, batch)]
        self._staged: dict[tuple[int, int], list[tuple[int, np.ndarray, DeltaBatch]]] = {}
        # bench counters
        self.rows_exchanged = 0
        self.collectives = 0
        self.rows_netted = 0  # rows removed by fused on-device consolidation

    # ------------------------------------------------------------ eligibility
    @staticmethod
    def _backend_initialized() -> bool:
        import sys

        xb = sys.modules.get("jax._src.xla_bridge")
        return bool(getattr(xb, "_backends", None))

    def available(self) -> bool:
        if self._unavailable:
            return False
        if self.mesh is None:
            # never initialize the jax backend from the routing hot path: in
            # auto mode the plane engages only when the process already runs
            # on jax (pipelines without device kernels keep zero jax cost —
            # and first-touch init inside a sweep thread cost ~1.4s, measured)
            if not self.force and not self._backend_initialized():
                return False
            with self._lock:
                if self.mesh is not None:
                    return True
                if self._unavailable:
                    return False
                try:
                    import jax
                    from jax.sharding import Mesh

                    devices = jax.devices()
                    if len(devices) < self.n_workers:
                        self._unavailable = True
                        return False
                    self.mesh = Mesh(np.array(devices[: self.n_workers]), (self.axis,))
                except Exception:
                    self._unavailable = True
                    return False
        return True

    @staticmethod
    def eligible(batch: DeltaBatch) -> bool:
        return all(c.dtype.kind in _FIXED_KINDS for c in batch.data.values())

    def _fused_active(self) -> bool:
        """Fused consolidate+exchange (PATHWAY_DEVICE_EXCHANGE_FUSED): keyed
        deltas are digest-netted in the same launch that re-shards them.
        ``auto`` engages on real accelerator meshes only (on the CPU mesh the
        extra device sort is a measured negative, like the exchange itself);
        ``on`` forces it for byte-identity suites."""
        from pathway_tpu.internals.config import get_pathway_config

        mode = get_pathway_config().device_exchange_fused
        if mode == "off":
            return False
        if mode == "on":
            return True
        return (
            self.mesh is not None
            and self.mesh.devices.flat[0].platform != "cpu"
        )

    def should_stage(self, batch: DeltaBatch) -> bool:
        if not self.available() or not self.eligible(batch):
            return False
        if self.force:
            return True
        # auto engages only on real accelerator meshes: on host-emulated CPU
        # devices the collective is a measured negative vs the host plane's
        # zero-copy hand-off (BASELINE.md §exchange)
        if self.mesh.devices.flat[0].platform == "cpu":
            return False
        return len(batch) >= self.min_rows

    # ---------------------------------------------------------------- staging
    def stage(
        self, consumer: int, port: int, src_worker: int, route_keys: np.ndarray, batch: DeltaBatch
    ) -> None:
        with self._lock:
            self._staged.setdefault((consumer, port), []).append(
                (src_worker, route_keys, batch)
            )

    # ----------------------------------------------------------------- flush
    def flush(self, deliver, time: int) -> bool:
        """Exchange every staged group; ``deliver(worker, consumer, port,
        batch)`` lands each output block. Returns True if any rows moved."""
        with self._lock:
            staged, self._staged = self._staged, {}
        if not staged:
            return False
        moved = False
        for (ci, port) in sorted(staged):
            entries = [(w, rk, b, None) for (w, rk, b) in staged[(ci, port)]]
            if self._exchange_groups(ci, port, entries, time, deliver):
                moved = True
        return moved

    def _exchange_groups(self, ci: int, port: int, entries: list, time: int, deliver) -> bool:
        """Split by column layout (one collective per identical signature —
        int vs float layouts can differ between producers) and exchange."""
        groups: dict[tuple, list] = {}
        for e in entries:
            sig = tuple((n, c.dtype.str) for n, c in e[2].data.items())
            groups.setdefault(sig, []).append(e)
        moved = False
        for sig in sorted(groups):
            if self._exchange_group(ci, port, groups[sig], time, deliver):
                moved = True
        return moved

    def _exchange_group(self, ci: int, port: int, entries: list, time: int, deliver) -> bool:
        from pathway_tpu.observability import engine_phases as _phases

        tok = _phases.start()
        try:
            return self._exchange_group_impl(ci, port, entries, time, deliver)
        finally:
            _phases.stop(tok, "exchange")

    def _exchange_group_impl(self, ci: int, port: int, entries: list, time: int, deliver) -> bool:
        """One collective. ``entries`` = (mesh_slot, route_keys, batch,
        dest|None); dest (int32 local device indices) overrides key-shard
        routing — the cluster plane maps global shards to local slots."""
        from pathway_tpu.parallel.device_exchange import exchange_by_key

        n = self.n_workers
        per_worker: list[list[tuple[np.ndarray, DeltaBatch, Any]]] = [[] for _ in range(n)]
        with_dest = False
        for w, rk, b, dest in entries:
            per_worker[w].append((rk, b, dest))
            with_dest = with_dest or dest is not None
        counts = [sum(len(b) for _, b, _ in lst) for lst in per_worker]
        total = sum(counts)
        if total == 0:
            return False
        # pow2 capacity buckets keep the jit cache small
        cap = max(8, 1 << (max(counts) - 1).bit_length())

        template = entries[0][2]
        col_names = list(template.data.keys())
        col_meta: list[tuple] = []
        # global staging arrays: worker w's rows occupy [w*cap, w*cap+counts[w]).
        # Only `valid` needs zeroing — invalid slots of the others are masked
        # out at decode, so np.empty skips ~MBs of memset per flush
        fused = self._fused_active()
        route = np.empty(n * cap, dtype=np.uint64)
        diffs = np.empty(n * cap, dtype=np.int32)
        valid = np.zeros(n * cap, dtype=bool)
        keys = np.empty(n * cap, dtype=np.uint64)
        dig = np.empty(n * cap, dtype=np.uint64) if fused else None
        dest_buf = np.empty(n * cap, dtype=np.int32) if with_dest else None
        col_bufs: list[np.ndarray] = []
        for name in col_names:
            dtype = template.data[name].dtype
            parts, meta = _encode_col(np.zeros(0, dtype=dtype))
            col_meta.append(meta)
            for p in parts:
                col_bufs.append(np.empty(n * cap, dtype=p.dtype))
        for w, lst in enumerate(per_worker):
            ofs = w * cap
            for rk, b, dest in lst:
                m = len(b)
                route[ofs : ofs + m] = rk
                diffs[ofs : ofs + m] = b.diffs
                keys[ofs : ofs + m] = b.keys
                valid[ofs : ofs + m] = True
                if fused:
                    dig[ofs : ofs + m] = b.row_digest()
                if with_dest:
                    dest_buf[ofs : ofs + m] = dest
                bi = 0
                for name in col_names:
                    parts, _meta = _encode_col(b.data[name])
                    for p in parts:
                        col_bufs[bi][ofs : ofs + m] = p
                        bi += 1
                ofs += m

        from pathway_tpu.parallel.device_exchange import split_keys_u64

        key_parts, _ = _encode_col(keys)
        payload = key_parts + col_bufs
        out_route, out_diffs, out_valid, out_cols = exchange_by_key(
            self.mesh, self.axis, split_keys_u64(route), diffs, payload, valid,
            dest=dest_buf,
            dig=split_keys_u64(dig) if fused else None,
        )
        self.collectives += 1
        self.rows_exchanged += total

        out_valid = np.asarray(out_valid)
        out_diffs = np.asarray(out_diffs)
        out_cols = [np.asarray(c) for c in out_cols]
        if fused:
            self.rows_netted += total - int(out_valid.sum())
        per_dev = out_valid.shape[0] // n
        moved = False
        for d in range(n):
            sl = slice(d * per_dev, (d + 1) * per_dev)
            mask = out_valid[sl]
            if not mask.any():
                continue
            dk = _decode_col([out_cols[0][sl][mask], out_cols[1][sl][mask]], ("u64", np.dtype(np.uint64)))
            data: dict[str, np.ndarray] = {}
            bi = 2
            for name, meta in zip(col_names, col_meta):
                n_parts = 2 if meta[0] == "u64" else 1
                parts = [out_cols[bi + j][sl][mask] for j in range(n_parts)]
                bi += n_parts
                data[name] = _decode_col(parts, meta)
            batch = DeltaBatch(dk, out_diffs[sl][mask].astype(np.int64), data, time)
            deliver(d, ci, port, batch)
            moved = True
        return moved


class ClusterDevicePlane(DeviceExchangePlane):
    """Cluster variant — the ICI/DCN split of SURVEY §5.8: rows whose key
    shard lives on THIS process ride the process-local mesh (one collective
    with explicit destinations), rows owned by other processes fall back to
    the host TCP links. The mesh spans the process's local workers (a
    TPU-VM host's chips); cross-host device exchange needs a
    ``jax.distributed`` global mesh, out of scope on this image."""

    def __init__(
        self,
        n_workers_global: int,
        threads: int,
        pid: int,
        force: bool = False,
        min_rows: int = 4096,
    ):
        super().__init__(threads, force=force, min_rows=min_rows)
        self.n_global = n_workers_global
        self.threads = threads
        self.pid = pid
        # versioned shard map (PATHWAY_SHARDMAP): set by ClusterRuntime.run();
        # None keeps the modulo rule. Destinations are always computed
        # host-side here and passed explicitly, so the in-kernel modulo never
        # re-derives ownership on this path.
        self.shard_map = None

    def flush(self, deliver, time: int) -> bool:
        """``deliver(global_worker, consumer, port, batch)`` — the cluster's
        ``_deliver``, which lands locally or sends over the peer link."""
        from pathway_tpu.parallel.mesh import shard_of_keys

        with self._lock:
            staged, self._staged = self._staged, {}
        if not staged:
            return False
        moved = False
        lo = self.pid * self.threads
        hi = lo + self.threads
        for (ci, port) in sorted(staged):
            local_entries = []
            for (w_global, rk, b) in staged[(ci, port)]:
                shards = shard_of_keys(rk, self.n_global, shard_map=self.shard_map)
                remote = (shards < lo) | (shards >= hi)
                if remote.any():
                    for dest_w in np.unique(shards[remote]):
                        idx = np.flatnonzero(shards == dest_w)
                        deliver(int(dest_w), ci, port, b.take(idx))
                        moved = True
                keep = np.flatnonzero(~remote)
                if len(keep):
                    local_entries.append(
                        (
                            w_global - lo,
                            rk[keep],
                            b.take(keep),
                            (shards[keep] - lo).astype(np.int32),
                        )
                    )
            if local_entries:

                def deliver_local(slot, ci_, port_, batch, _lo=lo):
                    deliver(_lo + slot, ci_, port_, batch)

                if self._exchange_groups(ci, port, local_entries, time, deliver_local):
                    moved = True
        return moved


def make_device_plane(n_workers: int) -> DeviceExchangePlane | None:
    """Flag-gated factory (``PATHWAY_DEVICE_EXCHANGE`` = off | auto | on)."""
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    mode = cfg.device_exchange
    if mode == "off" or n_workers < 2:
        return None
    return DeviceExchangePlane(
        n_workers, force=(mode == "on"), min_rows=cfg.device_exchange_min_rows
    )


def make_cluster_device_plane(
    n_workers_global: int, threads: int, pid: int
) -> ClusterDevicePlane | None:
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    mode = cfg.device_exchange
    if mode == "off" or threads < 2:
        return None
    return ClusterDevicePlane(
        n_workers_global,
        threads,
        pid,
        force=(mode == "on"),
        min_rows=cfg.device_exchange_min_rows,
    )
