"""Multi-worker sharded execution of the engine graph.

The reference's worker model (SURVEY §2.9, ``worker-architecture.md``): every
worker builds the IDENTICAL dataflow; records are exchanged between workers by
key shard before stateful operators; progress (the tick frontier) advances in
lockstep. This module is the block-engine version:

- ``ShardedRuntime(n_workers)`` builds one engine graph per worker from the same
  logical outputs (node indices align across workers by construction).
- At routing time, a consumer's :meth:`Node.exchange_key` decides placement:
  ``None`` → stay on the producing worker (stateless op); a key function →
  split the block by ``shard_of_keys`` and deliver each piece to its owner —
  numeric blocks may instead ride the on-device all_to_all plane
  (``parallel/device_plane.py``, ``PATHWAY_DEVICE_EXCHANGE``); ``SOLO`` →
  everything to worker 0 (serial operators: non-partitioned sources,
  unsharded sinks, sort's global order, non-shardable external indexes).
  Partitioned sources (``local_source`` nodes, e.g. Kafka) poll on their OWN
  worker with disjoint partition slices, and ``fs.write(sharded=True)`` sinks
  write per-worker shards with an ordered merge-commit — the r5 SOLO-pin
  kills (reference ``worker-architecture.md:36-47``). The temporal plane
  shards: temporal/asof-now joins by join key, session windows by instance,
  buffer/forget/freeze row state by row key with one shared watermark cell
  per logical node (``internals/time_ops._SharedWatermark``).
- Each tick runs sweep rounds: all workers sweep concurrently (threads), then
  meet at a barrier; the tick ends when a round does no work anywhere. The
  frontier phase runs the same way, so every worker passes timestamp t before
  any sees t+1 — the global consistency frontier.

Worker threads parallelize the host-side state machinery (hash joins, group
state); the FLOP-heavy work inside nodes is already batched XLA. The same
exchange contract carries to multi-process over ``jax.distributed`` (blocks
serialized between processes instead of handed between threads).
"""

from __future__ import annotations

import heapq
import threading
from typing import Any

import numpy as np

from pathway_tpu.engine import fusion as _fusion
from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import BROADCAST, END_OF_STREAM, SOLO, EngineGraph, Node
from pathway_tpu.internals.logical import BuildContext, LogicalNode
from pathway_tpu.internals.trace import run_annotated
from pathway_tpu.observability import audit as _audit
from pathway_tpu.observability import engine_phases as _phases
from pathway_tpu.observability import requests as _requests
from pathway_tpu.parallel.mesh import shard_of_keys
from pathway_tpu.resilience import faults as _faults


class _Worker:
    def __init__(self, index: int, graph: EngineGraph):
        self.index = index
        self.graph = graph
        self.lock = threading.Lock()  # guards cross-worker accepts
        # fused-chain sweep plan (interior links restricted to exchange-free
        # consumers: fusing across an exchange would move rows off the worker
        # the unfused routing would have placed them on)
        self.plan = _fusion.build_plan(graph, exchange_aware=True)
        #: dirty step positions (guarded by ``lock`` — marks arrive from any
        #: worker thread routing into this worker's graph)
        self.dirty: set[int] = set()
        #: the active sweep's heap — only this worker's own thread touches it
        self.sweep_heap: list[int] | None = None

    def mark_dirty_locked(self, node_index: int) -> None:
        """Mark the step owning ``node_index`` dirty. Caller holds ``lock``.
        No-op in legacy (PATHWAY_FUSE=off) mode — the full-scan sweep finds
        pending work by walking every node."""
        if self.plan is not None:
            self.dirty.add(self.plan.pos_of[node_index])


class ShardedRuntime:
    """Drives W aligned engine graphs tick by tick with key-shard exchange.

    API-compatible with ``engine.runtime.Runtime`` where the single-worker
    code paths touch it (connectors, persistence hooks are worker-0 concerns).
    """

    def __init__(
        self,
        n_workers: int = 2,
        monitoring_level: Any = None,
        autocommit_duration_ms: int | None = 20,
    ):
        if n_workers < 1:
            raise ValueError("need at least one worker")
        self.n_workers = n_workers
        self.autocommit_duration_ms = autocommit_duration_ms
        self.monitoring_level = monitoring_level
        self.connectors: list[Any] = []
        self.persistence: Any = None
        self.workers: list[_Worker] = []
        self._stop_requested = False
        self.streaming = False  # set after build (see engine.runtime.Runtime)
        self.current_time = 0
        self.on_tick_done: list[Any] = []
        # arrival-driven tick scheduling (REST serving plane wakeups)
        from pathway_tpu.engine.runtime import TickWakeup

        self.wakeup = TickWakeup()
        # live tracing (observability): installed in run(), None when off
        self.tracer = None
        self._trace_active = False
        # request-scoped tracing: the plane while a request is in flight this
        # tick, else None (see engine.graph.Scheduler)
        self._rp = None
        # on-device all_to_all exchange for numeric blocks (None = host-only;
        # see parallel/device_plane.py and PATHWAY_DEVICE_EXCHANGE)
        from pathway_tpu.parallel.device_plane import make_device_plane

        self.device_plane = make_device_plane(n_workers)

    def register_connector(self, driver) -> None:
        self.connectors.append(driver)

    def request_stop(self) -> None:
        self._stop_requested = True

    # ---------------------------------------------------------------- build
    def _build(self, outputs: list[LogicalNode]) -> None:
        # peers build first, worker 0 LAST: node factories may capture the built
        # node into shared holders (connector subjects, rest holders) — the last
        # build must be the one whose sources actually receive events and poll
        self.workers = [None] * self.n_workers  # type: ignore[list-item]
        for w in list(range(1, self.n_workers)) + [0]:
            ctx = BuildContext(
                runtime=self if w == 0 else None,
                worker_index=w,
                n_workers=self.n_workers,
                register=self.register_connector,
                shared_runtime=self,
            )
            for out in outputs:
                ctx.resolve(out)
            if w == 0:
                ctx.finish()
                self._ctx0 = ctx
            self.workers[w] = _Worker(w, ctx.graph)
        sizes = {len(w.graph.nodes) for w in self.workers}
        assert len(sizes) == 1, "worker graphs misaligned"

    # ---------------------------------------------------------------- routing
    def _accept_local(self, worker: _Worker, ci: int, port: int, batch) -> None:
        """Same-worker accept from the worker's own thread: a mid-sweep mark
        goes straight onto the active heap (edges only point forward), so
        the consumer runs in this same sweep — exactly the scan order the
        full-walk sweep had."""
        worker.graph.nodes[ci].accept(port, batch)
        if worker.plan is None:
            return  # legacy mode: the full scan finds it
        h = worker.sweep_heap
        if h is not None:
            heapq.heappush(h, worker.plan.pos_of[ci])
        else:
            with worker.lock:
                worker.mark_dirty_locked(ci)

    def _route(self, worker: _Worker, producer: Node, batches: list[DeltaBatch]) -> bool:
        routed = False
        consumers = worker.graph.edges.get(producer.node_index, [])
        for batch in batches:
            if batch is None or batch.is_empty:
                continue
            producer.stats_rows_out += len(batch)
            for ci, port in consumers:
                consumer = worker.graph.nodes[ci]
                key_fn = consumer.exchange_key(port)
                if key_fn is None:
                    self._accept_local(worker, ci, port, batch)
                    routed = True
                elif key_fn == SOLO:
                    target = self.workers[0]
                    dest = target.graph.nodes[ci]
                    with target.lock:
                        dest.accept(port, batch)
                        target.mark_dirty_locked(ci)
                    routed = True
                elif key_fn == BROADCAST:
                    for target in self.workers:
                        dest = target.graph.nodes[ci]
                        with target.lock:
                            dest.accept(port, batch)
                            target.mark_dirty_locked(ci)
                    routed = True
                else:
                    if self.n_workers == 1:
                        self._accept_local(worker, ci, port, batch)
                        routed = True
                        continue
                    route_keys = np.asarray(key_fn(batch), dtype=np.uint64)
                    if (
                        self.device_plane is not None
                        and self.device_plane.should_stage(batch)
                    ):
                        # numeric fast lane: the block rides the mesh at the
                        # next flush instead of host-splitting here
                        self.device_plane.stage(
                            ci, port, worker.index, route_keys, batch
                        )
                        routed = True
                        continue
                    shards = shard_of_keys(route_keys, self.n_workers)
                    for w_idx in np.unique(shards):
                        piece = batch.take(np.flatnonzero(shards == w_idx))
                        target = self.workers[int(w_idx)]
                        dest = target.graph.nodes[ci]
                        with target.lock:
                            dest.accept(port, piece)
                            target.mark_dirty_locked(ci)
                        routed = True
        return routed

    # ---------------------------------------------------------------- ticking
    def _sweep_worker_legacy(self, worker: _Worker, time: int) -> bool:
        """The r14 per-worker sweep, verbatim (PATHWAY_FUSE=off)."""
        import time as _t

        any_work = False
        trace = self._trace_active
        rp = self._rp
        aud = _audit.current()
        aud_note = aud is not None and aud.edge_sampled
        for node in worker.graph.nodes:
            with worker.lock:
                if not node.has_pending():
                    continue
                inputs = node.drain()
            rows_in = sum(len(b) for b in inputs if b is not None)
            node.stats_rows_in += rows_in
            if trace or rp is not None:
                from pathway_tpu.observability import device as _dev_prof

                w0 = _t.time_ns()
                dev0 = _dev_prof.thread_device_wait_ns() if trace else 0
            out = run_annotated(node, node.process, inputs, time)
            if trace or rp is not None:
                w1 = _t.time_ns()
                if rp is not None and (
                    rows_in
                    or any(b is not None and not b.is_empty for b in out)
                ):
                    # a no-op visit (nothing drained, nothing emitted) touched
                    # no request's rows — don't spend the per-tick ring budget
                    rp.note_stage(time, f"sweep/{node.name}", w0, w1, rows_in)
            if trace:
                dev_ns = _dev_prof.thread_device_wait_ns() - dev0
                self.tracer.span(
                    f"sweep/{node.name}",
                    w0,
                    w1,
                    {
                        "pathway.operator.id": node.node_index,
                        "pathway.worker": worker.index,
                        "pathway.rows_in": rows_in,
                        "pathway.device_ms": round(dev_ns / 1e6, 3),
                    },
                )
                if dev_ns:
                    _dev_prof.stats().note_span_split(
                        f"sweep/{node.name}", max(0, w1 - w0 - dev_ns), dev_ns
                    )
            if aud_note:
                aud.note_edge(node, inputs, out)
            if self._route(worker, node, out):
                any_work = True
            any_work = any_work or any(b is not None for b in inputs)
        return any_work

    def _sweep_worker(self, worker: _Worker, time: int) -> bool:
        import time as _t

        if worker.plan is None:
            return self._sweep_worker_legacy(worker, time)
        with worker.lock:
            if not worker.dirty:
                return False
            heap = sorted(worker.dirty)
            worker.dirty.clear()
        worker.sweep_heap = heap
        any_work = False
        trace = self._trace_active
        rp = self._rp
        aud = _audit.current()
        aud_note = aud is not None and aud.edge_sampled
        by_pos = worker.plan.by_pos
        last = -1
        try:
            while heap:
                pos = heapq.heappop(heap)
                if pos == last:
                    continue
                last = pos
                step = by_pos[pos]
                chain = step.chain
                if chain is not None:
                    if self._run_chain(worker, chain, time, trace, aud if aud_note else None):
                        any_work = True
                    continue
                node = step.node
                with worker.lock:
                    if not node.has_pending():
                        continue
                    inputs = node.drain()
                rows_in = sum(len(b) for b in inputs if b is not None)
                node.stats_rows_in += rows_in
                if trace or rp is not None:
                    from pathway_tpu.observability import device as _dev_prof

                    w0 = _t.time_ns()
                    dev0 = _dev_prof.thread_device_wait_ns() if trace else 0
                out = run_annotated(node, node.process, inputs, time)
                if trace or rp is not None:
                    w1 = _t.time_ns()
                    if rp is not None and (
                        rows_in
                        or any(b is not None and not b.is_empty for b in out)
                    ):
                        # a no-op visit (nothing drained, nothing emitted) touched
                        # no request's rows — don't spend the per-tick ring budget
                        rp.note_stage(time, f"sweep/{node.name}", w0, w1, rows_in)
                if trace:
                    dev_ns = _dev_prof.thread_device_wait_ns() - dev0
                    self.tracer.span(
                        f"sweep/{node.name}",
                        w0,
                        w1,
                        {
                            "pathway.operator.id": node.node_index,
                            "pathway.worker": worker.index,
                            "pathway.rows_in": rows_in,
                            "pathway.device_ms": round(dev_ns / 1e6, 3),
                        },
                    )
                    if dev_ns:
                        _dev_prof.stats().note_span_split(
                            f"sweep/{node.name}", max(0, w1 - w0 - dev_ns), dev_ns
                        )
                if aud_note:
                    # per-edge cardinality counters (node instances are
                    # per-worker, so no cross-thread contention; read side
                    # sums by position)
                    aud.note_edge(node, inputs, out)
                self._route(worker, node, out)
                any_work = True
        finally:
            worker.sweep_heap = None
        return any_work

    def _run_chain(self, worker: _Worker, chain, time: int, trace: bool, aud) -> bool:
        """One fused-chain step on this worker (see Scheduler._run_chain:
        per-chain span, device wait AND inner traced-jit cold walls
        subtracted from the host share)."""
        import time as _t

        from pathway_tpu.observability import device as _dev_prof

        rp = self._rp
        if trace or rp is not None:
            w0 = _t.time_ns()
            dev0 = _dev_prof.thread_device_wait_ns() if trace else 0
            cold0 = _dev_prof.thread_cold_s() if trace else 0.0
        t0 = _t.perf_counter_ns()
        tok = _phases.start()
        try:
            out, processed, rows_in, rows_out = chain.execute(
                time, worker.lock, aud
            )
        finally:
            _phases.stop(tok, "fused")
        if not processed:
            return False
        elapsed_ns = _t.perf_counter_ns() - t0
        chain.tail.stats_time_ns += elapsed_ns
        if rp is not None:
            rp.note_stage(
                time, f"sweep/chain{{{chain.label}}}", w0, _t.time_ns(), rows_in
            )
        if trace:
            w1 = _t.time_ns()
            dev_ns = _dev_prof.thread_device_wait_ns() - dev0
            cold_ns = int((_dev_prof.thread_cold_s() - cold0) * 1e9)
            name = f"sweep/chain{{{chain.label}}}"
            attrs = {
                "pathway.operator.id": chain.operator_ids(),
                "pathway.worker": worker.index,
                "pathway.chain.nodes": len(chain.members),
                "pathway.rows_in": rows_in,
                "pathway.rows_out": rows_out,
                "pathway.device_ms": round(dev_ns / 1e6, 3),
            }
            if cold_ns:
                attrs["pathway.compile_ms"] = round(cold_ns / 1e6, 3)
            self.tracer.span(name, w0, w1, attrs)
            if dev_ns:
                _dev_prof.stats().note_span_split(
                    name, max(0, elapsed_ns - dev_ns - cold_ns), dev_ns
                )
        self._route(worker, chain.tail, out)
        return True

    def _parallel(self, fn) -> list:
        """Run fn(worker) on every worker concurrently; collect results.
        A worker exception (e.g. terminate_on_error aborting a batch) is
        re-raised here so the run fails loudly instead of silently dropping
        that worker's batch."""
        results = [None] * self.n_workers
        if self.n_workers == 1:
            results[0] = fn(self.workers[0])
            return results
        errors: list[BaseException | None] = [None] * self.n_workers
        threads = []
        for i, w in enumerate(self.workers):
            def target(i=i, w=w):
                try:
                    results[i] = fn(w)
                except BaseException as e:  # noqa: BLE001 — transported to caller
                    errors[i] = e

            t = threading.Thread(target=target)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                raise e
        return results

    def _deliver(self, worker: int, ci: int, port: int, batch: DeltaBatch) -> None:
        target = self.workers[worker]
        with target.lock:
            target.graph.nodes[ci].accept(port, batch)
            target.mark_dirty_locked(ci)

    def _sweep_round(self, time: int) -> bool:
        """All workers sweep concurrently, then the device plane flushes its
        staged blocks through one collective per group — the exchange lands
        as new pending work, picked up by the next round."""
        any_work = any(self._parallel(lambda w: self._sweep_worker(w, time)))
        if self.device_plane is not None and self.device_plane.flush(
            self._deliver, time
        ):
            any_work = True
        return any_work

    def run_tick(self, time: int) -> None:
        self.current_time = time
        from pathway_tpu.observability import device as _dev_prof

        _dev_prof.tick_hook(time)
        tracer = self.tracer
        tick_token = tracer.begin_tick(time) if tracer is not None else None
        self._trace_active = tick_token is not None
        rp = _requests.current()
        if rp is not None and (not rp.hot or time == END_OF_STREAM):
            rp = None
        self._rp = rp
        if rp is not None:
            rp.note_tick(time)
        # non-partitioned sources live on worker 0 only — peers' copies never
        # poll (polling them would duplicate every input row per worker);
        # partitioned sources (``local_source``) poll on their OWN worker,
        # each subject owning a disjoint partition slice (r5: the SOLO-pin
        # kill, reference worker-architecture.md:36-47)
        aud = _audit.current()
        if aud is not None:
            aud.begin_tick(time)

        def _polled(w, node):
            polled = run_annotated(node, node.poll, time)
            if polled:
                # corruption faults apply before the audit monitors observe
                polled = _faults.corrupt_polled(0, time, polled)
                if aud is not None:
                    aud.observe_input(node, polled, time)
            return polled

        def _nodes(w, kind):
            if w.plan is None:
                return w.graph.nodes
            return getattr(w.plan, kind)

        w0 = self.workers[0]
        for node in _nodes(w0, "pollers"):
            self._route(w0, node, _polled(w0, node))
        for w in self.workers[1:]:
            for node in _nodes(w, "pollers"):
                if getattr(node, "local_source", False):
                    self._route(w, node, _polled(w, node))
        while self._sweep_round(time):
            pass
        progressed = True
        while progressed:
            progressed = False
            for w in self.workers:
                for node in _nodes(w, "frontier_nodes"):
                    out = run_annotated(node, node.on_frontier, time)
                    if self._route(w, node, out):
                        progressed = True
            if progressed:
                while self._sweep_round(time):
                    pass
        for w in self.workers:
            for node in _nodes(w, "tick_complete_nodes"):
                run_annotated(node, node.on_tick_complete, time)
        for cb in self.on_tick_done:
            cb(time)
        if tick_token is not None:
            self._trace_active = False
            tracer.end_tick(time, tick_token)

    # ---------------------------------------------------------------- run loop
    def run(self, outputs: list[LogicalNode]):
        import time as _time

        from pathway_tpu import flow as _flow
        from pathway_tpu import observability as _obs

        _faults.install_from_env()  # fault plan resets per run (as in Runtime)
        _obs.install_from_env(self)
        _flow.install_from_env(self)  # before build: gates attach to inputs
        try:
            self.tracer = _obs.current()
            return self._run_inner(outputs)
        except BaseException as e:
            _obs.device.on_run_error(e, self)  # flight-recorder post-mortem
            raise
        finally:
            self.tracer = None
            _obs.shutdown()
            _flow.shutdown()

    def _run_inner(self, outputs: list[LogicalNode]):
        import time as _time

        self._build(outputs)
        self.streaming = bool(self.connectors)
        if self.persistence is not None:
            self.persistence.on_graph_built(self._ctx0)
            self.on_tick_done.append(self.persistence.on_tick_done)

        from pathway_tpu import flow as _flow

        plane = _flow.current()
        if plane is not None:
            self.on_tick_done.append(lambda t: plane.on_tick_complete(self, t))
        for driver in self.connectors:
            driver.start()
        if not self.connectors:
            self.run_tick(0)
            self.close()
            return self
        tick = 0
        period = (self.autocommit_duration_ms or 20) / 1000.0
        all_virtual = all(getattr(d, "virtual", False) for d in self.connectors)
        try:
            while not self._stop_requested:
                t0 = _time.perf_counter()
                self.run_tick(tick)
                tick += 1
                from pathway_tpu.engine.runtime import check_connector_failures

                check_connector_failures(self.connectors)
                if all(d.is_finished() for d in self.connectors):
                    self.run_tick(tick)
                    break
                if not all_virtual:
                    elapsed = _time.perf_counter() - t0
                    if elapsed < period:
                        self.wakeup.wait(period - elapsed)
        finally:
            for driver in self.connectors:
                driver.stop()
        # re-check: a subject may error between the in-loop check and the
        # is_finished break (see engine.runtime.Runtime.run)
        from pathway_tpu.engine.runtime import check_connector_failures

        check_connector_failures(self.connectors)
        self.close()
        return self

    def close(self) -> None:
        self.run_tick(END_OF_STREAM)
        for w in self.workers:
            for node in w.graph.nodes:
                node.on_end()
        if self.persistence is not None:
            self.persistence.on_close()

    # Runtime API used by debug capture
    @property
    def scheduler(self):
        return self
