"""Multi-process cluster execution — cross-process block exchange.

Role of the reference's timely ``CommunicationConfig::Cluster`` (intra-process
channels + inter-process TCP with length-delimited frames,
``external/timely-dataflow/communication/src/networking.rs``,
``src/engine/dataflow/config.rs:63-120``): the global worker space is
``threads × processes``; worker ``w`` lives on process ``w // threads``. Every
process builds the identical dataflow for its local workers; a batch routed to a
remote worker is serialized (length-prefixed pickle) to the owning process.

Progress is coordinated, not gossiped: process 0 runs a tick coordinator. A tick
is a sequence of rounds — each process sweeps its local workers to quiescence,
reports ``(did_work, n_sent, n_received)``, and the coordinator declares the
round set done when nobody worked and global sent == received (simple
termination detection standing in for timely's distributed progress tracking —
correct here because ticks are globally ordered and sends only happen inside
rounds). The same barrier runs the frontier phase, so every process passes
timestamp t before any sees t+1.

On TPU pods this plane carries only control + relational blocks; FLOP-heavy
tensors move separately over ICI via jax collectives (``ops/knn.py`` shard_map).
The design keeps the two planes independent, like the reference keeps connector
I/O threads out of the timely workers.
"""

from __future__ import annotations

import os
import pickle
import select
import socket
import struct
import threading
import time as _time
from typing import Any

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine import fusion as _fusion
from pathway_tpu.engine.graph import BROADCAST, END_OF_STREAM, SOLO, Node
from pathway_tpu.internals.config import get_pathway_config
from pathway_tpu.internals.errors import OtherWorkerError
from pathway_tpu.internals.logical import BuildContext, LogicalNode
from pathway_tpu.internals.trace import run_annotated
from pathway_tpu.observability import audit as _audit
from pathway_tpu.observability import engine_phases as _phases
from pathway_tpu.observability import requests as _requests
from pathway_tpu.parallel.mesh import shard_of_keys
from pathway_tpu.resilience import faults as _faults

import heapq


def cluster_env() -> tuple[int, int, int, int]:
    """(threads, processes, process_id, first_port) from PathwayConfig."""
    cfg = get_pathway_config()
    return cfg.threads, cfg.processes, cfg.process_id, cfg.first_port


def barrier_timeout() -> float:
    """Seconds a barrier participant waits before declaring a peer dead."""
    return get_pathway_config().barrier_timeout


def _send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (n,) = struct.unpack("<Q", header)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    return pickle.loads(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _PeerLinks:
    """Pairwise TCP links between processes with a receiver thread per peer."""

    def __init__(self, pid: int, n_proc: int, first_port: int, on_block, host: str = "127.0.0.1"):
        self.pid = pid
        self.n_proc = n_proc
        self.first_port = first_port
        self.host = host
        self.on_block = on_block  # callback(worker, node_index, port, batch)
        self.sent = 0
        self.received = 0
        # counter lock is never held across socket I/O; each peer socket has its
        # own send lock so a full TCP buffer on one link can't stall the others
        # (or the receiver threads, which only need the counter lock)
        self._counter_lock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._out: dict[int, socket.socket] = {}
        self._send_locks: dict[int, threading.Lock] = {}
        self.error: BaseException | None = None
        self._closed = False
        self._threads: list[threading.Thread] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, first_port + 1 + pid))
        self._listener.listen(n_proc)
        # start the accept thread LAST: it reads instance attributes immediately
        self._accepting = threading.Thread(target=self._accept_loop, daemon=True)
        self._accepting.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._recv_loop, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _recv_loop(self, conn: socket.socket) -> None:
        try:
            while True:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                kind, worker, node_index, port, payload = msg
                if kind != "block":
                    raise RuntimeError(f"unexpected cluster message kind {kind!r}")
                keys, diffs, data, t = payload
                batch = DeltaBatch(keys, diffs, data, t)
                self.on_block(worker, node_index, port, batch)
                with self._counter_lock:
                    self.received += 1
        except BaseException as exc:  # surface to the main loop; don't die silently
            if not self._closed:
                self.error = exc
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def check_error(self) -> None:
        if self.error is not None:
            raise RuntimeError("cluster peer link failed") from self.error

    def _conn_to(self, peer: int) -> tuple[socket.socket, threading.Lock]:
        with self._conn_lock:
            sock = self._out.get(peer)
            if sock is not None:
                return sock, self._send_locks[peer]
        deadline = _time.time() + 30
        while True:
            try:
                sock = socket.create_connection(
                    (self.host, self.first_port + 1 + peer), timeout=5
                )
                break
            except OSError:
                if _time.time() > deadline:
                    raise
                _time.sleep(0.05)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._conn_lock:
            if peer in self._out:  # lost the race; use the winner's socket
                try:
                    sock.close()
                except OSError:
                    pass
                return self._out[peer], self._send_locks[peer]
            self._out[peer] = sock
            lock = self._send_locks[peer] = threading.Lock()
        return sock, lock

    def send_block(self, peer: int, worker: int, node_index: int, port: int, batch: DeltaBatch) -> None:
        sock, lock = self._conn_to(peer)
        with lock:
            _send_msg(
                sock,
                ("block", worker, node_index, port, (batch.keys, batch.diffs, batch.data, batch.time)),
            )
        with self._counter_lock:
            self.sent += 1

    def counters(self) -> tuple[int, int]:
        with self._counter_lock:
            return self.sent, self.received

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        for s in self._out.values():
            try:
                s.close()
            except OSError:
                pass


#: select() granularity while waiting on a barrier — how often the failure
#: detector is consulted, NOT an added latency (a ready socket returns at once)
_BARRIER_POLL_S = 0.2


class _Coordinator:
    """Process 0's barrier service: collects per-round reports, answers
    continue/advance/close decisions to every process (including itself).

    Peers identify themselves with a ``("join", pid)`` handshake, so a dead
    barrier connection maps to a process id. While waiting for reports the
    coordinator polls the heartbeat monitor (``resilience/heartbeat.py``):
    a peer that died (socket EOF) or went silent past ``heartbeat_timeout``
    surfaces as a structured ``OtherWorkerError`` naming the process and its
    last-known tick — broadcast to the surviving peers before raising, so the
    whole cluster fails with the same diagnosis instead of a cascade of bare
    timeouts (the reference's worker-panic propagation, SURVEY §5.3)."""

    def __init__(
        self, n_proc: int, first_port: int, host: str = "127.0.0.1", monitor: Any = None
    ):
        self.n_proc = n_proc
        self.monitor = monitor
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, first_port))
        self._server.listen(n_proc)
        self._conns: dict[int, socket.socket] = {}

    def wait_connections(self) -> None:
        deadline = _time.monotonic() + barrier_timeout()
        self._server.settimeout(_BARRIER_POLL_S)
        while len(self._conns) < self.n_proc - 1:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                if _time.monotonic() > deadline:
                    missing = sorted(set(range(1, self.n_proc)) - set(self._conns))
                    raise OtherWorkerError(
                        f"cluster startup timed out: process(es) {missing} never "
                        f"joined ({len(self._conns) + 1}/{self.n_proc} up)",
                        process_id=missing[0] if missing else None,
                        reason="never-joined",
                    ) from None
                continue
            conn.settimeout(barrier_timeout())
            msg = _recv_msg(conn)
            if not (isinstance(msg, tuple) and len(msg) == 2 and msg[0] == "join"):
                raise RuntimeError(f"unexpected cluster join message {msg!r}")
            self._conns[int(msg[1])] = conn

    def _peer_failed(self, pid: int | None, tick: int | None, reason: str) -> None:
        """Broadcast the failure diagnosis to survivors, then raise."""
        fail = {"__fail__": {"process_id": pid, "tick": tick, "reason": reason}}
        for conn in self._conns.values():
            try:
                _send_msg(conn, fail)
            except OSError:
                pass
        at = f" (last alive at tick {tick})" if tick is not None else ""
        raise OtherWorkerError(
            f"cluster process {pid} failed: {reason}{at}",
            process_id=pid,
            tick=tick,
            reason=reason,
        )

    def _check_detector(self) -> None:
        if self.monitor is None:
            return
        dead = self.monitor.dead_peer()
        if dead is not None:
            pid, tick, reason = dead
            self._peer_failed(pid, tick, reason)

    def _recv_report(self, pid: int, conn: socket.socket, deadline: float) -> Any:
        while True:
            self._check_detector()
            try:
                readable, _, _ = select.select([conn], [], [], _BARRIER_POLL_S)
            except OSError:
                self._peer_failed(pid, self._last_tick(pid), "disconnected")
            if readable:
                break
            if _time.monotonic() > deadline:
                self._peer_failed(pid, self._last_tick(pid), "barrier-timeout")
        # readable: the full frame follows promptly (the sender uses sendall);
        # keep a generous timeout as a backstop against a torn write
        conn.settimeout(max(5.0, deadline - _time.monotonic()))
        try:
            msg = _recv_msg(conn)
        except socket.timeout:
            self._peer_failed(pid, None, "barrier-timeout")
        except OSError:
            # a SIGKILLed peer with unread data queued sends RST — a reset is
            # the same diagnosis as clean EOF: the peer is gone
            self._peer_failed(pid, self._last_tick(pid), "disconnected")
        if msg is None:
            self._peer_failed(pid, self._last_tick(pid), "disconnected")
        return msg

    def _last_tick(self, pid: int) -> int | None:
        return self.monitor.seen_peers().get(pid) if self.monitor else None

    def barrier(self, my_report: Any, decide) -> Any:
        """Collect one report from every peer + self, apply ``decide`` over the
        list, broadcast and return the decision."""
        reports = [my_report]
        deadline = _time.monotonic() + barrier_timeout()
        for pid, conn in self._conns.items():
            reports.append(self._recv_report(pid, conn, deadline))
        decision = decide(reports)
        for pid, conn in self._conns.items():
            try:
                _send_msg(conn, decision)
            except OSError:
                # the peer died after reporting: surface the structured
                # diagnosis (and tell the other survivors) instead of dying
                # on a bare broken pipe
                self._peer_failed(pid, self._last_tick(pid), "disconnected")
        return decision

    def close(self) -> None:
        for c in self._conns.values():
            try:
                c.close()
            except OSError:
                pass
        try:
            self._server.close()
        except OSError:
            pass


class _CoordinatorClient:
    def __init__(
        self, pid: int, first_port: int, host: str = "127.0.0.1", hb_client: Any = None
    ):
        self.pid = pid
        self.hb = hb_client  # HeartbeatClient: flags a vanished coordinator
        deadline = _time.time() + 30
        while True:
            try:
                self._sock = socket.create_connection((host, first_port), timeout=5)
                break
            except OSError:
                if _time.time() > deadline:
                    raise
                _time.sleep(0.05)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_msg(self._sock, ("join", pid))

    def _coordinator_lost(self, reason: str) -> None:
        raise OtherWorkerError(
            f"cluster coordinator (process 0) lost: {reason}",
            process_id=0,
            reason=reason,
        )

    def barrier(self, my_report: Any, decide=None) -> Any:
        try:
            _send_msg(self._sock, my_report)
        except OSError:
            self._coordinator_lost("disconnected")
        deadline = _time.monotonic() + barrier_timeout()
        while True:
            if self.hb is not None and self.hb.coordinator_lost:
                self._coordinator_lost("coordinator-lost")
            try:
                readable, _, _ = select.select([self._sock], [], [], _BARRIER_POLL_S)
            except OSError:
                self._coordinator_lost("disconnected")
            if readable:
                break
            if _time.monotonic() > deadline:
                self._coordinator_lost("barrier-timeout")
        self._sock.settimeout(max(5.0, deadline - _time.monotonic()))
        try:
            decision = _recv_msg(self._sock)
        except socket.timeout:
            self._coordinator_lost("barrier-timeout")
        except OSError:
            self._coordinator_lost("disconnected")  # RST counts as gone
        if decision is None:
            self._coordinator_lost("disconnected")
        if isinstance(decision, dict) and "__fail__" in decision:
            f = decision["__fail__"]
            at = f" (last alive at tick {f['tick']})" if f["tick"] is not None else ""
            raise OtherWorkerError(
                f"cluster process {f['process_id']} failed: {f['reason']}{at}",
                process_id=f["process_id"],
                tick=f["tick"],
                reason=f["reason"],
            )
        return decision

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _LocalWorker:
    def __init__(self, global_index: int, graph):
        self.index = global_index
        self.graph = graph
        self.lock = threading.Lock()
        # fused-chain sweep plan (exchange-aware: see parallel/sharded.py)
        self.plan = _fusion.build_plan(graph, exchange_aware=True)
        #: dirty step positions, guarded by ``lock`` (marks arrive from peer
        #: link reader threads and sibling worker threads)
        self.dirty: set[int] = set()
        #: the active sweep's forward-insertion heap (own thread only)
        self.sweep_heap: list[int] | None = None

    def mark_dirty_locked(self, node_index: int) -> None:
        # no-op in legacy (PATHWAY_FUSE=off) mode: the full scan finds work
        if self.plan is not None:
            self.dirty.add(self.plan.pos_of[node_index])


class ClusterRuntime:
    """Sharded runtime spanning multiple processes.

    Worker ``w``'s graph exists only on its owning process; routing resolves the
    target worker by shard, then delivers locally or over the peer link. Every
    process must execute the same program (same logical graph), like the
    reference's per-worker ``logic`` closure.
    """

    def __init__(
        self,
        monitoring_level: Any = None,
        autocommit_duration_ms: int | None = 20,
    ):
        threads, processes, pid, first_port = cluster_env()
        self.threads = threads
        self.n_proc = processes
        self.pid = pid
        self.first_port = first_port
        self.n_workers = threads * processes
        self.autocommit_duration_ms = autocommit_duration_ms
        self.monitoring_level = monitoring_level
        self.connectors: list[Any] = []
        self.persistence: Any = None
        self.on_tick_done: list[Any] = []
        self._stop_requested = False
        # elasticity plane (PATHWAY_ELASTIC): set when the continuation
        # barrier broadcast carries a rescale decision — the pod quiesces to
        # one final committed epoch and exits with the rescale status
        self._rescale_decision: dict | None = None
        self.streaming = False  # set after build (see engine.runtime.Runtime)
        self.current_time = 0
        # arrival-driven tick scheduling: the coordinator (pid 0) owns the
        # inter-tick sleep, so REST wakeups there drive the whole pod
        from pathway_tpu.engine.runtime import TickWakeup

        self.wakeup = TickWakeup()
        # shard-map plane (PATHWAY_SHARDMAP): the versioned ownership table
        # every placement decision consults; None keeps the derived modulo
        # rule. Set in run() after the elastic plane installs (the map's
        # version rides the membership version).
        self.shardmap = None
        self._shardmap_prev = None
        # live tracing (observability): installed in run(), None when off
        self.tracer = None
        self._trace_active = False
        # request-scoped tracing: the plane while a request is in flight this
        # tick, else None (see engine.graph.Scheduler)
        self._rp = None
        self.local_workers: dict[int, _LocalWorker] = {}
        # intra-process rows ride the local mesh; cross-process rows take the
        # TCP links (the ICI/DCN split — see parallel/device_plane.py)
        from pathway_tpu.parallel.device_plane import make_cluster_device_plane

        self.device_plane = make_cluster_device_plane(self.n_workers, threads, pid)
        self.links = _PeerLinks(pid, processes, first_port, self._on_remote_block)
        # failure detection (resilience subsystem): a dedicated heartbeat link
        # per peer on port first_port + processes + 1; with the serving
        # fabric on, per-process fabric transports follow at
        # first_port + processes + 2 + pid — the cluster occupies
        # [first_port, first_port + 2*processes + 1]
        cfg = get_pathway_config()
        self.hb_monitor = None
        self.hb_client = None
        if processes > 1 and cfg.heartbeat_interval > 0:
            from pathway_tpu.resilience.heartbeat import (
                HeartbeatClient,
                HeartbeatMonitor,
            )

            hb_port = first_port + processes + 1
            if pid == 0:
                self.hb_monitor = HeartbeatMonitor(
                    processes, hb_port, timeout=cfg.heartbeat_timeout
                )
            else:
                self.hb_client = HeartbeatClient(
                    pid, hb_port, cfg.heartbeat_interval
                )
        if pid == 0:
            self.coord = _Coordinator(processes, first_port, monitor=self.hb_monitor)
        else:
            self.coord = None
        self.client = None  # set in run()

    # ------------------------------------------------------------------ build
    def owner_of(self, worker: int) -> int:
        return worker // self.threads

    def register_connector(self, driver) -> None:
        self.connectors.append(driver)

    def request_stop(self) -> None:
        self._stop_requested = True

    def _build(self, outputs: list[LogicalNode]) -> None:
        my_workers = range(self.pid * self.threads, (self.pid + 1) * self.threads)
        # build in reverse so global worker 0 (on process 0) builds LAST — its
        # nodes must own any shared holders (connector subjects, rest servers)
        for w in sorted(my_workers, reverse=True):
            ctx = BuildContext(
                runtime=self if w == 0 else None,
                worker_index=w,
                n_workers=self.n_workers,
                register=self.register_connector,
                shared_runtime=self,
            )
            for out in outputs:
                ctx.resolve(out)
            if w == 0:
                ctx.finish()
                self._ctx0 = ctx
            self._ctx_local = ctx  # any local context (non-0 processes have no
            # global worker 0; persistence reads only the graph shape from it)
            self.local_workers[w] = _LocalWorker(w, ctx.graph)

    # ---------------------------------------------------------------- routing
    def _on_remote_block(self, worker: int, node_index: int, port: int, batch: DeltaBatch) -> None:
        lw = self.local_workers[worker]
        with lw.lock:
            lw.graph.nodes[node_index].accept(port, batch)
            lw.mark_dirty_locked(node_index)

    def _deliver(self, worker: int, node_index: int, port: int, batch: DeltaBatch) -> None:
        owner = self.owner_of(worker)
        if owner == self.pid:
            lw = self.local_workers[worker]
            with lw.lock:
                lw.graph.nodes[node_index].accept(port, batch)
                lw.mark_dirty_locked(node_index)
        else:
            self.links.send_block(owner, worker, node_index, port, batch)

    def _accept_local(self, lw: _LocalWorker, ci: int, port: int, batch) -> None:
        """Same-worker accept from the worker's own thread (see
        parallel/sharded.py: a mid-sweep mark rides the active heap)."""
        lw.graph.nodes[ci].accept(port, batch)
        if lw.plan is None:
            return  # legacy mode: the full scan finds it
        h = lw.sweep_heap
        if h is not None:
            heapq.heappush(h, lw.plan.pos_of[ci])
        else:
            with lw.lock:
                lw.mark_dirty_locked(ci)

    def _route(self, lw: _LocalWorker, producer: Node, batches: list[DeltaBatch]) -> bool:
        routed = False
        consumers = lw.graph.edges.get(producer.node_index, [])
        for batch in batches:
            if batch is None or batch.is_empty:
                continue
            producer.stats_rows_out += len(batch)
            for ci, port in consumers:
                consumer = lw.graph.nodes[ci]
                key_fn = consumer.exchange_key(port)
                if key_fn is None:
                    self._accept_local(lw, ci, port, batch)
                elif key_fn == SOLO:
                    self._deliver(0, ci, port, batch)
                elif key_fn == BROADCAST:
                    for w_idx in range(self.n_workers):
                        self._deliver(w_idx, ci, port, batch)
                else:
                    route_keys = np.asarray(key_fn(batch), dtype=np.uint64)
                    if (
                        self.device_plane is not None
                        and self.device_plane.should_stage(batch)
                    ):
                        self.device_plane.stage(
                            ci, port, lw.index, route_keys, batch
                        )
                        routed = True
                        continue
                    shards = shard_of_keys(
                        route_keys, self.n_workers, shard_map=self.shardmap
                    )
                    for w_idx in np.unique(shards):
                        piece = batch.take(np.flatnonzero(shards == w_idx))
                        self._deliver(int(w_idx), ci, port, piece)
                routed = True
        return routed

    # ---------------------------------------------------------------- ticking
    def _sweep_worker_legacy(self, lw: _LocalWorker, time: int) -> bool:
        """The r14 per-worker sweep, verbatim (PATHWAY_FUSE=off)."""
        any_work = False
        trace = self._trace_active
        rp = self._rp
        aud = _audit.current()
        aud_note = aud is not None and aud.edge_sampled
        for node in lw.graph.nodes:
            with lw.lock:
                if not node.has_pending():
                    continue
                inputs = node.drain()
            rows_in = sum(len(b) for b in inputs if b is not None)
            node.stats_rows_in += rows_in
            if trace or rp is not None:
                from pathway_tpu.observability import device as _dev_prof

                w0 = _time.time_ns()
                dev0 = _dev_prof.thread_device_wait_ns() if trace else 0
            out = run_annotated(node, node.process, inputs, time)
            if trace or rp is not None:
                w1 = _time.time_ns()
                if rp is not None and (
                    rows_in
                    or any(b is not None and not b.is_empty for b in out)
                ):
                    # a no-op visit (nothing drained, nothing emitted) touched
                    # no request's rows — don't spend the per-tick ring budget
                    rp.note_stage(time, f"sweep/{node.name}", w0, w1, rows_in)
            if trace:
                dev_ns = _dev_prof.thread_device_wait_ns() - dev0
                self.tracer.span(
                    f"sweep/{node.name}",
                    w0,
                    w1,
                    {
                        "pathway.operator.id": node.node_index,
                        "pathway.worker": lw.index,
                        "pathway.rows_in": rows_in,
                        "pathway.device_ms": round(dev_ns / 1e6, 3),
                    },
                )
                if dev_ns:
                    _dev_prof.stats().note_span_split(
                        f"sweep/{node.name}", max(0, w1 - w0 - dev_ns), dev_ns
                    )
            if aud_note:
                aud.note_edge(node, inputs, out)
            self._route(lw, node, out)
            any_work = True
        return any_work

    def _sweep_worker(self, lw: _LocalWorker, time: int) -> bool:
        if lw.plan is None:
            return self._sweep_worker_legacy(lw, time)
        with lw.lock:
            if not lw.dirty:
                return False
            heap = sorted(lw.dirty)
            lw.dirty.clear()
        lw.sweep_heap = heap
        any_work = False
        trace = self._trace_active
        rp = self._rp
        aud = _audit.current()
        aud_note = aud is not None and aud.edge_sampled
        by_pos = lw.plan.by_pos
        last = -1
        try:
            while heap:
                pos = heapq.heappop(heap)
                if pos == last:
                    continue
                last = pos
                step = by_pos[pos]
                chain = step.chain
                if chain is not None:
                    if self._run_chain(lw, chain, time, trace, aud if aud_note else None):
                        any_work = True
                    continue
                node = step.node
                with lw.lock:
                    if not node.has_pending():
                        continue
                    inputs = node.drain()
                rows_in = sum(len(b) for b in inputs if b is not None)
                node.stats_rows_in += rows_in
                if trace or rp is not None:
                    from pathway_tpu.observability import device as _dev_prof

                    w0 = _time.time_ns()
                    dev0 = _dev_prof.thread_device_wait_ns() if trace else 0
                out = run_annotated(node, node.process, inputs, time)
                if trace or rp is not None:
                    w1 = _time.time_ns()
                    if rp is not None and (
                        rows_in
                        or any(b is not None and not b.is_empty for b in out)
                    ):
                        # a no-op visit (nothing drained, nothing emitted) touched
                        # no request's rows — don't spend the per-tick ring budget
                        rp.note_stage(time, f"sweep/{node.name}", w0, w1, rows_in)
                if trace:
                    dev_ns = _dev_prof.thread_device_wait_ns() - dev0
                    self.tracer.span(
                        f"sweep/{node.name}",
                        w0,
                        w1,
                        {
                            "pathway.operator.id": node.node_index,
                            "pathway.worker": lw.index,
                            "pathway.rows_in": rows_in,
                            "pathway.device_ms": round(dev_ns / 1e6, 3),
                        },
                    )
                    if dev_ns:
                        _dev_prof.stats().note_span_split(
                            f"sweep/{node.name}", max(0, w1 - w0 - dev_ns), dev_ns
                        )
                if aud_note:
                    aud.note_edge(node, inputs, out)
                self._route(lw, node, out)
                any_work = True
        finally:
            lw.sweep_heap = None
        return any_work

    def _run_chain(self, lw: _LocalWorker, chain, time: int, trace: bool, aud) -> bool:
        """One fused-chain step (see Scheduler._run_chain: per-chain span,
        device wait and traced-jit cold walls subtracted from host share)."""
        from pathway_tpu.observability import device as _dev_prof

        rp = self._rp
        if trace or rp is not None:
            w0 = _time.time_ns()
            dev0 = _dev_prof.thread_device_wait_ns() if trace else 0
            cold0 = _dev_prof.thread_cold_s() if trace else 0.0
        t0 = _time.perf_counter_ns()
        tok = _phases.start()
        try:
            out, processed, rows_in, rows_out = chain.execute(time, lw.lock, aud)
        finally:
            _phases.stop(tok, "fused")
        if not processed:
            return False
        elapsed_ns = _time.perf_counter_ns() - t0
        chain.tail.stats_time_ns += elapsed_ns
        if rp is not None:
            rp.note_stage(
                time, f"sweep/chain{{{chain.label}}}", w0, _time.time_ns(), rows_in
            )
        if trace:
            w1 = _time.time_ns()
            dev_ns = _dev_prof.thread_device_wait_ns() - dev0
            cold_ns = int((_dev_prof.thread_cold_s() - cold0) * 1e9)
            name = f"sweep/chain{{{chain.label}}}"
            attrs = {
                "pathway.operator.id": chain.operator_ids(),
                "pathway.worker": lw.index,
                "pathway.chain.nodes": len(chain.members),
                "pathway.rows_in": rows_in,
                "pathway.rows_out": rows_out,
                "pathway.device_ms": round(dev_ns / 1e6, 3),
            }
            if cold_ns:
                attrs["pathway.compile_ms"] = round(cold_ns / 1e6, 3)
            self.tracer.span(name, w0, w1, attrs)
            if dev_ns:
                _dev_prof.stats().note_span_split(
                    name, max(0, elapsed_ns - dev_ns - cold_ns), dev_ns
                )
        self._route(lw, chain.tail, out)
        return True

    def _sweep_all_local(self, time: int) -> bool:
        workers = list(self.local_workers.values())
        if len(workers) == 1:
            did = False
            while self._sweep_worker(workers[0], time):
                did = True
            return did
        did_any = False
        while True:
            results = [False] * len(workers)
            threads = []
            for i, lw in enumerate(workers):
                def target(i=i, lw=lw):
                    results[i] = self._sweep_worker(lw, time)

                t = threading.Thread(target=target)
                t.start()
                threads.append(t)
            for t in threads:
                t.join()
            if not any(results):
                return did_any
            did_any = True

    def _barrier(self, report: Any, decide) -> Any:
        _faults.before_barrier(self.pid, self.current_time)
        phase = report[0] if isinstance(report, tuple) and report else "barrier"
        # request-trace piggyback: peers ship their stage-event outbox on
        # barrier reports; the coordinator merges them and broadcasts the
        # live-request table with the decision — one request's flight path
        # stitches across processes with zero extra sockets or rounds. Both
        # directions are pay-as-you-go: an empty outbox ships nothing, and
        # the broadcast rides only while requests are live (one trailing
        # empty broadcast clears peers), so a cluster job with no traffic
        # adds no barrier payload at all
        rp = _requests.current()
        if rp is not None:
            outbox = rp.wire_out()
            if outbox is not None:
                report = ("__rt__", report, outbox)
            if self.pid == 0 and decide is not None:
                inner_decide = decide

                def decide(reports, _inner=inner_decide, _rp=rp):
                    # unwrap is per-report and tag-based: wrapped and bare
                    # reports mix freely (peers wrap only when shipping)
                    base = []
                    for r in reports:
                        if (
                            isinstance(r, tuple)
                            and len(r) == 3
                            and r[0] == "__rt__"
                        ):
                            _rp.wire_merge(r[2])
                            base.append(r[1])
                        else:
                            base.append(r)
                    d = _inner(base)
                    if isinstance(d, dict):
                        bc = _rp.wire_broadcast()
                        if bc is not None:
                            d = dict(d)
                            d["__rt_bc__"] = bc
                    return d

        if not self._trace_active:
            if self.pid == 0:
                decision = self.coord.barrier(report, decide)
            else:
                decision = self.client.barrier(report)
        else:
            # sampled tick: record the barrier round as a child span — wait
            # time at barriers IS the cluster's skew/critical-path signal
            # (SnailTrail)
            w0 = _time.time_ns()
            if self.pid == 0:
                decision = self.coord.barrier(report, decide)
            else:
                decision = self.client.barrier(report)
            self.tracer.span(
                f"cluster/barrier/{phase}",
                w0,
                _time.time_ns(),
                {"pathway.process_id": self.pid, "pathway.tick": self.current_time},
            )
        if rp is not None and self.pid != 0 and isinstance(decision, dict):
            rp.wire_apply(decision.get("__rt_bc__"))
        return decision

    def _round_until_quiescent(self, time: int, phase: str) -> None:
        """Sweep-report rounds until globally quiescent (no work anywhere and
        all in-flight messages delivered)."""
        while True:
            self.links.check_error()
            did = self._sweep_all_local(time)
            if self.device_plane is not None and self.device_plane.flush(
                self._deliver, time
            ):
                did = True
            sent, received = self.links.counters()
            # pending is read AFTER the counters: a block that lands between
            # sweep and here is visible either as sent>recv or as pending.
            # Pending nodes are re-marked dirty (idempotent) so the plan
            # sweep can never strand a buffered block.
            pending = False
            for lw in self.local_workers.values():
                for node in lw.graph.nodes:
                    if node.has_pending():
                        pending = True
                        with lw.lock:
                            lw.mark_dirty_locked(node.node_index)
            report = (phase, did or pending, sent, received)

            def decide(reports):
                any_work = any(r[1] for r in reports)
                total_sent = sum(r[2] for r in reports)
                total_recv = sum(r[3] for r in reports)
                return {"again": any_work or total_sent != total_recv}

            decision = self._barrier(report, decide)
            if not decision["again"]:
                return

    def _sync_watermarks(self) -> None:
        """Cross-process watermark gossip (the reference's frontier broadcast
        over timely's progress channels): merge every global-watermark node's
        per-process tick maximum so sharded buffer/forget/freeze shards all
        see the GLOBAL clock before releasing/dropping rows. Runs before each
        frontier round — frontier-phase emissions can advance the clock
        mid-tick, and the serial engine would observe those too."""
        local: dict[int, Any] = {}
        wm_nodes = []
        for lw in self.local_workers.values():
            for node in lw.graph.nodes:
                if getattr(node, "global_watermark", False):
                    wm_nodes.append(node)
                    tm = node._shared.tick_max
                    if tm is not None:
                        prev = local.get(node.node_index)
                        if prev is None or tm > prev:
                            local[node.node_index] = tm
        # graphs are aligned across processes, so this skip is symmetric —
        # every process sees the same wm_nodes emptiness and barrier count
        if not wm_nodes:
            return

        def decide(reports):
            merged: dict[int, Any] = {}
            for _tag, wm in reports:
                for idx, tm in wm.items():
                    if idx not in merged or tm > merged[idx]:
                        merged[idx] = tm
            return {"wm": merged}

        decision = self._barrier(("wmsync", local), decide)
        merged = decision["wm"]
        for node in wm_nodes:
            tm = merged.get(node.node_index)
            if tm is not None:
                with node._shared.lock:
                    if node._shared.tick_max is None or tm > node._shared.tick_max:
                        node._shared.tick_max = tm

    def run_tick(self, time: int, skip_poll: bool = False) -> None:
        self.current_time = time
        from pathway_tpu.observability import device as _dev_prof

        _dev_prof.tick_hook(time)
        tracer = self.tracer
        tick_token = tracer.begin_tick(time) if tracer is not None else None
        self._trace_active = tick_token is not None
        rp = _requests.current()
        if rp is not None and (not rp.hot or time == END_OF_STREAM):
            rp = None
        self._rp = rp
        if rp is not None:
            rp.note_tick(time)
        if self.hb_client is not None:
            self.hb_client.tick = time
        # non-partitioned sources poll on global worker 0 only; partitioned
        # sources (local_source, r5) poll on every owning worker — including
        # workers hosted by peer processes. ``skip_poll`` is the drop_poll
        # fault-injection point: buffered events stay upstream for this tick.
        aud = _audit.current()
        if aud is not None:
            aud.begin_tick(time)

        def _polled(node):
            polled = run_annotated(node, node.poll, time)
            if polled:
                # corruption faults (flip_diff/drop_retract) apply before the
                # audit monitors observe, keyed by THIS process id
                polled = _faults.corrupt_polled(self.pid, time, polled)
                if aud is not None:
                    aud.observe_input(node, polled, time)
            return polled

        def _nodes(lw, kind):
            if lw.plan is None:
                return lw.graph.nodes
            return getattr(lw.plan, kind)

        if not skip_poll and 0 in self.local_workers:
            lw0 = self.local_workers[0]
            for node in _nodes(lw0, "pollers"):
                self._route(lw0, node, _polled(node))
        if not skip_poll:
            for gi, lw in self.local_workers.items():
                if gi == 0:
                    continue
                for node in _nodes(lw, "pollers"):
                    if getattr(node, "local_source", False) or getattr(
                        node, "fabric_ingest", False
                    ):
                        # fabric_ingest: zero-hop doors push REST rows into
                        # THIS process's copy of the route input node, so
                        # peers must poll it like a partitioned source
                        self._route(lw, node, _polled(node))
        self._round_until_quiescent(time, "sweep")
        while True:
            self._sync_watermarks()
            progressed = False
            for lw in self.local_workers.values():
                for node in _nodes(lw, "frontier_nodes"):
                    if self._route(lw, node, run_annotated(node, node.on_frontier, time)):
                        progressed = True

            def decide(reports):
                return {"again": any(r[1] for r in reports)}

            decision = self._barrier(("frontier", progressed, 0, 0), decide)
            if not decision["again"]:
                break
            self._round_until_quiescent(time, "sweep")
        for lw in self.local_workers.values():
            for node in _nodes(lw, "tick_complete_nodes"):
                run_annotated(node, node.on_tick_complete, time)
        for cb in self.on_tick_done:
            cb(time)
        if tick_token is not None:
            self._trace_active = False
            tracer.end_tick(time, tick_token)

    def _peer_flows(self) -> dict[int, dict]:
        """pid → flow-plane gate summary from each peer's heartbeats (empty
        when failure detection is off — single-host pressure still applies)."""
        if self.hb_monitor is None:
            return {}
        return self.hb_monitor.peer_flow()

    # ---------------------------------------------------------------- run loop
    def run(self, outputs: list[LogicalNode]):
        from pathway_tpu import elastic as _elastic
        from pathway_tpu import flow as _flow
        from pathway_tpu import observability as _obs

        _faults.install_from_env()
        _obs.install_from_env(self)
        _flow.install_from_env(self)  # before build: gates attach to inputs
        # after persistence attach (pw.run order), so the plane finds the
        # backend the membership table lives in
        _elastic.install_from_env(self)
        eplane = _elastic.current()
        if get_pathway_config().shardmap == "on":
            # shard-map plane: derive (and, coordinator, commit) the versioned
            # ownership table BEFORE build/persistence — restores and door
            # routing both consult it. Derivation is deterministic from the
            # stored map + pod shape, so every process agrees without a
            # barrier; without a backend the equal initial split is used.
            from pathway_tpu.internals import shardmap as _shardmap

            backend = getattr(self.persistence, "backend", None)
            version = (
                eplane.membership.version
                if eplane is not None and eplane.membership is not None
                else 0
            )
            self.shardmap, self._shardmap_prev = _shardmap.ensure_shardmap(
                backend, self.n_workers, version, commit=(self.pid == 0)
            )
            if self.device_plane is not None:
                self.device_plane.shard_map = self.shardmap
        if (
            eplane is not None
            and eplane.membership is not None
            and self.hb_monitor is not None
        ):
            # stale-membership guard: heartbeat summaries stamped with an
            # older membership version (a retired process's last gasp) are
            # rejected instead of polluting the coordinator's merged state
            self.hb_monitor.set_membership_version(eplane.membership.version)
        self.tracer = _obs.current()
        if self.hb_client is not None:
            # telemetry summaries ride the existing heartbeat messages, so the
            # coordinator's /status can show this peer's tick/watermark/backlog
            # (and, flow plane on, its gate occupancy for the credit merge)
            self.hb_client.summary_fn = lambda: _obs.aggregate.local_summary(self)
        try:
            return self._run_inner(outputs)
        except BaseException as e:
            # flight-recorder post-mortem: on an OtherWorkerError the dump
            # names the dead peer and its last known tick (the survivors are
            # where the post-mortem evidence lives — the dead process wrote
            # nothing). A ClusterRescale is a coordinated exit, not a
            # failure — no post-mortem.
            if not isinstance(e, _elastic.ClusterRescale):
                _obs.device.on_run_error(e, self)
            raise
        finally:
            self.tracer = None
            from pathway_tpu import fabric as _fabric

            _fabric.shutdown()
            _obs.shutdown()
            _flow.shutdown()
            _elastic.shutdown()

    def _run_inner(self, outputs: list[LogicalNode]):
        from pathway_tpu import elastic as _elastic
        from pathway_tpu import flow as _flow

        self._build(outputs)
        self.streaming = bool(self.connectors)
        plane = _flow.current()
        eplane = _elastic.current()
        if plane is not None:
            self.on_tick_done.append(lambda t: plane.on_tick_complete(self, t))
        if self.pid == 0:
            self.coord.wait_connections()
        else:
            self.client = _CoordinatorClient(
                self.pid, self.first_port, hb_client=self.hb_client
            )
        if self.persistence is not None:
            # every process participates: input snapshots live with the
            # sources on process 0, peers persist their own partitioned source
            # slices, operator mode additionally snapshots/restores every
            # process's worker shards, and the per-tick epoch barrier commits
            # a global manifest (barrier-coordinated, see snapshots.py) — so
            # the hooks must run in lockstep on ALL processes
            self.persistence.on_graph_built(getattr(self, "_ctx0", self._ctx_local))
            self.on_tick_done.append(self.persistence.on_tick_done)
        # every process starts ITS OWN connectors: process 0 owns the
        # non-partitioned sources, peers own their workers' partition slices
        for driver in self.connectors:
            driver.start()
        # serving fabric (PATHWAY_FABRIC=on): AFTER connectors — the owner's
        # webserver and route states are live — and BEFORE the first tick, so
        # every peer's transport is accepting before the owner's first
        # replica cast (the startup barrier orders the two)
        from pathway_tpu import fabric as _fabric

        fplane = _fabric.install_from_env(self)
        if fplane is not None:
            self.on_tick_done.append(fplane.on_tick_done)
        # connectors live + fabric doors accepting: this door is ready
        # (health plane: starting → ready; a replica resync will demote it
        # to syncing until the gap closes)
        from pathway_tpu.observability import health as _health

        _health.mark_ready()

        period = (self.autocommit_duration_ms or 20) / 1000.0
        tick = 0
        try:
            while True:
                t0 = _time.perf_counter()
                drop_poll = _faults.on_tick_start(self.pid, tick)
                self.run_tick(tick, skip_poll=drop_poll)
                tick += 1
                from pathway_tpu.engine.runtime import check_connector_failures

                check_connector_failures(self.connectors)
                # continuation: done when EVERY process's sources are
                # exhausted (partitioned ingest spreads sources across
                # processes) — or when ANY process requested a stop (streaming
                # subjects never self-finish, so the stop flag must propagate
                # to peers through the barrier, not wait on their is_finished)
                local_done = all(d.is_finished() for d in self.connectors)
                report = ("cont", local_done, self._stop_requested, 0)
                if self.pid == 0:
                    all_virtual = not self.connectors or all(
                        getattr(d, "virtual", False) for d in self.connectors
                    )

                    def decide(reports, _tick=tick):
                        d = {
                            "done": any(r[2] for r in reports)
                            or all(r[1] for r in reports)
                        }
                        if plane is not None:
                            # cluster credit propagation: merge every peer's
                            # heartbeat-piggybacked gate occupancy into one
                            # pod-wide pressure and broadcast it with the
                            # continue decision — a slow peer throttles every
                            # producer instead of OOMing one host
                            d["flow"] = plane.cluster_signal(self._peer_flows())
                        if eplane is not None and not d["done"]:
                            # elasticity: manual scale requests + the
                            # autoscaler consult here, fed the SAME merged
                            # pod pressure the flow broadcast carries; a
                            # decision rides the continue verdict so every
                            # process quiesces at the same tick boundary
                            resc = eplane.maybe_decide(
                                self,
                                _tick,
                                (d.get("flow") or {}).get("pressure"),
                            )
                            if resc is not None:
                                d["rescale"] = resc
                        return d

                    decision = self.coord.barrier(report, decide)
                else:
                    decision = self.client.barrier(report)
                    all_virtual = True
                if plane is not None:
                    plane.apply_cluster_signal(decision.get("flow"))
                resc = decision.get("rescale")
                if resc is not None:
                    # readiness before the pause: every door flips to
                    # draining (503 + Retry-After on /readyz) BEFORE the
                    # quiesce drain tick, so a load balancer stops sending
                    # traffic into the rescale window
                    self._rescale_decision = resc
                    _health.mark_draining("rescale")
                if decision["done"] or resc is not None:
                    if decision["done"]:
                        _health.mark_draining("shutdown")
                    self.run_tick(tick)  # drain final events
                    break
                if self.pid == 0 and self.connectors and not all_virtual:
                    elapsed = _time.perf_counter() - t0
                    if elapsed < period:
                        self.wakeup.wait(period - elapsed)
        finally:
            for driver in self.connectors:
                driver.stop()
        # re-check: a subject may error between the in-loop check and the
        # is_finished break (see engine.runtime.Runtime.run)
        from pathway_tpu.engine.runtime import check_connector_failures

        check_connector_failures(self.connectors)
        self.close()
        if self._rescale_decision is not None:
            # the pod is quiesced and its final epoch is committed (close()
            # ran the coordinated at-close snapshot): publish the new
            # membership version and leave with the rescale status so a
            # Supervisor relaunches the cluster at the new shape
            if eplane is not None:
                eplane.finalize_rescale(self, self._rescale_decision)
            raise _elastic.ClusterRescale(  # peers without a plane still exit 75
                int(self._rescale_decision["target"]),
                int(self._rescale_decision["version"]),
                str(self._rescale_decision["reason"]),
            )
        return self

    def close(self) -> None:
        self.run_tick(END_OF_STREAM)
        for lw in self.local_workers.values():
            for node in lw.graph.nodes:
                node.on_end()
        if self.persistence is not None:
            self.persistence.on_close()
        # heartbeats outlive the last persistence barrier (a peer dying inside
        # on_close must still be detected); the goodbye marks this exit clean
        if self.hb_client is not None:
            self.hb_client.goodbye()
        if self.hb_monitor is not None:
            self.hb_monitor.close()
        if self.client is not None:
            self.client.close()
        if self.coord is not None:
            self.coord.close()
        self.links.close()

    @property
    def scheduler(self):
        return self
