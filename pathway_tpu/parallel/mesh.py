"""Mesh + distributed initialization helpers.

Replaces the reference's process wiring (``PATHWAY_PROCESSES``/``PATHWAY_PROCESS_ID``
/``PATHWAY_FIRST_PORT`` → timely ``CommunicationConfig::Cluster`` over TCP,
``src/engine/dataflow/config.rs:63-120``) with the JAX-native equivalents: the
``jax.distributed`` coordinator for multi-host process groups and
``jax.sharding.Mesh`` over the visible device pool for on-device collectives.
"""

from __future__ import annotations

import os

import numpy as np

from pathway_tpu.internals.keys import SHARD_MASK  # noqa: F401  (re-export)
from pathway_tpu.internals.keys import shard_of_keys as _shard_of_keys


def shard_of_keys(keys: np.ndarray, n_shards: int, shard_map=None) -> np.ndarray:
    """Worker assignment for row keys — delegates to the single authority in
    ``internals/keys.shard_of_keys`` (low shard bits modulo the worker count,
    reference ``shard.rs:15-20``; or the versioned shard map's segment table
    when one is active, see ``internals/shardmap``)."""
    return _shard_of_keys(keys, n_shards, shard_map=shard_map)


def distributed_initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize jax.distributed from args or PATHWAY_* env (no-op single-proc).

    Env: ``PATHWAY_PROCESSES`` (world size), ``PATHWAY_PROCESS_ID`` (rank),
    ``PATHWAY_COORDINATOR`` (host:port; default localhost:FIRST_PORT).
    """
    import jax

    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    num_processes = num_processes or cfg.processes
    if num_processes <= 1:
        return
    process_id = process_id if process_id is not None else cfg.process_id
    coordinator_address = coordinator_address or os.environ.get(
        "PATHWAY_COORDINATOR", f"127.0.0.1:{cfg.first_port}"
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def device_mesh(axis_shapes: dict[str, int] | None = None, devices=None):
    """Build a named Mesh over the (global) device pool.

    Default: 1-D ``("data",)`` mesh over all devices. Pass e.g.
    ``{"data": 4, "model": 2}`` for a 2-D dp×tp layout.
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    if not axis_shapes:
        return Mesh(np.array(devices), ("data",))
    names = tuple(axis_shapes.keys())
    shape = tuple(axis_shapes.values())
    n = int(np.prod(shape))
    if n != len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    return Mesh(np.array(devices).reshape(shape), names)
