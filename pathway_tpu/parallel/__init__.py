"""Distribution: device meshes, multi-worker sharded execution, spawn CLI.

The reference distributes by running the identical dataflow on every worker and
exchanging records by key shard (``src/engine/dataflow/shard.rs``; timely's
communication crate over shared memory/TCP, SURVEY §5.8). Here:

- :mod:`pathway_tpu.parallel.mesh` — ``jax.sharding.Mesh`` construction and
  ``jax.distributed`` initialization from ``PATHWAY_PROCESSES/PROCESS_ID`` env
  (the coordinator replaces ``PATHWAY_FIRST_PORT`` TCP wiring).
- :mod:`pathway_tpu.parallel.sharded` — the multi-worker engine runtime: every
  worker builds the identical engine graph; each node declares its partitioning
  contract (``Node.exchange_key``); blocks are split by key shard and routed to
  the owning worker at exchange edges; ticks advance in lockstep (the global
  frontier). Device compute inside nodes (einsums, jitted UDF batches) is where
  the FLOPs live — workers parallelize the host-side state machinery.
"""

from pathway_tpu.parallel.mesh import (
    device_mesh,
    distributed_initialize,
    shard_of_keys,
)
from pathway_tpu.parallel.sharded import ShardedRuntime

__all__ = [
    "ShardedRuntime",
    "device_mesh",
    "distributed_initialize",
    "shard_of_keys",
]
