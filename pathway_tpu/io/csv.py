"""CSV connector (reference: ``python/pathway/io/csv``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs


def read(path: str, *, schema=None, mode: str = "streaming", **kwargs: Any):
    return fs.read(path, format="csv", schema=schema, mode=mode, **kwargs)


def write(table, filename: str, **kwargs: Any) -> None:
    fs.write(table, filename, format="csv", **kwargs)
