"""Elasticsearch writer (reference: ``ElasticSearchWriter``
``src/connectors/data_storage.rs:1479``). Each positive diff indexes the row as a
JSON document; retractions delete by id. Requires the ``elasticsearch`` client
(not in this image; import-gated)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import _plain


def write(
    table: Table,
    host: str,
    auth: Any = None,
    index_name: str = "pathway",
    **kwargs: Any,
) -> None:
    try:
        from elasticsearch import Elasticsearch
    except ImportError:
        raise NotImplementedError(
            "pw.io.elasticsearch requires the elasticsearch client, which is not "
            "available in this environment"
        ) from None

    client = Elasticsearch(host, basic_auth=auth, **kwargs.get("client_kwargs", {}))
    cols = table.column_names()

    def on_batch(batch, columns) -> None:
        for key, diff, row in batch.rows():
            doc_id = str(int(key))
            if diff > 0:
                client.index(
                    index=index_name,
                    id=doc_id,
                    document={c: _plain(v) for c, v in zip(columns, row)},
                )
            else:
                client.delete(index=index_name, id=doc_id, ignore=[404])

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=f"elasticsearch_write:{index_name}",
    )._register_as_output()
