"""Airbyte connector — the serverless execution path (reference
``python/pathway/io/airbyte`` + vendored ``third_party/airbyte_serverless``).

The reference runs an Airbyte source connector program (docker image or
installed venv) and parses its stdout: JSON-lines Airbyte-protocol messages
(``CATALOG``/``RECORD``/``STATE``/``LOG``). Docker is genuinely unavailable
on this image, so that execution type gates — but the SERVERLESS path is
real: ``ExecutableRunner`` spawns any local command implementing the
protocol (``<argv> discover --config …`` / ``<argv> read --config
--catalog [--state]``, the same contract ``airbyte_serverless``'s
``executable_runner.py`` drives inside its containers) and the connector's
records stream into the table. A custom ``runner=`` injects the transport
for tests; ``tests/test_airbyte.py`` also exercises the real subprocess
path with a protocol-speaking Python connector.

Result schema matches the reference: one ``data`` JSON column per record
(``_AirbyteRecordSchema``). ``STATE`` messages checkpoint the source: the
latest state persists with the input offsets and hands back to the
connector on restart (incremental sync resume).
"""

from __future__ import annotations

import json as _json
import os
import subprocess
import sys
import tempfile
import time as _time
from typing import Any, Sequence

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table

FULL_REFRESH_SYNC_MODE = "full_refresh"
INCREMENTAL_SYNC_MODE = "incremental"


def _load_connection(config: Any) -> dict:
    """Accept a dict, a YAML/JSON file path, or YAML text (the
    ``abs create``-style connection document: ``{source: {…}}``)."""
    if isinstance(config, dict):
        doc = config
    else:
        if isinstance(config, os.PathLike) or (
            isinstance(config, str) and "\n" not in config
        ):
            # a path-shaped argument must BE a file — feeding a typo'd path
            # through the YAML parser would yield a baffling 'str has no
            # attribute get' instead of file-not-found
            if not os.path.exists(config):
                raise FileNotFoundError(
                    f"airbyte connection config file not found: {config!r}"
                )
            with open(config, encoding="utf-8") as fh:
                text = fh.read()
        elif isinstance(config, str):
            text = config  # inline YAML/JSON document
        else:
            raise ValueError(f"unsupported airbyte config: {config!r}")
        try:
            import yaml

            doc = yaml.safe_load(text)
        except ImportError:
            doc = _json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError(f"airbyte connection config is not a mapping: {config!r}")
    return doc.get("source", doc)


class ExecutableRunner:
    """Run a local Airbyte connector command (the serverless venv mode):
    ``argv spec|discover|read`` with ``--config``/``--catalog``/``--state``
    temp files, stdout parsed as protocol JSON lines."""

    def __init__(self, argv: Sequence[str], env: dict | None = None, timeout: float = 300.0):
        self.argv = list(argv)
        self.env = env
        self.timeout = timeout

    def _run(self, args: list[str]) -> list[dict]:
        env = dict(os.environ, **(self.env or {}))
        proc = subprocess.run(
            self.argv + args,
            capture_output=True,
            text=True,
            timeout=self.timeout,
            env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"airbyte connector {self.argv} failed "
                f"({proc.returncode}): {(proc.stderr or proc.stdout)[-500:]}"
            )
        messages = []
        for line in proc.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                messages.append(_json.loads(line))
            except ValueError:
                continue  # connectors may print non-protocol noise
        return messages

    def discover(self, config: dict) -> list[dict]:
        with tempfile.TemporaryDirectory() as td:
            cfg = os.path.join(td, "config.json")
            with open(cfg, "w") as fh:
                _json.dump(config, fh)
            for m in self._run(["discover", "--config", cfg]):
                if m.get("type") == "CATALOG":
                    return m["catalog"]["streams"]
        raise RuntimeError("airbyte connector produced no CATALOG message")

    def read(self, config: dict, catalog: dict, state: Any = None) -> list[dict]:
        with tempfile.TemporaryDirectory() as td:
            cfg = os.path.join(td, "config.json")
            cat = os.path.join(td, "catalog.json")
            with open(cfg, "w") as fh:
                _json.dump(config, fh)
            with open(cat, "w") as fh:
                _json.dump(catalog, fh)
            args = ["read", "--config", cfg, "--catalog", cat]
            if state is not None:
                st = os.path.join(td, "state.json")
                with open(st, "w") as fh:
                    _json.dump(state, fh)
                args += ["--state", st]
            return self._run(args)


def _configured_catalog(streams_meta: list[dict], streams: Sequence[str]) -> dict:
    available = {s["name"]: s for s in streams_meta}
    missing = [s for s in streams if s not in available]
    if missing:
        raise ValueError(
            f"airbyte streams not found: {missing}; available: {sorted(available)}"
        )
    configured = []
    for name in streams:
        meta = available[name]
        modes = meta.get("supported_sync_modes", [FULL_REFRESH_SYNC_MODE])
        sync_mode = (
            INCREMENTAL_SYNC_MODE
            if INCREMENTAL_SYNC_MODE in modes
            else FULL_REFRESH_SYNC_MODE
        )
        configured.append(
            {
                "stream": meta,
                "sync_mode": sync_mode,
                "destination_sync_mode": "append",
            }
        )
    return {"streams": configured}


def read(
    config: Any,
    streams: Sequence[str],
    *,
    mode: str = "streaming",
    execution_type: str = "local",
    refresh_interval_ms: int = 60000,
    runner: Any = None,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read Airbyte streams into a table of ``data`` JSON records."""
    if mode not in ("streaming", "static"):
        raise ValueError(f"unknown airbyte mode {mode!r}")
    if execution_type not in ("local", "remote"):
        raise ValueError(f"unknown airbyte execution_type {execution_type!r}")
    unknown = [k for k in kwargs if not k.startswith("_")]
    if unknown:
        raise TypeError(f"pw.io.airbyte.read: unknown options {unknown}")
    source = _load_connection(config)
    source_config = source.get("config", {})
    if runner is None:
        if execution_type == "remote":
            raise NotImplementedError(
                "pw.io.airbyte execution_type='remote' needs a cloud runner "
                "not available in this environment"
            )
        executable = source.get("executable")
        if executable:
            argv = executable if isinstance(executable, list) else [executable]
            # connectors shipped as python scripts run under this interpreter
            if len(argv) == 1 and str(argv[0]).endswith(".py"):
                argv = [sys.executable, argv[0]]
            runner = ExecutableRunner(argv, env=source.get("env"))
        elif source.get("docker_image"):
            raise NotImplementedError(
                "pw.io.airbyte docker execution requires docker, which is not "
                "available in this environment; ship the connector as a local "
                "executable (source.executable) or inject runner="
            )
        else:
            raise ValueError(
                "airbyte source config needs 'executable' (serverless local "
                "run) or 'docker_image'"
            )

    from pathway_tpu.internals.json import Json
    from pathway_tpu.internals.keys import stable_hash_obj
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    schema = schema_mod.schema_from_types(data=dict)
    selected = list(streams)
    poll_s = kwargs.get("_poll_interval", refresh_interval_ms / 1000.0)

    class _AirbyteSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._stop = False
            # per-stream STATE registry (ADVICE r5): Airbyte emits one STATE
            # message per stream descriptor (type STREAM), or a single GLOBAL /
            # LEGACY document. Keeping only the LAST message dropped every
            # other stream's cursor, so a multi-stream incremental sync
            # re-synced all but one stream from scratch on the next read.
            # Keyed by descriptor; the merged document hands back on read.
            self._states: dict[tuple, Any] = {}
            # live keys of full-refresh streams from the previous poll — a
            # re-read that no longer contains a key retracts it (upstream
            # deletion); incremental streams are append-only
            self._fr_live: set[int] = set()

        def _on_state(self, state: Any) -> None:
            stype = state.get("type") if isinstance(state, dict) else None
            if stype == "STREAM":
                desc = (state.get("stream") or {}).get("stream_descriptor") or {}
                key = ("STREAM", desc.get("name"), desc.get("namespace"))
            elif stype == "GLOBAL":
                key = ("GLOBAL", None, None)
            else:  # legacy state blob ({"data": …} or a bare cursor document)
                key = ("LEGACY", None, None)
            self._states[key] = state

        def _merged_state(self) -> Any:
            """The state document for the next ``read``: a list of
            AirbyteStateMessages (per-stream / global), or — for legacy-only
            connectors — the bare legacy blob, matching what they emitted."""
            if not self._states:
                return None
            if set(self._states) == {("LEGACY", None, None)}:
                legacy = self._states[("LEGACY", None, None)]
                if isinstance(legacy, dict) and "data" in legacy:
                    return legacy["data"]
                return legacy
            return [self._states[k] for k in sorted(self._states, key=str)]

        def run(self) -> None:
            import warnings

            catalog = _configured_catalog(runner.discover(source_config), selected)
            full_refresh = {
                s["stream"]["name"]
                for s in catalog["streams"]
                if s["sync_mode"] == FULL_REFRESH_SYNC_MODE
            }
            while not self._stop:
                try:
                    messages = runner.read(source_config, catalog, self._merged_state())
                except Exception as e:  # noqa: BLE001 — transient connector errors retry
                    if mode == "static":
                        raise
                    warnings.warn(
                        f"airbyte read failed ({e!r}); retrying in {poll_s}s",
                        stacklevel=2,
                    )
                    _time.sleep(poll_s)
                    continue
                assert self._node is not None
                events = []
                # duplicate payloads are distinct rows: the key carries an
                # occurrence ordinal per (stream, content) within one read,
                # stable across full-refresh re-reads
                occurrence: dict[tuple, int] = {}
                fr_seen: set[int] = set()
                for m in messages:
                    t = m.get("type")
                    if t == "RECORD":
                        rec = m["record"]
                        stream = rec.get("stream")
                        if stream not in selected:
                            continue
                        payload = rec.get("data", {})
                        ck = (stream, _json.dumps(payload, sort_keys=True))
                        ordinal = occurrence.get(ck, 0)
                        occurrence[ck] = ordinal + 1
                        key = int(stable_hash_obj(("airbyte", *ck, ordinal)))
                        events.append((key, (Json(payload),), 1))
                        if stream in full_refresh:
                            fr_seen.add(key)
                    elif t == "STATE":
                        self._on_state(m.get("state") or {})
                # upstream deletions in full-refresh streams: keys present
                # last poll but absent now retract (upsert session delete)
                if mode == "streaming":
                    for gone in self._fr_live - fr_seen:
                        events.append((gone, None, -1))
                self._fr_live = fr_seen
                self._node.push_many(events)
                if mode == "static":
                    return
                # incremental sources resume from self._state next poll;
                # full-refresh re-reads replace content in place (upsert keys)
                _time.sleep(poll_s)

        @property
        def _session_type(self) -> str:
            # full-refresh polls re-emit the whole stream; upsert semantics
            # (key = stream+content) dedup replays in place
            return "upsert" if mode == "streaming" else "native"

        # persistence contract: the connector's own STATE is the offset;
        # the full-refresh live-key set travels with it so deletions that
        # happen across a restart still retract
        def offset_state(self) -> dict:
            return {
                # the merged doc under the legacy key keeps old snapshots
                # readable; the per-stream registry restores losslessly
                "airbyte_state": self._merged_state(),
                "airbyte_states": dict(self._states),
                "fr_live": sorted(self._fr_live),
                "seq": self._seq,
            }

        def seek(self, state: dict) -> None:
            if "airbyte_states" in state:
                self._states = dict(state["airbyte_states"])
            else:
                # snapshot from before per-stream states: a single opaque doc
                legacy = state.get("airbyte_state")
                self._states = (
                    {("LEGACY", None, None): legacy} if legacy is not None else {}
                )
            self._fr_live = set(state.get("fr_live", []))
            self._seq = int(state.get("seq", 0))

        def on_stop(self) -> None:
            self._stop = True

    return py_read(
        _AirbyteSubject(), schema=schema, name=name or f"airbyte:{','.join(selected)}"
    )
