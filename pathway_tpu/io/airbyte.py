"""Gated connector: reference `python/pathway/io/airbyte`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("airbyte", "Docker or an airbyte-serverless runtime")
