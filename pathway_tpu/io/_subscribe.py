"""``pw.io.subscribe`` (reference: ``python/pathway/io/_subscribe.py``)."""

from __future__ import annotations

from typing import Any, Callable


def subscribe(
    table: Any,
    on_change: Callable,
    on_end: Callable | None = None,
    on_time_end: Callable | None = None,
    *,
    name: str | None = None,
    service_class: str = "interactive",
    route_by: Callable | None = None,
) -> None:
    """Calls ``on_change(key, row, time, is_addition)`` for every change,
    ``on_time_end(time)`` at the end of each logical time, ``on_end()`` on close.

    ``service_class`` scopes the flow plane's latency objective
    (``PATHWAY_LATENCY_SLO_MS``): the AIMD microbatch controller reads the
    end-to-end latency histograms of ``interactive`` sinks only, so a
    ``bulk``-class subscriber (backfill mirror, audit log) never drags the
    bucket size down on behalf of traffic that doesn't care."""
    from pathway_tpu.flow import validate_service_class

    node = table._subscribe_node(
        on_change=on_change,
        on_time_end=on_time_end,
        on_end=on_end,
        service_class=validate_service_class(service_class),
        route_by=route_by,
    )
    node._register_as_output()
