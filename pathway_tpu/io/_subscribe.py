"""``pw.io.subscribe`` (reference: ``python/pathway/io/_subscribe.py``)."""

from __future__ import annotations

from typing import Any, Callable


def subscribe(
    table: Any,
    on_change: Callable,
    on_end: Callable | None = None,
    on_time_end: Callable | None = None,
    *,
    name: str | None = None,
) -> None:
    """Calls ``on_change(key, row, time, is_addition)`` for every change,
    ``on_time_end(time)`` at the end of each logical time, ``on_end()`` on close."""
    node = table._subscribe_node(on_change=on_change, on_time_end=on_time_end, on_end=on_end)
    node._register_as_output()
