"""Gated connector: reference `python/pathway/io/gdrive`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("gdrive", "Google Drive API credentials and network egress")
