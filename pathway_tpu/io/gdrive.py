"""Google Drive connector: polling reader with object cache and deletions
detection (reference ``python/pathway/io/gdrive/__init__.py``, 417 LoC).

The Google API client libraries are not on this image, so the transport is
INJECTABLE (the S3/Kafka fake-client pattern, ``tests/test_gated_connectors.py``):
pass ``client=`` any object exposing the two calls the reference makes —

- ``tree(object_id) -> dict[file_id, meta]`` where meta carries at least
  ``id``, ``name``, ``mimeType``, ``modifiedTime`` and optionally ``size``
  (the reference's ``files().list``/``get`` + folder recursion), and
- ``download(meta) -> bytes | None`` (``get_media`` / ``export_media``).

Without an injected client the module tries the real google libraries and
raises the dependency gate otherwise. Poll-loop semantics mirror the
reference exactly: every ``refresh_interval`` the listing is re-fetched;
new and modified files (by ``modifiedTime``) upsert keyed by file id,
removed files retract (streaming runs use an upsert session; static runs
read one listing and finish). ``object_size_limit`` skips oversized files,
``file_name_pattern`` (glob or list of globs) filters by name.
"""

from __future__ import annotations

import fnmatch
import time as _time
import warnings
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.keys import stable_hash_obj
from pathway_tpu.internals.table import Table
from pathway_tpu.io.python import ConnectorSubject, read as py_read

#: Google-native docs export to Office formats (reference DEFAULT_MIME_TYPE_MAPPING)
DEFAULT_MIME_TYPE_MAPPING: dict[str, str] = {
    "application/vnd.google-apps.document": "application/vnd.openxmlformats-officedocument.wordprocessingml.document",
    "application/vnd.google-apps.spreadsheet": "application/vnd.openxmlformats-officedocument.spreadsheetml.sheet",
    "application/vnd.google-apps.presentation": "application/vnd.openxmlformats-officedocument.presentationml.presentation",  # noqa: E501
}

MIME_TYPE_FOLDER = "application/vnd.google-apps.folder"


def _filter_files(
    files: list[dict],
    object_size_limit: int | None,
    file_name_pattern: list | str | None,
) -> list[dict]:
    out = []
    for f in files:
        if object_size_limit is not None:
            if "size" not in f:
                warnings.warn(
                    f"skipping gdrive object {f.get('name')}: no size (symlink?)",
                    stacklevel=2,
                )
                continue
            if int(f["size"]) > object_size_limit:
                warnings.warn(
                    f"skipping gdrive object {f.get('name')}: size {f['size']} "
                    f"exceeds limit {object_size_limit}",
                    stacklevel=2,
                )
                continue
        if file_name_pattern is not None:
            patterns = (
                [file_name_pattern]
                if isinstance(file_name_pattern, str)
                else list(file_name_pattern)
            )
            if not any(fnmatch.fnmatch(f.get("name", ""), p) for p in patterns):
                continue
        out.append(f)
    return out


def _real_client(credentials_file: str, export_mapping: dict):
    """The actual googleapiclient transport — a dependency gate here."""
    try:
        from google.oauth2.service_account import Credentials as ServiceCredentials
        from googleapiclient.discovery import build
        from googleapiclient.http import MediaIoBaseDownload  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "pw.io.gdrive requires the google-api-python-client libraries (or "
            "an injected client= transport), which are not available in this "
            "environment"
        ) from None

    import io as _io

    creds = ServiceCredentials.from_service_account_file(credentials_file)
    drive = build("drive", "v3", credentials=creds, num_retries=3)

    # explicit fields: Drive v3 partial responses default to id/name/mimeType
    # only — modifiedTime/size are required for change detection + size limits
    file_fields = "id, name, mimeType, modifiedTime, trashed, size"

    class _Client:
        def tree(self, object_id: str) -> dict:
            files: dict[str, dict] = {}

            def ls(fid: str) -> None:
                meta = (
                    drive.files()
                    .get(fileId=fid, fields=file_fields, supportsAllDrives=True)
                    .execute()
                )
                if meta.get("trashed"):
                    return
                if meta.get("mimeType") != MIME_TYPE_FOLDER:
                    files[meta["id"]] = meta
                    return
                page = None
                while True:
                    resp = (
                        drive.files()
                        .list(
                            q=f"'{fid}' in parents and trashed=false",
                            fields=f"nextPageToken, files({file_fields})",
                            supportsAllDrives=True,
                            includeItemsFromAllDrives=True,
                            pageToken=page,
                        )
                        .execute()
                    )
                    for item in resp.get("files", []):
                        if item.get("mimeType") == MIME_TYPE_FOLDER:
                            ls(item["id"])
                        else:
                            files[item["id"]] = item
                    page = resp.get("nextPageToken")
                    if page is None:
                        return

            ls(object_id)
            return files

        def download(self, meta: dict) -> bytes | None:
            from googleapiclient.http import MediaIoBaseDownload

            export_type = export_mapping.get(meta.get("mimeType"))
            if export_type is not None:
                req = drive.files().export_media(
                    fileId=meta["id"], mimeType=export_type
                )
            else:
                req = drive.files().get_media(fileId=meta["id"])
            buf = _io.BytesIO()
            dl = MediaIoBaseDownload(buf, req)
            done = False
            while not done:
                _status, done = dl.next_chunk()
            return buf.getvalue()

    return _Client()


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    object_size_limit: int | None = None,
    refresh_interval: int = 30,
    service_user_credentials_file: str | None = None,
    with_metadata: bool = False,
    file_name_pattern: list | str | None = None,
    client: Any = None,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read a Drive file or folder subtree as a table of ``data: bytes``
    rows (plus ``_metadata`` when requested), keyed by file id — new and
    modified files upsert in place, removals retract (streaming mode)."""
    if mode not in ("streaming", "static"):
        raise ValueError(f"unknown gdrive mode {mode!r}")
    if client is None:
        if service_user_credentials_file is None:
            raise ValueError(
                "pw.io.gdrive.read needs service_user_credentials_file= (real "
                "transport) or client= (injected transport)"
            )
        client = _real_client(service_user_credentials_file, DEFAULT_MIME_TYPE_MAPPING)

    schema = schema_mod.schema_from_types(data=bytes)
    if with_metadata:
        schema = schema | schema_mod.schema_from_types(_metadata=dict)
    poll_interval = kwargs.get("_poll_interval", refresh_interval)

    class _GDriveSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._stop = False
            # object cache: file id -> modifiedTime of the emitted version
            # (mtime only — caching payloads would pin the whole corpus in RAM)
            self._cache: dict[str, str] = {}

        @property
        def _session_type(self) -> str:
            return "upsert" if mode == "streaming" else "native"

        def _key(self, fid: str) -> int:
            return int(stable_hash_obj(("gdrive", fid)))

        def _meta_of(self, meta: dict) -> dict:
            fid = meta.get("id")
            return {
                **{k: v for k, v in meta.items() if k != "parents"},
                "url": f"https://drive.google.com/file/d/{fid}/",
                "path": meta.get("name"),
                "seen_at": int(_time.time()),
                "status": "downloaded",
            }

        def run(self) -> None:
            while not self._stop:
                try:
                    tree = client.tree(object_id)
                except Exception as e:  # noqa: BLE001 — transient listing errors retry
                    warnings.warn(
                        f"gdrive listing failed ({e!r}); retrying in "
                        f"{poll_interval}s",
                        stacklevel=2,
                    )
                    _time.sleep(poll_interval)
                    continue
                files = _filter_files(
                    list(tree.values()), object_size_limit, file_name_pattern
                )
                live = {f["id"]: f for f in files}
                assert self._node is not None
                if mode == "streaming":
                    for fid in list(self._cache):
                        if fid not in live:  # deletion detection
                            del self._cache[fid]
                            self._node.push(self._key(fid), None, -1)
                for fid, meta in live.items():
                    prev_mtime = self._cache.get(fid)
                    mtime = meta.get("modifiedTime", "")
                    if prev_mtime is not None and prev_mtime >= mtime:
                        continue  # object cache hit: not re-downloaded
                    payload = client.download(meta)
                    if payload is None:
                        continue
                    values = (
                        (payload, self._meta_of(meta))
                        if with_metadata
                        else (payload,)
                    )
                    self._cache[fid] = mtime
                    self._node.push(self._key(fid), values, 1)
                if mode == "static":
                    return
                _time.sleep(poll_interval)

        def on_stop(self) -> None:
            self._stop = True

    return py_read(
        _GDriveSubject(), schema=schema, name=name or f"gdrive:{object_id}"
    )
