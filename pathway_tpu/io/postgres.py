"""PostgreSQL writer (reference: ``PsqlWriter`` ``src/connectors/data_storage.rs:1326``
+ ``PsqlUpdatesFormatter``/``PsqlSnapshotFormatter`` ``data_format.rs:1733,1826``).

``write``: every diff appends an INSERT carrying time/diff columns (updates mode)
— append-only by construction, so retractions are rejected with a pointer to
``write_snapshot``. ``write_snapshot``: maintains one live row per primary key
via diff-aware UPSERT/DELETE — the snapshot mode; with
``delivery="exactly_once"`` the statements route through the delivery ledger
and land one transaction per epoch guarded by the ``pathway_delivery`` commit
table. Requires ``psycopg2`` (not in this image; import-gated) or a DBAPI
connection injected via ``connection=`` / ``connection_factory=`` in the
settings dict (e.g. the in-process :class:`~pathway_tpu.io._pg_fake.FakePostgres`)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.io._pg_fake import FakePostgres, FakePostgresError  # noqa: F401


def _connect(settings: dict):
    # DI hooks: a pre-built DBAPI connection, or a zero-arg factory producing
    # one (the factory form survives fork/exec — how the exactly-once tests
    # exercise the write paths on this driverless image)
    if "connection" in settings:
        return settings["connection"]
    if "connection_factory" in settings:
        return settings["connection_factory"]()
    try:
        import psycopg2  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "pw.io.postgres requires psycopg2 (or a pre-built connection= / "
            "connection_factory= in the settings dict), which is not "
            "available in this environment"
        ) from None
    import psycopg2

    return psycopg2.connect(**settings)


def _register_writer(table: Table, on_batch, name: str, writer=None) -> None:
    cols = table.column_names()

    def _node():
        if writer is None:
            return ops.CallbackOutputNode(cols, on_batch)
        node = ops.CallbackOutputNode(
            cols,
            on_batch,
            sink_state=writer.sink_state,
            restore_sink=writer.restore_sink,
        )
        node.delivery_writer = writer
        return node

    LogicalNode(_node, [table._node], name=name)._register_as_output()


def write(table: Table, postgres_settings: dict, table_name: str, **kwargs: Any) -> None:
    con = _connect(postgres_settings)
    cols = table.column_names()
    placeholders = ", ".join(["%s"] * (len(cols) + 2))
    stmt = (
        f"INSERT INTO {table_name} ({', '.join(cols)}, time, diff) "  # noqa: S608
        f"VALUES ({placeholders})"
    )

    def on_batch(batch, columns) -> None:
        with con.cursor() as cur:
            for _key, diff, row in batch.rows():
                if diff < 0:
                    raise RuntimeError(
                        f"pw.io.postgres.write({table_name!r}): retraction for "
                        f"row {tuple(row)!r} in plain-append mode — appended "
                        "INSERTs cannot express a deletion; use "
                        "pw.io.postgres.write_snapshot(primary_key=[...]) for "
                        "diff-aware UPSERT/DELETE output"
                    )
                cur.execute(stmt, tuple(row) + (batch.time, diff))
        con.commit()

    _register_writer(table, on_batch, f"postgres_write:{table_name}")


def _snapshot_sql(table_name: str, cols: list[str], pk: list[str]):
    """The diff-aware statement pair: PK-conflict UPSERT for ``diff > 0``,
    PK-match DELETE for ``diff < 0`` (shared by the direct writer and the
    delivery transport). Returns ``(upsert, delete, pk_idx)``."""
    non_pk = [c for c in cols if c not in pk]
    placeholders = ", ".join(["%s"] * len(cols))
    updates = ", ".join(f"{c} = EXCLUDED.{c}" for c in non_pk) or f"{pk[0]} = EXCLUDED.{pk[0]}"
    upsert = (
        f"INSERT INTO {table_name} ({', '.join(cols)}) VALUES ({placeholders}) "  # noqa: S608
        f"ON CONFLICT ({', '.join(pk)}) DO UPDATE SET {updates}"
    )
    delete = (
        f"DELETE FROM {table_name} WHERE "  # noqa: S608
        + " AND ".join(f"{c} = %s" for c in pk)
    )
    pk_idx = [cols.index(c) for c in pk]
    return upsert, delete, pk_idx


def _net_snapshot_ops(batch, pk_idx: list[int]):
    """Net one output batch per primary key (reference
    ``PsqlSnapshotFormatter``): an update arrives as retract(old)+insert(new)
    for the SAME pk within one consolidated tick, and replaying those in raw
    batch order would let a pk-only DELETE land after the UPSERT and wipe the
    live row. Per pk, an insertion anywhere in the batch wins (UPSERT with the
    newest values); a pk seeing only retractions is a genuine DELETE."""
    live: dict[tuple, tuple] = {}
    dead: dict[tuple, None] = {}
    for _key, diff, row in batch.rows():
        pkv = tuple(row[i] for i in pk_idx)
        if diff > 0:
            live[pkv] = tuple(row)
            dead.pop(pkv, None)
        elif pkv not in live:
            dead[pkv] = None
    for pkv in dead:
        yield "d", pkv
    for row in live.values():
        yield "u", row


def write_snapshot(
    table: Table,
    postgres_settings: dict,
    table_name: str,
    primary_key: list[str],
    *,
    delivery: str | None = None,
    **kwargs: Any,
) -> None:
    cols = table.column_names()
    pk = list(primary_key)
    upsert, delete, pk_idx = _snapshot_sql(table_name, cols, pk)

    from pathway_tpu import delivery as _delivery

    if _delivery.resolve_mode(delivery) == "exactly_once":
        # exactly-once: UPSERT/DELETE records stage in the durable ledger and
        # land as one transaction per epoch; the pathway_delivery commit table
        # makes a crash-window re-publish a no-op (delivery/transports.py)
        transport = _delivery.PostgresDeliveryTransport(
            postgres_settings, {"u": upsert, "d": delete}
        )
        writer = _delivery.LedgerWriter(f"postgres.{table_name}", transport)

        def on_batch_ledger(batch, columns) -> None:
            for op, args in _net_snapshot_ops(batch, pk_idx):
                writer.append(0, (op, args))

        _register_writer(
            table,
            on_batch_ledger,
            f"postgres_snapshot:{table_name}",
            writer=writer,
        )
        return

    con = _connect(postgres_settings)

    def on_batch(batch, columns) -> None:
        with con.cursor() as cur:
            for op, args in _net_snapshot_ops(batch, pk_idx):
                cur.execute(upsert if op == "u" else delete, args)
        con.commit()

    _register_writer(table, on_batch, f"postgres_snapshot:{table_name}")
