"""PostgreSQL writer (reference: ``PsqlWriter`` ``src/connectors/data_storage.rs:1326``
+ ``PsqlUpdatesFormatter``/``PsqlSnapshotFormatter`` ``data_format.rs:1733,1826``).

``write``: every diff appends an INSERT carrying time/diff columns (updates mode).
``write_snapshot``: maintains one live row per primary key via upsert/delete — the
diff-aware snapshot mode. Requires ``psycopg2`` (not in this image; import-gated)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table


def _connect(settings: dict):
    # DI hook: a pre-built DBAPI connection (how CI exercises the write paths
    # on this driverless image — tests/test_gated_connectors.py)
    if "connection" in settings:
        return settings["connection"]
    try:
        import psycopg2  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "pw.io.postgres requires psycopg2 (or a pre-built connection= in "
            "the settings dict), which is not available in this environment"
        ) from None
    import psycopg2

    return psycopg2.connect(**settings)


def _register_writer(table: Table, on_batch, name: str) -> None:
    cols = table.column_names()
    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=name,
    )._register_as_output()


def write(table: Table, postgres_settings: dict, table_name: str, **kwargs: Any) -> None:
    con = _connect(postgres_settings)
    cols = table.column_names()
    placeholders = ", ".join(["%s"] * (len(cols) + 2))
    stmt = (
        f"INSERT INTO {table_name} ({', '.join(cols)}, time, diff) "  # noqa: S608
        f"VALUES ({placeholders})"
    )

    def on_batch(batch, columns) -> None:
        with con.cursor() as cur:
            for _key, diff, row in batch.rows():
                cur.execute(stmt, tuple(row) + (batch.time, diff))
        con.commit()

    _register_writer(table, on_batch, f"postgres_write:{table_name}")


def write_snapshot(
    table: Table, postgres_settings: dict, table_name: str, primary_key: list[str], **kwargs: Any
) -> None:
    con = _connect(postgres_settings)
    cols = table.column_names()
    pk = list(primary_key)
    non_pk = [c for c in cols if c not in pk]
    placeholders = ", ".join(["%s"] * len(cols))
    updates = ", ".join(f"{c} = EXCLUDED.{c}" for c in non_pk) or f"{pk[0]} = EXCLUDED.{pk[0]}"
    upsert = (
        f"INSERT INTO {table_name} ({', '.join(cols)}) VALUES ({placeholders}) "  # noqa: S608
        f"ON CONFLICT ({', '.join(pk)}) DO UPDATE SET {updates}"
    )
    delete = (
        f"DELETE FROM {table_name} WHERE "  # noqa: S608
        + " AND ".join(f"{c} = %s" for c in pk)
    )
    pk_idx = [cols.index(c) for c in pk]

    def on_batch(batch, columns) -> None:
        with con.cursor() as cur:
            for _key, diff, row in batch.rows():
                if diff > 0:
                    cur.execute(upsert, tuple(row))
                else:
                    cur.execute(delete, tuple(row[i] for i in pk_idx))
        con.commit()

    _register_writer(table, on_batch, f"postgres_snapshot:{table_name}")
