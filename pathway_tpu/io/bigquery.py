"""BigQuery writer (reference: ``python/pathway/io/bigquery``). Streams output
diffs into a BigQuery table via the insert-rows API, carrying time/diff columns."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import _plain


def write(
    table: Table,
    dataset_name: str,
    table_name: str,
    service_user_credentials_file: str | None = None,
    **kwargs: Any,
) -> None:
    try:
        from google.cloud import bigquery
    except ImportError:
        raise NotImplementedError(
            "pw.io.bigquery requires google-cloud-bigquery"
        ) from None

    if service_user_credentials_file is not None:
        client = bigquery.Client.from_service_account_json(service_user_credentials_file)
    else:
        client = bigquery.Client()
    ref = f"{dataset_name}.{table_name}"
    cols = table.column_names()

    def on_batch(batch, columns) -> None:
        rows = []
        for _key, diff, row in batch.rows():
            rec = {c: _plain(v) for c, v in zip(columns, row)}
            rec["time"] = batch.time
            rec["diff"] = diff
            rows.append(rec)
        if rows:
            errors = client.insert_rows_json(ref, rows)
            if errors:
                raise RuntimeError(f"bigquery insert failed: {errors}")

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=f"bigquery_write:{ref}",
    )._register_as_output()
